"""Preemption: evict lower-priority pods to make room for a pending pod.

Reference: /root/reference/pkg/scheduler/core/generic_scheduler.go
(Preempt :270, selectNodesForPreemption :850, selectVictimsOnNode :940,
filterPodsWithPDBViolation :884, pickOneNodeForPreemption :721,
nodesWherePreemptionMightHelp :1033, podEligibleToPreemptOthers :1054)
and pkg/scheduler/scheduler.go:392 (sched.preempt host-side actions), with
MoreImportantPod/GetPodStartTime from pkg/scheduler/util/utils.go:38-83.

The TPU-vectorized victim search (sorted victim prefix + re-mask check per
candidate node) plugs in at ``select_victims_on_node``; this host
implementation is the parity oracle.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.selectors import labels_match_selector
from kubernetes_tpu.api.types import Pod, PodDisruptionBudget
from kubernetes_tpu.cache.node_info import NodeInfo, pod_host_ports
from kubernetes_tpu.framework.interface import (
    CycleState,
    FitError,
    StatusCode,
)
from kubernetes_tpu.utils import metrics

logger = logging.getLogger(__name__)

_MAX_INT32 = (1 << 31) - 1


def pod_start_time(pod: Pod) -> float:
    """utils.go:38 GetPodStartTime: assumed/bound-but-unstarted pods count
    as 'now'."""
    if pod.status.start_time is not None:
        return pod.status.start_time
    return time.time()


def more_important_pod(p1: Pod, p2: Pod) -> bool:
    """utils.go:76: higher priority, then earlier start time."""
    if p1.spec.priority != p2.spec.priority:
        return p1.spec.priority > p2.spec.priority
    return pod_start_time(p1) < pod_start_time(p2)


def filter_pods_with_pdb_violation(
    pods: List[Pod], pdbs: List[PodDisruptionBudget]
) -> Tuple[List[Pod], List[Pod]]:
    """generic_scheduler.go:884: greedily spend each PDB's
    DisruptionsAllowed budget; pods beyond it are 'violating'."""
    allowed = [pdb.status.disruptions_allowed for pdb in pdbs]
    violating: List[Pod] = []
    non_violating: List[Pod] = []
    for pod in pods:
        violated = False
        if pod.metadata.labels:
            for i, pdb in enumerate(pdbs):
                if pdb.metadata.namespace != pod.metadata.namespace:
                    continue
                if pdb.selector is None:
                    continue  # nil selector matches nothing
                if not labels_match_selector(pod.metadata.labels, pdb.selector):
                    continue
                if allowed[i] <= 0:
                    violated = True
                    break
                allowed[i] -= 1
        (violating if violated else non_violating).append(pod)
    return violating, non_violating


class Victims:
    __slots__ = ("pods", "num_pdb_violations")

    def __init__(self, pods: List[Pod], num_pdb_violations: int) -> None:
        self.pods = pods
        self.num_pdb_violations = num_pdb_violations


def pick_one_node_for_preemption(
    nodes_to_victims: Dict[str, Victims]
) -> Optional[str]:
    """generic_scheduler.go:721: 6-rule lexicographic choice."""
    if not nodes_to_victims:
        return None
    for name, victims in nodes_to_victims.items():
        if not victims.pods:
            return name  # free lunch: no preemption needed

    candidates = list(nodes_to_victims)
    # 1. fewest PDB violations
    min_v = min(nodes_to_victims[n].num_pdb_violations for n in candidates)
    candidates = [
        n for n in candidates if nodes_to_victims[n].num_pdb_violations == min_v
    ]
    if len(candidates) == 1:
        return candidates[0]
    # 2. lowest highest-victim priority (victims sorted important-first)
    min_hp = min(nodes_to_victims[n].pods[0].spec.priority for n in candidates)
    candidates = [
        n for n in candidates
        if nodes_to_victims[n].pods[0].spec.priority == min_hp
    ]
    if len(candidates) == 1:
        return candidates[0]
    # 3. smallest priority sum (offset keeps negatives comparable)
    def prio_sum(n: str) -> int:
        return sum(
            p.spec.priority + _MAX_INT32 + 1 for p in nodes_to_victims[n].pods
        )

    min_sum = min(prio_sum(n) for n in candidates)
    candidates = [n for n in candidates if prio_sum(n) == min_sum]
    if len(candidates) == 1:
        return candidates[0]
    # 4. fewest victims
    min_pods = min(len(nodes_to_victims[n].pods) for n in candidates)
    candidates = [
        n for n in candidates if len(nodes_to_victims[n].pods) == min_pods
    ]
    if len(candidates) == 1:
        return candidates[0]
    # 5. latest earliest-start-time among highest-priority victims
    def earliest_start(n: str) -> float:
        # victims are ordered PDB-violating-first, so pods[0] need not be
        # the highest priority; scan all (GetEarliestPodStartTime).
        pods = nodes_to_victims[n].pods
        max_prio = max(p.spec.priority for p in pods)
        return min(
            pod_start_time(p) for p in pods if p.spec.priority == max_prio
        )

    return max(candidates, key=earliest_start)


class Preemptor:
    """Wires the preemption algorithm to the API side effects
    (scheduler.go:392 preempt + podPreemptor)."""

    #: filter plugins whose semantics the device victim search models
    #: exactly for a plain (solver_supported) preemptor: resource fit +
    #: the static label mask, plus plugins that are no-ops for pods
    #: without the matching spec fields (ports/volumes/spread/affinity)
    DEVICE_MODELED_FILTERS = frozenset({
        "NodeUnschedulable", "NodeResourcesFit", "NodeName", "NodePorts",
        "NodeAffinity", "VolumeRestrictions", "TaintToleration",
        "EBSLimits", "GCEPDLimits", "AzureDiskLimits",
        "NodeVolumeLimitsCSI", "VolumeBinding", "VolumeZone",
        "PodTopologySpread", "InterPodAffinity",
        # no-op for pods without the numa opt-in annotation, and
        # annotated pods are rejected by solver_supported above
        "NodeResourcesNumaAligned",
    })

    def __init__(self, algorithm, queue, client) -> None:
        self.algorithm = algorithm  # GenericScheduler (snapshot + filters)
        self.queue = queue
        self.client = client
        # device victim-search state (stage-7): tensors cached per
        # snapshot generation so a burst of failed pods packs once
        from kubernetes_tpu.tensors import NodeTensorCache

        self._tensor_cache = NodeTensorCache()
        self._pack = None
        self._pack_key = None
        self._pack_cv = threading.Condition()
        self._nt_lock = threading.Lock()  # dims/topology interner guard
        self._prewarm_busy = False
        self._last_adims = None
        self.device_preemptions = 0
        self.host_preemptions = 0

    # -- eligibility --------------------------------------------------------

    def pod_eligible_to_preempt_others(self, pod: Pod) -> bool:
        """generic_scheduler.go:1054."""
        if pod.spec.preemption_policy == "Never":
            return False
        nom = pod.status.nominated_node_name
        if nom:
            ni = self.algorithm.snapshot.get_node_info(nom)
            if ni is not None:
                for p in ni.pods:
                    if (
                        p.metadata.deletion_timestamp is not None
                        and p.spec.priority < pod.spec.priority
                    ):
                        return False  # a previous victim is still terminating
        return True

    # -- core algorithm -----------------------------------------------------

    def nodes_where_preemption_might_help(
        self, fit_err: FitError
    ) -> List[NodeInfo]:
        """generic_scheduler.go:1033: skip UnschedulableAndUnresolvable."""
        out = []
        for ni in self.algorithm.snapshot.list_node_infos():
            status = fit_err.filtered_nodes_statuses.get(ni.node_name)
            if (
                status is not None
                and status.code == StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE
            ):
                continue
            out.append(ni)
        return out

    def select_victims_on_node(
        self,
        prof,
        state: CycleState,
        pod: Pod,
        node_info: NodeInfo,
        pdbs: List[PodDisruptionBudget],
    ) -> Tuple[List[Pod], int, bool]:
        """generic_scheduler.go:940 on cloned state/nodeinfo."""
        node_info = node_info.clone()
        state = state.clone()

        def remove_pod(p: Pod) -> None:
            node_info.remove_pod(p)
            prof.run_pre_filter_extension_remove_pod(state, pod, p, node_info)

        def add_pod(p: Pod) -> None:
            node_info.add_pod(p)
            prof.run_pre_filter_extension_add_pod(state, pod, p, node_info)

        potential: List[Pod] = []
        for p in list(node_info.pods):
            if p.spec.priority < pod.spec.priority:
                potential.append(p)
                remove_pod(p)
        fits, _ = self.algorithm.pod_passes_filters_on_node(
            prof, state, pod, node_info
        )
        if not fits:
            return [], 0, False

        potential.sort(
            key=lambda p: (-p.spec.priority, pod_start_time(p))
        )  # MoreImportantPod order
        violating, non_violating = filter_pods_with_pdb_violation(
            potential, pdbs
        )
        victims: List[Pod] = []
        num_violating = 0

        def reprieve(p: Pod) -> bool:
            add_pod(p)
            fits, _ = self.algorithm.pod_passes_filters_on_node(
                prof, state, pod, node_info
            )
            if not fits:
                remove_pod(p)
                victims.append(p)
            return fits

        for p in violating:
            if not reprieve(p):
                num_violating += 1
        for p in non_violating:
            reprieve(p)
        return victims, num_violating, True

    def device_eligible(self, prof, pod: Pod, cluster_anti=None) -> bool:
        """True when the device victim search is exact for this pod:
        plain pod (solver_supported), no gang semantics, no extenders,
        no custom filter plugins, and no existing-pod required
        anti-affinity (whose removal the device fit model can't see).
        ``cluster_anti`` may carry a precomputed
        cluster_has_required_anti_affinity answer (the batch path checks
        eligibility for hundreds of pods against one snapshot)."""
        from kubernetes_tpu.api.types import POD_GROUP_LABEL
        from kubernetes_tpu.ops.affinity import (
            cluster_has_required_anti_affinity,
        )
        from kubernetes_tpu.scheduler.batch import solver_supported

        if not solver_supported(pod):
            return False
        if any(v.pvc_claim_name for v in pod.spec.volumes):
            # bound-simple-PV pods are solver-safe for PLACEMENT, but
            # the victim search keeps them on the host oracle: volume
            # state can change between the wave and the retry, and the
            # exact oracle re-resolves claims per node
            return False
        # solver_supported admits required pod (anti-)affinity and hard
        # spread (the batch solver models them via count tensors); the
        # victim search does NOT -- a preemptor carrying either must take
        # the host oracle or it would evict victims for a node its
        # constraint still rejects
        if pod.spec.topology_spread_constraints:
            return False
        # host-port preemptors too: static_mask_compact bakes existing
        # port conflicts into the candidate mask, so a node whose only
        # remedy is evicting the current port holder is never searched.
        # The reference re-runs NodePorts with victims removed
        # (generic_scheduler.go:940); the host oracle does the same here.
        if pod_host_ports(pod):
            return False
        a = pod.spec.affinity
        if a is not None and (
            a.pod_affinity is not None or a.pod_anti_affinity is not None
        ):
            return False
        if pod.metadata.labels.get(POD_GROUP_LABEL):
            return False
        if getattr(self.algorithm, "extenders", []):
            return False
        filters = set(prof.list_plugins().get("filter", []))
        if not filters <= self.DEVICE_MODELED_FILTERS:
            return False
        if cluster_anti is None:
            cluster_anti = cluster_has_required_anti_affinity(
                self.algorithm.snapshot
            )
        if cluster_anti:
            return False
        return True

    def _device_answers(
        self, pods: List[Pod], potentials, pdbs
    ) -> List[Tuple[str, List[Pod], int]]:
        """Stage-7 device victim search (ops/preemption.py) for a group
        of failed pods in priority-desc order, ONE device round trip: the
        kernel's pod scan carries each nomination so later pods see
        earlier ones (addNominatedPods semantics). Returns one
        (node_name, victims, num_violating) per pod ("" = no candidate).

        ``potentials``: per-pod iterable of candidate NodeInfos (already
        pruned of UnschedulableAndUnresolvable nodes)."""
        import numpy as np

        from kubernetes_tpu.ops.host_masks import static_mask_compact
        from kubernetes_tpu.ops.preemption import (
            pack_preemption_state,
            preempt_batch_device,
            victims_for_node,
        )
        from kubernetes_tpu.tensors import pack_pod_batch

        snapshot = self.algorithm.snapshot
        # the interners inside dims/topology are check-then-insert; the
        # prewarm thread updates a sibling cache sharing them
        with self._nt_lock:
            nt = self._tensor_cache.update(snapshot)
        key = self._pack_cache_key(snapshot, pdbs)
        from kubernetes_tpu.utils import timeline as _tl
        with _tl.span("pack_wait"), self._pack_cv:
            # a prewarm in flight is about to deliver this exact pack:
            # wait for it instead of duplicating ~0.3s of packing work
            deadline = time.monotonic() + 2.0
            while (
                self._prewarm_busy
                and self._pack_key != key
                and time.monotonic() < deadline
            ):
                self._pack_cv.wait(0.05)
            pack = self._pack if self._pack_key == key else None
        if pack is None:
            with _tl.span("pack_build"):
                pack = pack_preemption_state(snapshot, nt, pdbs)
            with self._pack_cv:
                self._pack = pack
                self._pack_key = key
        n = len(pack.node_names)
        b = len(pods)

        batch = pack_pod_batch(pods, nt.dims)
        mask_rows, mask_index = static_mask_compact(pods, snapshot, nt)
        nt_rows = np.array(
            [nt.row(name) for name in pack.node_names], dtype=np.int64
        )
        # candidate masks arrive PRE-DEDUPLICATED: the dedup key is
        # (static-mask row, potential-list identity) -- both known per
        # pod -- so a wave of identical pods shares one [N] row and the
        # kernel never sees (nor np.unique's) a [B, N] matrix (measured
        # ~1.1s at 1000x5000, half the wave)
        pot_rows: Dict[int, np.ndarray] = {}
        cand_cache: Dict[Tuple[int, int], int] = {}
        content_cache: Dict[bytes, int] = {}
        cand_rows: List[np.ndarray] = []
        cand_index = np.zeros(b, dtype=np.int32)
        zero_row: Optional[int] = None
        for k, pod in enumerate(pods):
            if batch.unsatisfiable[k]:
                # no pod removal adds a resource dimension
                if zero_row is None:
                    zero_row = len(cand_rows)
                    cand_rows.append(np.zeros(n, dtype=bool))
                cand_index[k] = zero_row
                continue
            key = (int(mask_index[k]), id(potentials[k]))
            u = cand_cache.get(key)
            if u is None:
                pot_key = id(potentials[k])
                pot_row = pot_rows.get(pot_key)
                if pot_row is None:
                    pot_row = np.zeros(n, dtype=bool)
                    idxs = [
                        pack.node_index.get(ni.node_name)
                        for ni in potentials[k]
                    ]
                    pot_row[[i for i in idxs if i is not None]] = True
                    pot_rows[pot_key] = pot_row
                row = mask_rows[mask_index[k]][nt_rows] & pot_row
                # CONTENT-level dedup on top of the identity key: a
                # deferred wave combines failures from several batches
                # whose statuses/potential objects differ by identity
                # but not content; without this the distinct-row count
                # crosses its pad bucket and forks a multi-second
                # kernel recompile mid-burst
                ckey = row.tobytes()
                u = content_cache.get(ckey)
                if u is None:
                    u = len(cand_rows)
                    cand_rows.append(row)
                    content_cache[ckey] = u
                cand_cache[key] = u
            cand_index[k] = u

        # pre-existing nominations (in-scan ones ride the kernel carry)
        pod_uids = {p.metadata.uid for p in pods}
        nom_pods, nom_prio, nom_node = [], [], []
        for node_name, noms in (
            self.queue.all_nominated_pods_by_node() if self.queue else {}
        ).items():
            i = pack.node_index.get(node_name)
            if i is None:
                continue
            for p in noms:
                if p.metadata.uid in pod_uids:
                    continue
                nom_pods.append(p)
                nom_prio.append(p.spec.priority)
                nom_node.append(i)
        if nom_pods:
            nom_req = pack_pod_batch(nom_pods, nt.dims).requests
        else:
            nom_req = np.zeros((0, nt.dims.num_dims), dtype=np.int32)

        _span = _tl.span("preempt_device")
        _span.__enter__()
        chosen, victims, viol, nviol = preempt_batch_device(
            pack,
            batch.requests,
            np.clip(
                [p.spec.priority for p in pods], -(1 << 31), (1 << 31) - 2
            ).astype(np.int32),
            None,
            nom_req,
            np.array(nom_prio, dtype=np.int32),
            np.array(nom_node, dtype=np.int32),
            cand_dedup=(np.stack(cand_rows), cand_index),
        )
        _span.__exit__(None, None, None)
        if getattr(pack, "last_adims", None) is not None:
            self._last_adims = pack.last_adims
        out = []
        for k in range(b):
            idx = int(chosen[k])
            if idx < 0:
                out.append(("", [], 0))
                continue
            out.append(
                (
                    pack.node_names[idx],
                    victims_for_node(pack, idx, victims[k], viol[k]),
                    int(nviol[k]),
                )
            )
        return out

    def _pack_cache_key(self, snapshot, pdbs):
        return (
            snapshot.generation,
            tuple(
                (
                    pdb.metadata.namespace, pdb.metadata.name,
                    pdb.metadata.resource_version,
                    pdb.status.disruptions_allowed,
                )
                for pdb in pdbs
            ),
        )

    def prewarm_pack_async(self, adims=None) -> None:
        """Speculatively build + upload the victim-search pack for the
        CURRENT snapshot on a helper thread. The BatchScheduler calls
        this when a dispatched batch's demand exceeds the cluster's free
        capacity -- preemption is then likely, and the ~0.25s host pack
        plus the ~5MB device upload overlap the failing solve instead of
        serializing into the wave."""
        with self._pack_cv:
            if self._prewarm_busy:
                return
            self._prewarm_busy = True
            if adims is None:
                adims = self._last_adims

        def run() -> None:
            try:
                snapshot = self.algorithm.snapshot
                pdbs = []
                if self.client is not None:
                    try:
                        pdbs, _ = self.client.list_pdbs()
                    except Exception:
                        pass
                key = self._pack_cache_key(snapshot, pdbs)
                with self._pack_cv:
                    if self._pack_key == key:
                        return
                from kubernetes_tpu.ops.preemption import (
                    pack_preemption_state,
                    upload_pack,
                )
                from kubernetes_tpu.tensors import NodeTensorCache

                # own cache INSTANCE (update mutates arrays in place and
                # the committer may be mid-wave on self._tensor_cache)
                # but the SHARED dims/topology schema: a fresh
                # ResourceDims could order resource columns differently
                # and silently misalign the wave's pod packing against
                # this pack
                with self._nt_lock:
                    nt = NodeTensorCache(
                        dims=self._tensor_cache.dims,
                        topology_encoder=self._tensor_cache.topology,
                    ).update(snapshot)
                pack = pack_preemption_state(snapshot, nt, pdbs)
                if adims is not None and not pdbs and pack.v_max <= 32:
                    # start the slim device upload too (async): the
                    # ~1.6MB transfer rides the link before the wave.
                    # Gated like preempt_batch_device's pallas path --
                    # PDB / v_max>32 waves take the XLA kernel and
                    # would only waste the ~0.3s link transfer
                    upload_pack(pack, tuple(adims))
                with self._pack_cv:
                    installed_gen = (
                        self._pack_key[0]
                        if self._pack_key is not None else -1
                    )
                    if self._pack_key != key and installed_gen <= key[0]:
                        # never clobber a NEWER pack a wave installed
                        # meanwhile; an older installed pack (or none)
                        # is always worth replacing -- a wave blocked
                        # in pack_wait may be waiting for this exact key
                        self._pack = pack
                        self._pack_key = key
            except Exception:
                logger.exception("preemption pack prewarm failed")
            finally:
                with self._pack_cv:
                    self._prewarm_busy = False
                    self._pack_cv.notify_all()

        threading.Thread(
            target=run, name="preempt-prewarm", daemon=True
        ).start()

    def _find_preemption_device(
        self, pod: Pod, potential, pdbs
    ) -> Optional[Tuple[str, List[Pod], int]]:
        """Single-pod wrapper over the batched device search."""
        return self._device_answers([pod], [potential], pdbs)[0]

    def find_preemption(
        self, prof, state: CycleState, pod: Pod, fit_err: FitError
    ) -> Tuple[str, List[Pod], List[Pod]]:
        """generic_scheduler.go:270 Preempt. Returns
        (node_name, victims, nominated_pods_to_clear)."""
        if not self.pod_eligible_to_preempt_others(pod):
            return "", [], []
        potential = self.nodes_where_preemption_might_help(fit_err)
        if not potential:
            return "", [], [pod]  # clear any stale nomination
        pdbs = []
        if self.client is not None:
            try:
                pdbs, _ = self.client.list_pdbs()
            except Exception:
                logger.exception("listing PDBs")
        if self.device_eligible(prof, pod):
            result = self._find_preemption_device(pod, potential, pdbs)
            if result is not None:
                self.device_preemptions += 1
                node_name, victims, _ = result
                if not node_name:
                    return "", [], []
                nominated_to_clear = self._lower_priority_nominated_pods(
                    pod, node_name
                )
                return node_name, victims, nominated_to_clear
        self.host_preemptions += 1
        nodes_to_victims: Dict[str, Victims] = {}
        for ni in potential:
            victims, num_violating, fits = self.select_victims_on_node(
                prof, state, pod, ni, pdbs
            )
            if fits:
                nodes_to_victims[ni.node_name] = Victims(victims, num_violating)
        # extenders supporting preemption narrow the candidates
        # (generic_scheduler.go:328 processPreemptionWithExtenders)
        for extender in getattr(self.algorithm, "extenders", []):
            if not nodes_to_victims:
                break
            if getattr(extender, "supports_preemption", lambda: False)() and \
                    extender.is_interested(pod):
                nodes_to_victims = extender.process_preemption(
                    pod, nodes_to_victims
                )
        node_name = pick_one_node_for_preemption(nodes_to_victims)
        if node_name is None:
            return "", [], []
        nominated_to_clear = self._lower_priority_nominated_pods(pod, node_name)
        return node_name, nodes_to_victims[node_name].pods, nominated_to_clear

    def _lower_priority_nominated_pods(
        self, pod: Pod, node_name: str
    ) -> List[Pod]:
        """generic_scheduler.go:364."""
        if self.queue is None:
            return []
        nominated = self.queue.nominated_pods_for_node(node_name)
        return [p for p in nominated if p.spec.priority < pod.spec.priority]

    # -- batched entry (the BatchScheduler's NO_NODE group) ------------------

    def preempt_batch(
        self, prof, items: List[Tuple[Pod, FitError]]
    ) -> Tuple[List[str], List[str]]:
        """Preemption for a whole failed-pod group (priority-desc order)
        in ONE device round trip, then the per-pod API side effects in
        order. Every pod must already be device_eligible. Returns
        (nominated node per pod, evicted victim uids); "" = no
        nomination for that pod. The victim uids let the caller wait for
        the deletions to propagate into its cache before retrying the
        nominated node name per pod ("" = none)."""
        pods = []
        for pod, _ in items:
            if self.client is not None:
                try:
                    pod = self.client.get_pod(
                        pod.metadata.namespace, pod.metadata.name
                    )
                except KeyError:
                    pod = None
            pods.append(pod)
        pdbs = []
        if self.client is not None:
            try:
                pdbs, _ = self.client.list_pdbs()
            except Exception:
                logger.exception("listing PDBs")
        live: List[int] = []
        live_pods: List[Pod] = []
        potentials = []
        results = [""] * len(items)
        # identical failed pods share one statuses dict (the batch path
        # dedups reason maps per mask row), so a wave computes each
        # potential-node list ONCE instead of O(pods x nodes) times
        pot_cache: Dict[int, List] = {}
        for k, (item, pod) in enumerate(zip(items, pods)):
            if pod is None or pod.spec.node_name:
                # deleted, or a STALE failure record: the pod bound
                # since (its signature would poison the wave's shared
                # candidate row with a single-node mask)
                continue
            if not self.pod_eligible_to_preempt_others(pod):
                continue
            pot_key = id(item[1].filtered_nodes_statuses)
            potential = pot_cache.get(pot_key)
            if potential is None:
                potential = self.nodes_where_preemption_might_help(item[1])
                pot_cache[pot_key] = potential
            if not potential:
                # no node can ever help: clear any stale nomination (the
                # host path's to_clear=[pod] branch)
                metrics.preemption_attempts.inc()
                self._clear_nomination(pod)
                continue
            live.append(k)
            live_pods.append(pod)
            potentials.append(potential)
        if not live_pods:
            return results, []
        answers = self._device_answers(live_pods, potentials, pdbs)
        self.device_preemptions += len(live_pods)
        all_victims = {}
        for k, pod, (node_name, victims, _) in zip(
            live, live_pods, answers
        ):
            metrics.preemption_attempts.inc()
            if node_name:
                metrics.preemption_victims.observe(len(victims))
                if self._apply_preemption(
                    prof, pod, node_name, victims,
                    delete_victims=False, write_status=False,
                ):
                    results[k] = node_name
                    for v in victims:
                        all_victims[v.metadata.uid] = v
        # one eviction transaction for the whole group (victims chosen
        # by several pods dedup by uid; deletion is idempotent)
        if all_victims:
            evicted = True
            if self.client is not None:
                try:
                    self.client.delete_pods_bulk(
                        [
                            (v.metadata.namespace, v.metadata.name)
                            for v in all_victims.values()
                        ]
                    )
                except Exception:
                    # nominations stand (they self-heal on the pods'
                    # retries), but waiting victims must NOT be rejected
                    # for an eviction that never happened
                    logger.exception("bulk victim eviction")
                    evicted = False
            if not evicted:
                # eviction failed: nominations stand but the cluster is
                # unchanged -- callers must requeue WITH backoff (None
                # sentinel), or the nominees hot-loop a full wave +
                # eviction attempt against a persistent API failure
                return results, None
            for v in all_victims.values():
                waiting = prof.get_waiting_pod(v.metadata.uid)
                if waiting is not None:
                    waiting.reject("preemption", "preempted")
            return results, list(all_victims.keys())
        return results, []

    def _clear_nomination(self, pod: Pod) -> None:
        self.queue.delete_nominated_pod_if_exists(pod)
        if self.client is not None and pod.status.nominated_node_name:
            try:
                def clear(q: Pod) -> None:
                    q.status.nominated_node_name = ""

                self.client.update_pod_status(
                    pod.metadata.namespace, pod.metadata.name, clear
                )
            except Exception:
                logger.exception("clearing nominatedNodeName")

    def _apply_preemption(
        self,
        prof,
        pod: Pod,
        node_name: str,
        victims: List[Pod],
        delete_victims: bool = True,
        write_status: bool = True,
    ) -> bool:
        """The API side effects of one successful preemption
        (scheduler.go:392): nominate, delete victims, clear superseded
        lower-priority nominations. Returns False when the nomination
        write failed and was rolled back (no victims were evicted) --
        callers must then report no nomination. ``delete_victims=False``
        lets preempt_batch evict the whole group's victims in one
        transaction afterwards. ``write_status=False`` skips the API
        nominatedNodeName write: the batched path defers it to
        record_scheduling_failure's condition write, which happens
        immediately after the pod is requeued -- the watch ECHO of a
        status write arrives as a pod update, and an update for a pod
        that is in no queue re-adds it to the activeQ
        (scheduling_queue.update), so a write issued while the pod is
        still parked for the wave creates a DUPLICATE scheduling of the
        same pod (phantom demand, cascading over-eviction)."""
        self.queue.update_nominated_pod_for_node(pod, node_name)
        if self.client is not None and write_status:
            try:
                def set_nominated(p: Pod) -> None:
                    p.status.nominated_node_name = node_name

                self.client.update_pod_status(
                    pod.metadata.namespace, pod.metadata.name, set_nominated
                )
            except Exception:
                logger.exception("setting nominatedNodeName")
                self.queue.delete_nominated_pod_if_exists(pod)
                return False
        for victim in victims:
            recorder = getattr(prof, "recorder", None)
            if recorder is not None:
                recorder.eventf(
                    victim, "Normal", "Preempted",
                    f"Preempted by {pod.metadata.namespace}/"
                    f"{pod.metadata.name} on node {node_name}",
                )
            if not delete_victims:
                continue
            if self.client is not None:
                try:
                    self.client.delete_pod(
                        victim.metadata.namespace, victim.metadata.name
                    )
                except KeyError:
                    pass
            waiting = prof.get_waiting_pod(victim.metadata.uid)
            if waiting is not None:
                waiting.reject("preemption", "preempted")
        for p in self._lower_priority_nominated_pods(pod, node_name):
            self.queue.delete_nominated_pod_if_exists(p)
            if self.client is not None and p.status.nominated_node_name:
                try:
                    def clear(q: Pod) -> None:
                        q.status.nominated_node_name = ""

                    self.client.update_pod_status(
                        p.metadata.namespace, p.metadata.name, clear
                    )
                except Exception:
                    logger.exception("clearing nominatedNodeName")
        return True

    # -- host-side actions (scheduler.go:392) --------------------------------

    def preempt(
        self, prof, state: CycleState, pod: Pod, fit_err: FitError
    ) -> str:
        if self.client is not None:
            try:
                pod = self.client.get_pod(
                    pod.metadata.namespace, pod.metadata.name
                )
            except KeyError:
                return ""
        node_name, victims, to_clear = self.find_preemption(
            prof, state, pod, fit_err
        )
        metrics.preemption_attempts.inc()
        if node_name:
            metrics.preemption_victims.observe(len(victims))
            if not self._apply_preemption(prof, pod, node_name, victims):
                return ""  # nomination write failed and was rolled back
            return node_name
        # no candidate: clear any stale nomination of the pod itself
        for p in to_clear:
            self._clear_nomination(p)
        return node_name
