"""MetricsRecorder: plugs the framework runtime into the metric set.

Reference: pkg/scheduler/framework/v1alpha1/metrics_recorder.go (the
reference buffers and flushes asynchronously; host-side observation here
is cheap enough to record inline) and the 10% sampling of
plugin_execution_duration (scheduler.go:57 pluginMetricsSamplePercent).
"""

from __future__ import annotations

import random
from kubernetes_tpu.utils import metrics

PLUGIN_METRICS_SAMPLE_PERCENT = 10  # scheduler.go:57


class MetricsRecorder:
    def __init__(self, rng: random.Random = None) -> None:
        self.rng = rng or random.Random()

    def observe_plugin_duration(
        self, plugin: str, extension_point: str, seconds: float
    ) -> None:
        if self.rng.randrange(100) >= PLUGIN_METRICS_SAMPLE_PERCENT:
            return
        metrics.plugin_execution_duration.observe(
            seconds,
            plugin=plugin,
            extension_point=extension_point,
            status="Success",
        )

    def observe_extension_point(
        self, extension_point: str, seconds: float, status: str = "Success"
    ) -> None:
        metrics.framework_extension_point_duration.observe(
            seconds, extension_point=extension_point, status=status
        )
