"""BatchScheduler: the TPU fast path -- drain the activeQ as a batch and
solve placement on device.

This is the north-star replacement for the reference's serialized
scheduleOne loop (/root/reference/pkg/scheduler/scheduler.go:548): the
activeQ drain becomes the batch (SURVEY.md section 2.1 "TPU equivalent"),
the NodeInfo snapshot becomes an incrementally-updated NodeTensor, the
Filter/Score plugins become the device mask/score matrices + host static
mask, and selectHost becomes the argmax inside the assignment scan.

The scheduling-framework contract stays intact: Reserve, Permit
(gang-scheduling hook), PreBind, Bind and the failure/Unreserve paths run
through the same Framework pipeline per pod (finish_schedule). Pods with
constraints the solver doesn't model yet -- inter-pod (anti-)affinity,
topology spread, host ports -- fall back to the sequential oracle path
(attempt_schedule), exactly like the reference runs unsupported pods
through extenders.
"""

from __future__ import annotations

import logging
import math
from typing import List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.framework.interface import CycleState, FitError, PodInfo
from kubernetes_tpu.ops.assignment import (
    GreedyConfig,
    NO_NODE,
    greedy_assign,
    greedy_assign_spread,
)
from kubernetes_tpu.ops.host_masks import static_mask
from kubernetes_tpu.ops.topology import pack_spread_batch
from kubernetes_tpu.scheduler.generic import SNAPSHOT_STATE_KEY
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.tensors import NodeTensorCache, pack_pod_batch
from kubernetes_tpu.utils import metrics

logger = logging.getLogger(__name__)

POD_BUCKET = 64  # batch padded to a multiple of this to bound re-JITs


def solver_supported(pod: Pod) -> bool:
    """Constraints the device solver models today. Anything else falls
    back to the sequential path (still fully correct, just not batched)."""
    spec = pod.spec
    for c in spec.topology_spread_constraints:
        # hard constraints are solved on device via the group-count scan
        # (ops/topology.py); soft ones shape scoring, which the device
        # scorer set doesn't include yet; combining spread with node
        # selectors changes pair-count eligibility per pod
        if c.when_unsatisfiable != "DoNotSchedule":
            return False
    if spec.topology_spread_constraints and (
        spec.node_selector
        or (
            spec.affinity is not None
            and spec.affinity.node_affinity is not None
        )
    ):
        return False
    a = spec.affinity
    if a is not None and (
        a.pod_affinity is not None or a.pod_anti_affinity is not None
    ):
        return False
    for c in spec.containers:
        for p in c.ports:
            if p.host_port:
                return False
    # volume feasibility (PVC binding, disk conflicts, zone/limit checks)
    # stays host-side
    for v in spec.volumes:
        if (
            v.pvc_claim_name or v.gce_pd_name or v.aws_ebs_volume_id
            or v.iscsi_target or v.rbd_image
        ):
            return False
    return True


_AVOID_PODS_ANNOTATION = "scheduler.alpha.kubernetes.io/preferAvoidPods"


def cluster_solver_compatible(snapshot) -> bool:
    """Cluster-level conditions the device solver can't express yet.

    (1) Existing pods with REQUIRED anti-affinity impose symmetric hard
    constraints on incoming pods that have no affinity of their own
    (interpodaffinity filtering.go:404 satisfiesExistingPodsAntiAffinity);
    the static mask doesn't model them, so their presence forces the
    sequential path. Preferred-only (anti-)affinity on existing pods is a
    score divergence, not a correctness one, and does not disable batching.

    (2) The preferAvoidPods annotation scores at weight 10000 -- a
    near-hard exclusion sequentially -- which the device scorer set
    doesn't include.
    """
    for ni in snapshot.have_pods_with_affinity_list:
        for p in ni.pods_with_affinity:
            a = p.spec.affinity
            if (
                a is not None
                and a.pod_anti_affinity is not None
                and a.pod_anti_affinity.required_during_scheduling
            ):
                return False
    for ni in snapshot.list_node_infos():
        if (
            ni.node is not None
            and _AVOID_PODS_ANNOTATION in ni.node.metadata.annotations
        ):
            return False
    return True


class BatchScheduler(Scheduler):
    def __init__(
        self,
        *args,
        max_batch: int = 256,
        solver_config: GreedyConfig = GreedyConfig(),
        tensor_cache: Optional[NodeTensorCache] = None,
        **kwargs,
    ) -> None:
        super().__init__(*args, **kwargs)
        self.max_batch = max_batch
        self.solver_config = solver_config
        self.tensor_cache = tensor_cache or NodeTensorCache()
        self.batches_solved = 0
        self.pods_solved_on_device = 0
        self.pods_fallback = 0

    # -- one batch ----------------------------------------------------------

    def schedule_batch(self, timeout: Optional[float] = None) -> int:
        """Pop up to max_batch pods, solve device-supported ones in one
        jitted call, route the rest through the sequential path. Returns
        the number of pods processed."""
        batch_infos = self.queue.pop_batch(self.max_batch, timeout=timeout)
        if not batch_infos:
            return 0
        pod_scheduling_cycle = self.queue.scheduling_cycle

        # Process in activeQ order: a fallback pod must not jump ahead of
        # higher-priority solver pods popped before it, so solver runs are
        # flushed at each fallback boundary (each flush re-snapshots and
        # re-checks cluster compatibility, so fallback capacity claims and
        # newly-placed anti-affinity pods are visible to later solver pods).
        solver_infos: List[PodInfo] = []

        def flush() -> None:
            if solver_infos:
                self._solve_and_commit(solver_infos, pod_scheduling_cycle)
                self.batches_solved += 1
                solver_infos.clear()

        extenders = self.algorithm.extenders
        for pi in batch_infos:
            if self._skip_pod_schedule(pi.pod):
                continue
            if solver_supported(pi.pod) and not any(
                e.is_interested(pi.pod) for e in extenders
            ):
                solver_infos.append(pi)
            else:
                flush()
                self.pods_fallback += 1
                self.attempt_schedule(pi)
        flush()
        return len(batch_infos)

    def _solve_and_commit(
        self, solver_infos: List[PodInfo], pod_scheduling_cycle: int
    ) -> None:
        snapshot = self.algorithm.snapshot
        self.cache.update_snapshot(snapshot)
        if not cluster_solver_compatible(snapshot):
            # a fallback pod placed earlier in this batch (or informer
            # churn) introduced constraints the device can't model yet
            for pi in solver_infos:
                self.pods_fallback += 1
                self.attempt_schedule(pi)
            return
        nt = self.tensor_cache.update(snapshot)
        pods = [pi.pod for pi in solver_infos]
        batch = pack_pod_batch(
            pods, nt.dims, timestamps=[pi.timestamp for pi in solver_infos]
        )
        smask = static_mask(pods, snapshot, nt)
        # pods requesting resources no node advertises are unsatisfiable
        smask[batch.unsatisfiable] = False

        # Nominated-pod overlay: reserve capacity for preemption nominees
        # (the batch analogue of _add_nominated_pods' virtual add,
        # generic_scheduler.go:535). Conservatively reserves for ALL
        # nominees regardless of relative priority.
        node_requested, node_nzr = nt.requested, nt.non_zero_requested
        batch_uids = {pi.pod.metadata.uid for pi in solver_infos}
        copied = False
        for node_name, nominated in self.queue.all_nominated_pods_by_node().items():
            if node_name not in nt.names:
                continue
            j = nt.row(node_name)
            for npod in nominated:
                if npod.metadata.uid in batch_uids:
                    continue
                if not copied:
                    node_requested = node_requested.copy()
                    node_nzr = node_nzr.copy()
                    copied = True
                nbatch = pack_pod_batch([npod], nt.dims)
                node_requested[j] += nbatch.requests[0]
                node_nzr[j] += nbatch.non_zero_requests[0]

        b = batch.size
        padded = POD_BUCKET * math.ceil(b / POD_BUCKET)
        order = batch.order
        req = np.zeros((padded, nt.dims.num_dims), dtype=np.int32)
        nzr = np.zeros((padded, 2), dtype=np.int32)
        sm = np.zeros((padded, nt.capacity), dtype=bool)
        active = np.zeros(padded, dtype=bool)
        req[:b] = batch.requests[order]
        nzr[:b] = batch.non_zero_requests[order]
        sm[:b] = smask[order]
        active[:b] = True

        # hard topology-spread constraints solve on device via the
        # group-count scan (ops/topology.py)
        spread = None
        if any(p.spec.topology_spread_constraints for p in pods):
            ordered_pods = [pods[int(i)] for i in order]
            spread = pack_spread_batch(ordered_pods, snapshot, nt)
            if spread is None:
                # envelope exceeded: host path keeps full correctness
                for pi in solver_infos:
                    self.pods_fallback += 1
                    self.attempt_schedule(pi)
                return

        solve_timer = metrics.SinceTimer(metrics.batch_solve_duration)
        common_args = (
            jnp.asarray(nt.allocatable),
            jnp.asarray(node_requested),
            jnp.asarray(node_nzr),
            jnp.asarray(nt.valid),
            jnp.asarray(req),
            jnp.asarray(nzr),
            jnp.asarray(sm),
            jnp.asarray(active),
        )
        if spread is None:
            assignments, _, _ = greedy_assign(
                *common_args, config=self.solver_config
            )
        else:
            c = spread.pod_groups.shape[1]
            pg = np.full((padded, c), -1, dtype=np.int32)
            ps = np.zeros((padded, c), dtype=np.int32)
            pm = np.zeros((padded, spread.pod_match.shape[1]), dtype=np.int32)
            pg[:b] = spread.pod_groups
            ps[:b] = spread.pod_self
            pm[:b] = spread.pod_match
            sk = np.zeros((padded, c), dtype=np.int32)
            sk[:b] = spread.pod_max_skew
            assignments, _, _, _ = greedy_assign_spread(
                *common_args,
                jnp.asarray(spread.group_counts),
                jnp.asarray(spread.value_valid),
                jnp.asarray(spread.node_value),
                jnp.asarray(pg),
                jnp.asarray(sk),
                jnp.asarray(ps),
                jnp.asarray(pm),
                config=self.solver_config,
            )
        assignments = np.asarray(assignments)
        solve_timer.observe()
        metrics.batch_size.observe(b)

        num_nodes = nt.num_nodes
        for k in range(b):
            pi = solver_infos[int(order[k])]
            choice = int(assignments[k])
            prof = self.profiles.get(pi.pod.spec.scheduler_name)
            if prof is None:
                logger.error("no profile for %s", pi.pod.key())
                continue
            state = CycleState()
            state.write(SNAPSHOT_STATE_KEY, snapshot)
            if choice == NO_NODE:
                metrics.schedule_attempts.inc(result="unschedulable")
                # populate PreFilter state so preemption's victim
                # simulation can run the full filter pipeline (the
                # sequential path gets this from algorithm.schedule)
                prof.run_pre_filter_plugins(state, pi.pod)
                fit_err = FitError(pi.pod, num_nodes, {})
                self.handle_fit_error(
                    prof, state, pi, fit_err, pod_scheduling_cycle
                )
                self.pods_solved_on_device += 1
                continue
            self.finish_schedule(
                prof, state, pi, nt.names[choice], pod_scheduling_cycle
            )
            self.pods_solved_on_device += 1

    # -- loop ---------------------------------------------------------------

    def run(self) -> None:
        self.queue.run()
        while not self._stop.is_set():
            self.schedule_batch(timeout=0.5)
