"""BatchScheduler: the TPU fast path -- drain the activeQ as a batch and
solve placement on device.

This is the north-star replacement for the reference's serialized
scheduleOne loop (/root/reference/pkg/scheduler/scheduler.go:548): the
activeQ drain becomes the batch (SURVEY.md section 2.1 "TPU equivalent"),
the NodeInfo snapshot becomes an incrementally-updated NodeTensor, the
Filter/Score plugins become the device mask/score matrices + host static
mask, and selectHost becomes the argmax inside the assignment scan.

The scheduling-framework contract stays intact: Reserve, Permit
(gang-scheduling hook), PreBind, Bind and the failure/Unreserve paths run
through the same Framework pipeline per pod (finish_schedule). Required
(anti-)affinity, topology spread, the full default score family
(including preferred inter-pod affinity), host ports (static mask for
existing pods + synthetic anti rows for within-batch conflicts), gang
quorum masks, and batched preemption all solve on device; the few
remaining shapes the solver doesn't model (volume-bound pods,
spread+nodeSelector eligibility coupling -- see solver_supported) fall
back to the sequential oracle path (attempt_schedule), exactly like the
reference runs unsupported pods through extenders.
"""

from __future__ import annotations

import collections
import logging
import math
import os
import threading
import time
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from kubernetes_tpu.api.types import Binding, POD_GROUP_LABEL, Pod
from kubernetes_tpu.apiserver.server import Conflict as ApiConflict
from kubernetes_tpu.cache.node_info import pod_host_ports
from kubernetes_tpu.scheduler.admission import (
    Admission,
    classify_pod as _classify_pod,
    solver_unsupported_reason,
)
from kubernetes_tpu.framework.interface import (
    CycleState,
    FitError,
    PodInfo,
    Status,
)
from kubernetes_tpu.ops.assignment import (
    GreedyConfig,
    NO_NODE,
    apply_assignment_delta,
    compress_carry,
    decompress_carry,
    greedy_assign_compact,
    greedy_assign_constrained,
    sinkhorn_assign,
    solve_packed,
)
from kubernetes_tpu.ops.affinity import (
    add_host_port_rows,
    cluster_has_required_anti_affinity,
    noop_affinity_tensors,
    pack_affinity_batch,
    pad_affinity_tensors,
)
from kubernetes_tpu.ops.host_masks import (
    mask_rows_upload,
    static_mask_compact,
)
from kubernetes_tpu.ops.scoring import (
    ScoreEnvelopeExceeded,
    batch_selector_spread_live,
    cluster_has_affinity_scoring,
    noop_score_tensors,
    pack_score_batch,
    pad_score_tensors,
)
from kubernetes_tpu.ops.topology import (
    noop_spread_tensors,
    pack_spread_batch,
    pad_spread_tensors,
)
from kubernetes_tpu.robustness.circuit import SolveTimeout
from kubernetes_tpu.robustness.containment import (
    ContainmentConfig,
    QuarantineManager,
)
from kubernetes_tpu.robustness.faults import (
    FaultPoint,
    PoisonError,
    SchedulerCrashed,
    get_injector,
    pod_is_poisoned,
    poison_stamp_maybe,
)
from kubernetes_tpu.robustness.ladder import (
    LadderExhausted,
    RobustnessConfig,
    SolverLadder,
    TIER_HOST_GREEDY,
    TIER_PALLAS,
    TIER_SEQUENTIAL,
    TIER_XLA,
    host_greedy_assign,
)
from kubernetes_tpu.scheduler.generic import SNAPSHOT_STATE_KEY
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.tensors import NodeTensorCache, pack_pod_batch
from kubernetes_tpu.utils import flightrecorder
from kubernetes_tpu.utils import metrics
from kubernetes_tpu.utils import timeline

try:
    from kubernetes_tpu.native import assume_clones as _assume_clones
    from kubernetes_tpu.native import commit_gather as _commit_gather
except Exception:  # noqa: BLE001 - pure-Python fallback
    _assume_clones = None
    _commit_gather = None


def _commit_gather_py(solver_infos, order, assigns, names):
    """Pure-Python fallback for native commit_gather: gather the placed
    slots' PodInfos, build their assumed clones with spec.node_name set,
    and resolve host names, in one pass (identical semantics to the C
    loop; differentially tested in tests/test_native_commit.py)."""
    pis, clones, hosts = [], [], []
    for oi, ci in zip(order, assigns):
        pi = solver_infos[oi]
        host = names[ci]
        assumed = pi.pod.assumed_clone()
        assumed.spec.node_name = host
        pis.append(pi)
        clones.append(assumed)
        hosts.append(host)
    return pis, clones, hosts


def _mirror_scatter_py(assignments, b, req, nzr, req_shadow, nzr_shadow):
    """Pure-Python twin of native mirror_scatter: compact the batch's
    placed rows and scatter-add them into the shadow expectation.
    Returns (rows [K] int64, req_rows [K, R], nzr_rows [K, 2]) or None
    when nothing placed -- identical semantics to the C loop
    (differentially tested in tests/test_native_mirror.py)."""
    placed = assignments[:b] != NO_NODE
    if not placed.any():
        return None
    rows_placed = assignments[:b][placed].astype(np.int64)
    req_rows = req[:b][placed]
    nzr_rows = nzr[:b][placed]
    np.add.at(req_shadow, rows_placed, req_rows)
    np.add.at(nzr_shadow, rows_placed, nzr_rows)
    return rows_placed, req_rows, nzr_rows


def _mirror_scatter(assignments, b, req, nzr, req_shadow, nzr_shadow):
    """The bind-echo -> shadow-mirror hot loop: one C pass
    (native/_hotpath.c mirror_scatter) over the batch's assignments
    compacts the placed rows AND applies the scatter-add, replacing
    three fancy-index materializations plus two np.add.at passes per
    batch on the committer thread. The C side validates every index
    BEFORE mutating, so a native failure can always fall back to the
    twin without double-applying."""
    from kubernetes_tpu import native as _native

    fn, expected = _native.ingest_fn("mirror_scatter")
    if fn is not None:
        try:
            a = np.ascontiguousarray(assignments[:b], dtype=np.int32)
            req_b = np.ascontiguousarray(req[:b], dtype=np.int32)
            nzr_b = np.ascontiguousarray(nzr[:b], dtype=np.int32)
            rows_out = np.empty(b, dtype=np.int64)
            req_out = np.empty((b, req_b.shape[1]), dtype=np.int32)
            nzr_out = np.empty((b, 2), dtype=np.int32)
            k = fn(
                a, req_b, nzr_b, req_shadow, nzr_shadow,
                rows_out, req_out, nzr_out,
            )
            if k == 0:
                return None
            return rows_out[:k], req_out[:k], nzr_out[:k]
        except Exception:
            logger.exception("native mirror_scatter failed")
            metrics.ingest_native_fallbacks.inc(site="mirror-scatter")
    elif expected:
        metrics.ingest_native_fallbacks.inc(site="mirror-scatter")
    return _mirror_scatter_py(
        assignments, b, req, nzr, req_shadow, nzr_shadow
    )


class _EagerDownload:
    """Device->host result copy started at DISPATCH time on its own
    daemon thread, so the transfer (and the numpy conversion) rides
    concurrently with the next batch's pop/pack instead of serializing
    inside the committer. ``result()`` blocks until the copy lands; the
    committer calls it under the same wall-clock watchdog that guarded
    the old in-committer ``np.asarray`` (a wedged serving link still
    times out and trips the breaker)."""

    __slots__ = ("_done", "_value", "_error")

    def __init__(self, dev) -> None:
        self._done = threading.Event()
        self._value = None
        self._error: Optional[BaseException] = None
        threading.Thread(
            target=self._run, args=(dev,), name="solve-download",
            daemon=True,
        ).start()

    def _run(self, dev) -> None:
        try:
            self._value = np.asarray(dev)
        except BaseException as e:  # noqa: BLE001 - re-raised in result()
            self._error = e
        finally:
            self._done.set()

    def result(self):
        self._done.wait()
        if self._error is not None:
            raise self._error
        return self._value

logger = logging.getLogger(__name__)


class _JitCacheWatch:
    """Runtime jit-cache watchdog: diff the solver families' compiled-
    signature counts after each solve. Every growth books
    ``scheduler_tpu_jit_compiles_total{signature}``; growth after
    ``seal()`` (end of warmup) is a MID-RUN recompile and additionally
    fires a flight-recorder mark + warning -- the production
    generalization of the dryrun's test-only ``mesh_packed_cache_size``
    probe. O(families) dict reads per batch."""

    __slots__ = ("_mesh", "_last", "_sealed")

    def __init__(self, mesh=None) -> None:
        self._mesh = mesh
        self._last: dict = {}
        self._sealed = False

    def seal(self) -> None:
        """Warmup is done: from here, cache growth is unplanned."""
        self.refresh()
        self._sealed = True

    def refresh(self) -> None:
        from kubernetes_tpu.ops.assignment import jit_cache_sizes

        try:
            sizes = jit_cache_sizes(self._mesh)
        except Exception:  # pragma: no cover - probe must never break solves
            return
        for sig, n in sizes.items():
            prev = self._last.get(sig, 0)
            if n > prev:
                metrics.jit_compiles.inc(n - prev, signature=sig)
                if self._sealed:
                    flightrecorder.mark(
                        "jit_recompile", signature=sig, cache_size=n,
                        compiles=n - prev,
                    )
                    logger.warning(
                        "mid-run jit recompile: %s cache grew %d -> %d",
                        sig, prev, n,
                    )
            self._last[sig] = n


POD_BUCKET = 64  # batch padded to a multiple of this to bound re-JITs
#: constrained batches above this node capacity take the sequential host
#: path: the XLA constrained scan's compile at >32k nodes runs for
#: minutes (long enough to trip the serving link's dead-man timer and
#: wedge the device), and the fused kernel's VMEM gate already excludes
#: these shapes. The 50k-node regime is a plain-pod churn workload
#: (BASELINE #5); constrained families at that scale are out of the
#: supported envelope, like the reference's adaptive sampling regime.
CONSTRAINED_NODE_CAP = 32768
MASK_ROW_BUCKET = 8  # dedup static-mask rows padded to a multiple of this
#: solver batches in flight between dispatcher and committer. With the
#: result download riding its own thread from dispatch time
#: (_EagerDownload) extra slots keep the committer fed instead of idling
#: on the serving-link round trip -- but only when the host has cores to
#: run them: on a 2-core box a deeper pipeline steals GIL time from the
#: committer (measured ~10% slower at 4 in flight there), so the depth
#: scales with the host instead of being raised unconditionally.
MAX_INFLIGHT = max(3, min(6, (os.cpu_count() or 4) // 2))
#: eager result downloads need a core to run on; see _eager_download
_EAGER_DOWNLOAD_OK = (os.cpu_count() or 4) >= 4


def solver_supported(pod: Pod) -> bool:
    """Constraints the device solver models today. Anything else falls
    back to the sequential path (still fully correct, just not batched).

    Hard spread solves on device via the group-count scan
    (ops/topology.py), REQUIRED pod (anti-)affinity via the count-tensor
    replay (ops/affinity.py), preferred terms ride the ipa_* score
    family, host ports ride the static mask + synthetic anti rows, and
    attachable-volume COUNT limits ride the ``[N, R]`` volume columns
    (tensors/node_tensor.py) -- so the remaining host-only shapes are
    NUMA-aligned pods, soft spread with node scoping, and direct
    conflict-bearing volume sources. The per-shape reason strings (and
    the lister-dependent volume half of the decision) live in
    scheduler/admission.py, which computes the full classification once
    at informer ingest."""
    return not solver_unsupported_reason(pod)


#: padded row count of the (indices, rows) delta-scatter slot riding the
#: steady-state upload buffer: one fixed bucket keeps the steady solve at
#: ONE jit signature regardless of churn; more than this many changed
#: rows per dispatch escalates to a (counted) full upload
DELTA_ROW_BUCKET = 64
#: per-batch expected-delta ring bound: the host can trail the device by
#: at most the in-flight batches plus the mirror/assume window. Overflow
#: drops the oldest delta, which at worst turns a later handshake into a
#: counted divergence (full upload) -- never a silent mismatch.
_SHADOW_RING_CAP = MAX_INFLIGHT + 2


#: int16 engage ceiling for the compressed carry: resident max + batch
#: load + in-flight load must stay under this (a guard band below 32767
#: absorbs row patches that land between the gate read and the solve)
_CARRY_COMPRESS_CEILING = 24576


def _batch_load16(req, nzr, b) -> int:
    """Worst-case per-column load this batch can add to any node row
    (every pod landing on one node): the range gate's per-dispatch
    term."""
    if not b:
        return 0
    return max(
        int(req[:b].sum(axis=0, dtype=np.int64).max(initial=0)),
        int(nzr[:b].sum(axis=0, dtype=np.int64).max(initial=0)),
    )


def _delta_slot_pieces(
    n_cap, r_dims, fix_rows=None, alloc_rows=None,
    node_requested=None, node_nzr=None, allocatable=None, valid=None,
    compress=False,
):
    """The fixed `DELTA_ROW_BUCKET`-sized (indices, rows) scatter slots
    every steady-state dispatch carries in the single upload buffer.
    Shapes/dtypes/padding here ARE the jit signature the warmup
    precompiles -- the dispatch path and `_maybe_warm` must build them
    through this one helper or they fork a second signature and the
    first production batch pays the compile the warmup was built to
    prevent. Empty slots carry index ``n_cap`` (out of bounds) and drop
    on device.

    ``svalid`` rides with the alloc scatter: membership churn retires /
    claims row slots in place, so the patched rows must also flip the
    device-resident valid mask (a retired slot with alloc zeroed is
    still choosable by a zero-request pod unless valid drops).

    ``compress`` ships the req/nzr delta rows packed int16 (the 'h'
    layout kind) -- only the dispatch gate engages it, and only when
    the row content is provably in range; the index/alloc slots stay
    int32 (allocatable KiB routinely exceeds int16)."""
    row_dt = np.int16 if compress else np.int32
    didx = np.full(DELTA_ROW_BUCKET, n_cap, dtype=np.int32)
    dreq = np.zeros((DELTA_ROW_BUCKET, r_dims), dtype=row_dt)
    dnzr = np.zeros((DELTA_ROW_BUCKET, 2), dtype=row_dt)
    sidx = np.full(DELTA_ROW_BUCKET, n_cap, dtype=np.int32)
    salloc = np.zeros((DELTA_ROW_BUCKET, r_dims), dtype=np.int32)
    svalid = np.zeros(DELTA_ROW_BUCKET, dtype=np.int32)
    if fix_rows is not None and fix_rows.size:
        didx[: fix_rows.size] = fix_rows
        dreq[: fix_rows.size] = node_requested[fix_rows]
        dnzr[: fix_rows.size] = node_nzr[fix_rows]
    if alloc_rows is not None and alloc_rows.size:
        sidx[: alloc_rows.size] = alloc_rows
        salloc[: alloc_rows.size] = allocatable[alloc_rows]
        svalid[: alloc_rows.size] = valid[alloc_rows]
    return [
        ("didx", didx), ("dreq", dreq), ("dnzr", dnzr),
        ("sidx", sidx), ("salloc", salloc), ("svalid", svalid),
    ]


def _audit_checksum_host(arr: np.ndarray) -> Tuple[int, int]:
    """Order-independent wrapping checksum pair (plain sum + row-weighted
    sum, both mod 2^32) of a host array. Must match
    ``_audit_checksum_dev`` bit-for-bit: both sides compute in int32
    with C wrap semantics, and wrapped +/* form a ring, so reduction
    order never matters."""
    a = np.asarray(arr)
    if a.dtype != np.int32:
        a = a.astype(np.int32)
    if a.ndim == 1:
        a = a[:, None]
    w = (np.arange(a.shape[0], dtype=np.int32) + 1)[:, None]
    s = int(a.sum(dtype=np.int32))
    ws = int((a * w).sum(dtype=np.int32))
    return s, ws


def _audit_checksum_dev(arr):
    """Device twin of ``_audit_checksum_host``: two O(N*R) int32
    reductions ON the device -- the cheap per-sweep cost of the carry
    audit; the full [N, R] download happens only on mismatch. Returns
    device scalars (the caller converts once, batching the sync)."""
    a = arr.astype(jnp.int32)
    if a.ndim == 1:
        a = a[:, None]
    w = (jnp.arange(a.shape[0], dtype=jnp.int32) + 1)[:, None]
    return jnp.sum(a, dtype=jnp.int32), jnp.sum(a * w, dtype=jnp.int32)


class _DeviceNodeState:
    """Device-resident node tensors + the generation-handshake
    bookkeeping that validates their reuse.

    Every host->device transfer over the serving link pays a round trip
    (SURVEY.md section 7 "hardest parts (e)"), so the solver keeps node
    state ON DEVICE between batches: the scan already returns the
    post-batch (requested, nzr) on device, and the host mirrors the same
    integer updates into ``req_shadow``/``nzr_shadow`` at commit time.

    Reuse validation is a GENERATION HANDSHAKE, not an array sweep: the
    NodeTensorCache stamps every repacked row with a monotonic epoch, so
    at dispatch only ``rows_changed_since(validated_epoch)`` need a
    content compare against the expectation -- O(changed rows), while the
    old design re-swept the full [N, R] arrays against every shadow
    generation. The committer may trail the dispatcher by several
    batches; ``pending_deltas`` holds each mirrored batch's per-row adds
    so a host state that trails the shadow by a suffix of them still
    validates. Changed rows the expectation does NOT explain (node churn,
    bind failures) are divergences: they are scatter-patched onto the
    resident state as (indices, rows) -- or, with work in flight or too
    many rows, resolved by a counted full upload. Never silently wrong.
    """

    def __init__(self) -> None:
        self.alloc_dev = None
        self.valid_dev = None
        self.req_dev = None
        self.nzr_dev = None
        # -- handshake bookkeeping ---------------------------------------
        # the NodeTensorCache layout epoch the device buffers were built
        # against: row identity is only comparable while it stands
        self.layout_epoch = -1
        # the cache update epoch the shadows were last reconciled to
        self.validated_epoch = -1
        # expected host state: alloc mirrors the packed allocatable
        # (patched row-wise); req/nzr mirror the packed requested state
        # plus every mirrored (committed) batch
        self.alloc_shadow: Optional[np.ndarray] = None
        self.valid_shadow: Optional[np.ndarray] = None
        self.req_shadow: Optional[np.ndarray] = None
        self.nzr_shadow: Optional[np.ndarray] = None
        # per-batch expected row deltas the host pack may not have shown
        # yet: (node_rows [K], req_rows [K, R], nzr_rows [K, 2]), newest
        # last (replaces the retired full-array shadow_gens ring)
        self.pending_deltas: "collections.deque" = collections.deque(
            maxlen=_SHADOW_RING_CAP
        )

    def invalidate_carry(self) -> None:
        self.req_dev = None
        self.nzr_dev = None
        self.req_shadow = None
        self.nzr_shadow = None
        self.pending_deltas.clear()


class BatchScheduler(Scheduler):
    def __init__(
        self,
        *args,
        max_batch: int = 256,
        solver_config: GreedyConfig = GreedyConfig(),
        tensor_cache: Optional[NodeTensorCache] = None,
        batch_window: float = 0.01,
        solver_mode: str = "greedy",
        mesh=None,
        robustness_config: Optional[RobustnessConfig] = None,
        containment_config: Optional[ContainmentConfig] = None,
        **kwargs,
    ) -> None:
        """``solver_mode``: "greedy" replays the sequential argmax exactly
        (parity mode); "sinkhorn" adds the entropic-OT global prior for
        the churn/rebalance regime (ops/sinkhorn.py) on unconstrained
        batches -- constrained batches always use the greedy replay.

        ``mesh``: an optional ``jax.sharding.Mesh`` with a "nodes" axis;
        node-dimension tensors are device_put with node-axis shardings and
        GSPMD partitions the solver scan across the mesh, inserting the
        cross-shard argmax/psum collectives over ICI (SURVEY.md
        section 2.5)."""
        super().__init__(*args, **kwargs)
        self.max_batch = max_batch
        self.solver_config = solver_config
        self.tensor_cache = tensor_cache or NodeTensorCache()
        self.batch_window = batch_window
        # SLO-adaptive batching (streaming/autobatch.py): when a
        # controller is attached it rewrites batch_window AND these two
        # knobs between batches -- dispatch_batch_cap bounds how many
        # pods one pop_batch drains, solve_pad floors the padded solve
        # shape below max_batch so latency-mode batches stop paying the
        # full-pad fixed solve cost. None = static knobs (today's
        # behavior, zero overhead).
        self.autobatch = None
        self.dispatch_batch_cap: Optional[int] = None
        self.solve_pad: Optional[int] = None
        # solve-pad shapes warmup() pre-compiles beyond max_batch
        # (attach_autobatch adds every controller rung)
        self._warmup_pads: set = {max_batch}
        # measured steady-solve seconds per warmed pad (warmup fills
        # this post-compile); feeds AutoBatchController.calibrate so
        # the rung ladder is sized from what each pad actually costs
        self.pad_solve_seconds: dict = {}
        if solver_mode not in ("greedy", "sinkhorn"):
            raise ValueError(f"unknown solver_mode {solver_mode!r}")
        self.solver_mode = solver_mode
        self.mesh = mesh
        # sharded mesh delta path (PR 9): the mesh dispatch rides the
        # same single-buffer + device-resident-carry + delta-scatter
        # machinery as the single-device path, through the sharded twin
        # (ops/assignment.make_mesh_packed_solver) with shard-local row
        # scatters. KTPU_MESH_DELTA=0 restores the PR-5 counted
        # full-upload fallback (the escape hatch the
        # allow_scatter=False seam in _negotiate_device_state serves).
        # Greedy mesh batches additionally solve on the shard_map'd
        # PALLAS tier (PR 10, ops/assignment._mesh_shard_solver):
        # per-shard fused step + one best-of-shards combine per pod,
        # ladder [pallas, xla] with breaker fallback to the GSPMD twin;
        # KTPU_MESH_PALLAS=0 pins the twin-only behavior (predicate:
        # ops/assignment.mesh_pallas_candidate).
        self.mesh_delta = (
            mesh is not None
            and os.environ.get("KTPU_MESH_DELTA", "1") != "0"
        )
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            self._sh_node1 = NamedSharding(mesh, P("nodes"))
            self._sh_node2 = NamedSharding(mesh, P("nodes", None))
            self._sh_rows = NamedSharding(mesh, P(None, "nodes"))
            self._sh_repl = NamedSharding(mesh, P())
        self.batches_solved = 0
        self.pods_solved_on_device = 0
        self.pods_fallback = 0
        # perf-matrix visibility (VERDICT r2: the drain cliff and the
        # envelope fallbacks were unmetered)
        self.envelope_fallbacks = 0  # whole batches sent to host by packers
        self.pipeline_drains = 0  # constrained dispatch drained the pipeline
        self.gang_resolves = 0  # quorum-failure re-solves (_gang_fixup)
        self.nominee_constrained_fallbacks = 0  # nominees + constraints
        self.state_reuses = 0
        self.state_uploads = 0
        # generation-handshake visibility: total changed node rows shipped
        # as (indices, rows) scatters instead of full [N, R] uploads, and
        # handshake mismatches (host state not explained by our own
        # mirrored placements -- node churn, bind failures)
        self.delta_rows_uploaded = 0
        self.carry_divergences = 0
        # membership churn absorbed as in-place slot scatters (node
        # add/remove rows patched onto the resident state without a
        # layout move, an upload, or a divergence)
        self.membership_row_patches = 0
        self._dev = _DeviceNodeState()
        self._shadow_lock = threading.Lock()
        # pipelined batches flow dispatcher -> committer through this
        # bounded FIFO; the committer thread owns download + commit so the
        # dispatcher never blocks on a serving-link round trip
        self._pending_q: "collections.deque" = collections.deque()
        self._pending_cv = threading.Condition()
        self._committer: Optional[threading.Thread] = None
        # failures parked across in-flight batches for one combined
        # preemption wave (touched only by the committing thread: the
        # committer loop, or the dispatcher on the synchronous paths,
        # which drain the pipeline first)
        self._deferred_preempt: List = []
        self._volume_listers = None
        self._deferred_since = 0.0
        self._prewarm_next_commit = False
        self._committer_stop = False
        # -- admission classifier state (scheduler/admission.py) ---------
        # volume-topology generation: bumped by every PV/PVC/StorageClass/
        # CSINode event (eventhandlers), compared against each PVC-bearing
        # pod's cached admission record at pop time
        self._volume_topo_gen = 0
        # memo ownership token: an admission record from another scheduler
        # instance (different extenders / dims registry) is re-classified
        self._admission_token = object()
        self.admissions_classified = 0
        self.reclassifications = 0
        self.volume_reject_retries = 0  # device NO_NODE -> host re-checks
        # the plain-pod fast path (native ingest_stamp / its twin): ONE
        # shared read-only Admission record serves every plain pod, and
        # the native cfg tuple is built once per scheduler
        self._plain_adm: Optional[Admission] = None
        self._ingest_cfg: Optional[tuple] = None
        # per-stage wall-clock accumulators, ALWAYS on (bench.py emits
        # profile_stage_seconds every round; only the per-pod classify
        # timer stays behind profile_stages). Per-THREAD dicts merged at
        # read: the dispatcher (pop/classify/pack/device_solve) and the
        # committer (download/commit) accumulate without sharing a
        # read-modify-write -- the old single dict dropped stage time
        # under pipelining whenever both threads raced the same key
        self.profile_stages = False
        self._stage_lock = threading.Lock()
        self._stage_local = threading.local()
        self._stage_dicts: List[dict] = []
        # flight-recorder spine (utils/flightrecorder.py): the pop-side
        # stage timings of the CURRENT drain, consumed by the first
        # span it dispatches (pop_batch drains before the flush loop
        # splits batches, so the pop cost belongs to the drain's head)
        # (drain-work seconds, arrival-wait seconds, pop-start
        # perf_counter) of the current drain
        self._pop_note: Optional[Tuple[float, float, float]] = None
        # runtime jit-cache watchdog: sealed at the end of warmup();
        # unsealed growth still counts compiles, it just isn't flagged
        # as a mid-run recompile (tests that skip warmup stay quiet)
        self._jit_watch = _JitCacheWatch(mesh)
        # collect-at-idle gc policy, engaged only by the production run
        # loop (tests driving schedule_batch directly keep gc untouched)
        self._gc_guard = None
        # solver degradation ladder (robustness/): per-tier circuit
        # breakers + retry + watchdog around every device interaction,
        # so a sick device path steps down Pallas -> XLA -> host greedy
        # -> sequential oracle and the batch ALWAYS completes
        self.ladder = SolverLadder(robustness_config)
        # bind retries share the ladder's policy + injectable sleep
        self.bind_retry_policy = self.ladder.config.retry
        self._retry_sleep = self.ladder.config.sleep
        # set when the committer failed to join at shutdown (satellite:
        # the silent join(timeout=10) hang) -- surfaced via the
        # scheduler_degraded_health gauge and this flag
        self.commit_degraded = False
        # -- blast-radius containment (robustness/containment.py) --------
        # poison bisection + the quarantine ledger: a ladder-exhausted
        # batch is split O(log B)-wise on the warm pad rungs instead of
        # failing whole to the sequential floor; isolated pods take
        # escalating holds and park with a typed PodQuarantined
        # condition when the strike budget runs out
        self.containment_config = containment_config or ContainmentConfig()
        self.quarantine = QuarantineManager(
            self.queue, self.client, self.containment_config
        )
        # a real spec update releasing a PARKED pod must also clear its
        # apiserver-visible PodQuarantined condition
        self.queue.on_quarantine_release = (
            self.quarantine.clear_condition_async
        )
        self.bisections = 0
        self.pods_quarantined = 0
        # ladder_exhausted crash-loop detector: the uid signature of the
        # last exhausted batch and how many consecutive times it
        # exhausted (>= 2 books exhausted_crashloop and forces the
        # containment path over another identical full-batch retry)
        self._last_exhaust_sig: Optional[frozenset] = None
        self._exhaust_repeats = 0
        # carry integrity audit bookkeeping: the dispatch sequence lets
        # an audit detect that a dispatch/commit raced its checksum
        # window (bumped per dispatch AND per shadow mirror)
        self._dispatch_seq = 0
        self.carry_audits = 0
        self.carry_audit_heals = 0
        # device-loss rebuild: perf_counter at loss detection; cleared
        # (and metered into device_rebuild_ms) when the next jitted
        # solve lands on fully re-uploaded state
        self._device_lost_at: Optional[float] = None
        # -- pipelined speculative dispatch (ISSUE 18) --------------------
        # in-flight depth knob: the bench's serial arm pins 1 so the
        # pipelined/serial comparison runs the same code path
        self.max_inflight = MAX_INFLIGHT
        self.speculative_launches = 0
        self.speculative_rewinds = 0
        # range-gated int16 carry compression (single-device basic
        # solves): engaged per dispatch while every resident column sum
        # provably stays inside the int16 guard band, so the narrowed
        # carry is bit-exact. KTPU_CARRY_COMPRESS=0 pins the int32
        # carry (the A/B knob).
        self.carry_compress_enabled = (
            mesh is None
            and os.environ.get("KTPU_CARRY_COMPRESS", "1") != "0"
        )

    # -- one batch ----------------------------------------------------------

    def schedule_batch(
        self, timeout: Optional[float] = None, pipeline: bool = False
    ) -> int:
        """Pop up to max_batch pods, solve device-supported ones in one
        jitted call, route the rest through the sequential path. Returns
        the number of pods processed.

        With ``pipeline=True`` (the production run loop) a pure-resource
        batch may be left in flight on device: the NEXT call dispatches
        its own solve against the device-resident carry BEFORE downloading
        and committing the previous result, so the serving link's
        round-trip latency is overlapped with host commit work instead of
        serializing with it."""
        ab = self.autobatch
        if ab is not None:
            # one controller decision per interval, taken between
            # batches on the dispatcher thread (deterministic ordering
            # with the drain; the callable window below lets a shrink
            # land mid-wait too)
            ab.maybe_step(self)
        cap = self.dispatch_batch_cap
        size = (
            self.max_batch
            if not cap
            else max(1, min(self.max_batch, cap))
        )
        t_pop = time.perf_counter()
        batch_infos = self.queue.pop_batch(
            size,
            timeout=timeout,
            window=(self._live_window if ab is not None
                    else self.batch_window),
        )
        dt_pop = time.perf_counter() - t_pop
        # split drain WORK from arrival wait: blocking on an empty queue
        # (burst still streaming in, or plain idle) is not hot-path time
        # and would drown the pop_batch share the profile exists to watch
        waited = getattr(self.queue, "last_pop_wait_seconds", 0.0)
        self._stage_add("pop_batch", max(0.0, dt_pop - waited))
        if waited:
            self._stage_add("pop_wait", waited)
        # the first span this drain dispatches claims the pop timings
        self._pop_note = (max(0.0, dt_pop - waited), waited, t_pop)
        guard = self._gc_guard
        if not batch_infos:
            # idle: finish whatever is still in flight
            self._drain_pending()
            if self._deferred_preempt:
                # safety net: a mixed burst whose tail took the fallback
                # path produces no further batch commits to trigger the
                # deferred wave
                self._flush_deferred_preemptions()
            if guard is not None:
                guard.idle()
            return 0
        if guard is not None:
            guard.active()
        pod_scheduling_cycle = self.queue.scheduling_cycle

        # Process in activeQ order: a fallback pod must not jump ahead of
        # higher-priority solver pods popped before it, so solver runs are
        # flushed at each fallback boundary (each flush re-snapshots and
        # re-checks cluster compatibility, so fallback capacity claims and
        # newly-placed anti-affinity pods are visible to later solver pods).
        solver_infos: List[PodInfo] = []

        def flush() -> None:
            if solver_infos:
                if pipeline:
                    self._solve_pipelined(solver_infos, pod_scheduling_cycle)
                else:
                    self._solve_and_commit(solver_infos, pod_scheduling_cycle)
                self.batches_solved += 1
                solver_infos.clear()

        # admission is a precomputed-field read here: the classifier ran
        # at informer ingest (eventhandlers), so the hot loop does one
        # memo get per pod instead of re-walking annotations, volume
        # sources, and NUMA hints per pod per cycle (the round-5
        # regression). Stale volume classifications re-check inside
        # _admission_of.
        profiling = self.profile_stages
        inj = get_injector()
        quota_gate = self.quota
        for pi in batch_infos:
            if self._skip_pod_schedule(pi.pod):
                continue
            if quota_gate is not None and not self._quota_admit(
                pi, pod_scheduling_cycle
            ):
                # parked typed-QuotaExceeded (woken by quota/usage
                # events) or routed to the backoff clock; either way it
                # never enters a batch uncharged
                continue
            if inj is not None:
                # one POISON_POD draw per pod ever (uid-keyed, so the
                # verdict survives informer object replacement): a
                # firing draw stamps the pod and the fault follows it
                # through every later batch
                poison_stamp_maybe(pi.pod)
            if profiling:
                t_cls = time.perf_counter()
                adm = self._admission_of(pi.pod)
                self._stage_add("classify", time.perf_counter() - t_cls)
            else:
                adm = self._admission_of(pi.pod)
            if adm.device_ok:
                # one profile per solver batch: score weights and owner
                # lookups are profile-scoped (the sequential path resolves
                # them per pod, scheduler.go:741)
                if solver_infos and (
                    solver_infos[0].pod.spec.scheduler_name
                    != pi.pod.spec.scheduler_name
                ):
                    flush()
                solver_infos.append(pi)
            else:
                flush()
                # the sequential path filters against the host cache,
                # which must include every in-flight placement
                self._drain_pending()
                self.pods_fallback += 1
                self.attempt_schedule(pi)
        flush()
        if not pipeline:
            self._drain_pending()
        return len(batch_infos)

    def _solve_and_commit(
        self, solver_infos: List[PodInfo], pod_scheduling_cycle: int
    ) -> None:
        """Synchronous solve: dispatch + download + commit in one call,
        with the gang quorum fixup between solve and commit."""
        pending = self._dispatch_solve(solver_infos, pod_scheduling_cycle)
        if pending is None:
            return
        try:
            if any(
                pi.pod.metadata.labels.get(POD_GROUP_LABEL)
                for pi in solver_infos
            ):
                pending = self._gang_fixup(solver_infos, pending)
                if pending is None:
                    return
            self._complete_solve(pending)
        except SchedulerCrashed:
            self._simulate_crash()  # no recovery: the process "died"
        except Exception:
            # a failed download/commit must not crash the dispatch loop:
            # requeue the batch's pods (they retry on whatever tier the
            # breakers now route to) and drop the stale carry
            logger.exception("synchronous batch completion failed")
            self._recover_failed_batch(pending)

    # -- gang all-or-nothing group masks (SURVEY stage 6) --------------------

    def _gang_fixup(self, solver_infos: List[PodInfo], pending):
        """All-or-nothing placement for PodGroups inside the solver: a
        group whose placed + potential outside members can't reach
        min_member is masked inactive and the batch re-solves, so a
        half-fitting gang reserves NOTHING (no Permit-timeout churn).
        Permit remains the cross-batch completion gate for groups that
        can still assemble (framework/v1alpha1/interface.go:384).

        Outside members (held or still pending) count optimistically --
        the same knowledge horizon as Coscheduling's PreFilter fail-fast
        (total known members vs min_member), sharpened with this batch's
        actual capacity outcome."""
        inactive: set = set()
        for _attempt in range(2):
            assignments = self._pending_assignments(pending)
            failed = self._gang_quorum_failures(pending, assignments)
            failed -= inactive
            if not failed:
                pending["gang_failed_uids"] = inactive
                return pending
            inactive |= failed
            self.gang_resolves += 1
            self._rewind_carry(pending)
            pending = self._dispatch_solve(
                solver_infos, pending["cycle"], inactive_uids=inactive
            )
            if pending is None:
                return None  # packers routed the batch to the host path
        # leftover failures after the final pass are committed as
        # NO_NODE without a re-solve: their capacity stays reserved in
        # the device output, so drop the carry
        assignments = self._pending_assignments(pending)
        leftover = self._gang_quorum_failures(pending, assignments)
        if leftover - inactive:
            inactive |= leftover
            with self._shadow_lock:
                self._dev.invalidate_carry()
        pending["gang_failed_uids"] = inactive
        return pending

    def _rewind_carry(self, pending) -> None:
        """Rewind the device carry to the given batch's pre-solve state:
        the gang quorum fixup re-solves the same batch, which must not
        see the first attempt's reservations. When the dispatch reused
        the carry, its pre-solve device refs are still alive
        (``carry_in``) and the rewind costs nothing on the serving link;
        otherwise the carry drops and the re-dispatch re-uploads."""
        ci = pending.get("carry_in")
        with self._shadow_lock:
            if ci is not None and self._dev.req_dev is not None:
                self._dev.req_dev, self._dev.nzr_dev = ci
            else:
                self._dev.invalidate_carry()

    def _pending_assignments(self, p):
        """The batch's downloaded assignments for the gang fixup: await
        the eager copy when one is in flight, else convert now -- under
        the same wall-clock watchdog that guards the committer's
        download, so a wedged serving link raises SolveTimeout (routed
        through _solve_and_commit's recovery) instead of hanging the
        dispatcher thread forever."""
        tier = p.get("tier", TIER_XLA)
        timeout = (
            self.ladder.config.solve_timeout_seconds
            if tier in (TIER_PALLAS, TIER_XLA) and self.ladder.config.enabled
            else 0.0
        )

        def download():
            eager = p.get("download")
            if eager is not None:
                return eager.result()
            return np.asarray(p["assignments_dev"])

        try:
            return self.ladder.watchdog.call(download, timeout, tier=tier)
        except SolveTimeout:
            breaker = self.ladder.breakers.get(tier)
            if breaker is not None:
                breaker.force_open()
            raise

    def _gang_quorum_failures(self, pending, assignments) -> set:
        """UIDs of every member of a group that cannot reach min_member:
        placed-in-batch + ALL outside known members (held or pending)
        falls short."""
        solver_infos = pending["solver_infos"]
        order = pending["order"]
        b = pending["b"]
        groups = {}
        for k in range(b):
            pod = solver_infos[int(order[k])].pod
            g = pod.metadata.labels.get(POD_GROUP_LABEL)
            if g:
                groups.setdefault(
                    (pod.metadata.namespace, g), []
                ).append(k)
        if not groups:
            return set()
        prof = self.profiles.get(
            solver_infos[0].pod.spec.scheduler_name
        )
        cos = (
            prof.plugin_instance("Coscheduling") if prof is not None else None
        )
        if cos is None:
            # no Coscheduling plugin: the group label carries no gang
            # semantics in this profile -- never mask
            return set()
        failed: set = set()
        for (ns, g), ks in groups.items():
            pod0 = solver_infos[int(order[ks[0]])].pod
            min_member, total = cos.group_quorum_info(pod0, g)
            in_batch_uids = {
                solver_infos[int(order[k])].pod.metadata.uid for k in ks
            }
            placed = sum(
                1 for k in ks if int(assignments[k]) != NO_NODE
            )
            outside = max(0, total - len(in_batch_uids))
            if placed + outside < min_member:
                failed |= in_batch_uids
        return failed

    def _pending_exists(self) -> bool:
        with self._pending_cv:
            return bool(self._pending_q)

    def _pending_head(self):
        with self._pending_cv:
            return self._pending_q[0] if self._pending_q else None

    def _pending_first_unmirrored(self):
        """First pending record whose commit has NOT passed the
        shadow-mutation point (the mirror in ``_complete_solve``).
        Mirrors land in FIFO order, so this record's ``carry_in`` is
        the one snapshot that still equals the host shadows -- the
        under-load carry audit's comparand."""
        with self._pending_cv:
            for p in self._pending_q:
                if not p.get("mirrored"):
                    return p
        return None

    def _unmirrored_exists(self) -> bool:
        """Any dispatched batch whose shadow mirror has NOT landed yet?
        Once every pending record is mirrored the device carry equals
        the host shadow exactly (each dispatch rebinds the carry refs
        and the mirror is the only shadow writer), so the handshake can
        negotiate row-exact repairs with commits still in flight -- the
        speculative chain's cheap-rewind precondition."""
        with self._pending_cv:
            return any(not p.get("mirrored") for p in self._pending_q)

    def _inflight_load16(self) -> int:
        """Worst-case column load of every dispatched-but-unmirrored
        batch: their deltas live in the device carry but not yet in the
        shadow the compression range gate reads."""
        with self._pending_cv:
            return sum(
                int(p.get("load16", 0))
                for p in self._pending_q
                if not p.get("mirrored")
            )

    def _await_mirrors(self, timeout: float = 30.0) -> bool:
        """Block until every in-flight batch has mirrored its deltas
        into the shadow -- far cheaper than ``_drain_pending``, which
        also waits out the bind/commit API transactions. The committer
        notifies ``_pending_cv`` right after each mirror. Returns False
        on timeout or when no committer is running (the caller falls
        back to a full drain)."""
        if self._committer is None:
            return not self._pending_exists()
        deadline = time.monotonic() + timeout
        with self._pending_cv:
            while any(not p.get("mirrored") for p in self._pending_q):
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._pending_cv.wait(min(left, 0.5))
        return True

    def _device_tiers(
        self, mode: str, b: int, n_cap: int, r_dims: int, u_rows: int
    ) -> List[str]:
        """Device tiers live for this (mode, shape), ladder order. The
        pallas tier is only offered when solve_packed would actually run
        the fused kernel (shared predicate ops.assignment
        .pallas_candidate) -- otherwise a shape-ineligible batch would
        run the identical XLA solve twice on failure and charge it to
        the pallas breaker. The XLA scan is always available.

        A MESH offers the shard_map'd Pallas tier instead (shared
        predicate ops.assignment.mesh_pallas_candidate: greedy batches,
        KTPU_MESH_PALLAS=1, node axis divisible by the mesh): each
        device runs the fused whole-array step on its own carry shard
        with one best-of-shards combine per pod. The single-core
        whole-array kernels themselves are still never attempted on a
        mesh; a faulted mesh-pallas solve steps down to the GSPMD XLA
        twin through the same breaker."""
        from kubernetes_tpu.ops.assignment import (
            mesh_pallas_candidate,
            pallas_candidate,
        )

        if self.mesh is None and pallas_candidate(
            mode, b, n_cap, r_dims, u_rows
        ):
            return [TIER_PALLAS, TIER_XLA]
        if (
            self.mesh is not None
            and self.mesh_delta
            and mesh_pallas_candidate(mode, n_cap, self.mesh)
        ):
            return [TIER_PALLAS, TIER_XLA]
        return [TIER_XLA]

    def _pending_has_required_anti(self) -> bool:
        with self._pending_cv:
            return any(p.get("has_required_anti") for p in self._pending_q)

    # -- admission classification (scheduler/admission.py) -------------------

    def _listers(self):
        """Lazily constructed shared PVC/PV/SC/CSINode lister access."""
        listers = self._volume_listers
        if listers is None:
            from kubernetes_tpu.plugins.volumes import _Listers

            prof = next(iter(self.profiles.values()), None)
            listers = _Listers(prof)
            self._volume_listers = listers
        return listers

    def bump_volume_topology_gen(self) -> None:
        """A PV/PVC/StorageClass/CSINode mutation landed: cached
        admission records of PVC-bearing pods are stale from here."""
        self._volume_topo_gen += 1

    def classify_pod(self, pod: Pod) -> Admission:
        """Compute + memoize the pod's admission record (called at
        informer ingest by the event handlers, and lazily at pop time
        for pods that entered the queue some other way). Does NOT touch
        the tensor schema -- only the dispatcher thread registers volume
        columns (_ensure_vol_columns), so the dims registry never grows
        under a concurrently packing NodeTensorCache.update."""
        self.admissions_classified += 1
        return _classify_pod(
            pod,
            extenders=self.algorithm.extenders,
            listers=self._listers(),
            volume_gen=self._volume_topo_gen,
            token=self._admission_token,
            priority_resolver=self._effective_priority,
        )

    def _effective_priority(self, pod: Pod) -> int:
        """The pod's band priority: an explicit spec.priority wins; a
        bare priorityClassName resolves through the PriorityClass
        lister (stamped once at ingest -- the queue's band check is a
        memo read, never a lister lookup per drain)."""
        if pod.spec.priority:
            return pod.spec.priority
        name = pod.spec.priority_class_name
        if name:
            prof = next(iter(self.profiles.values()), None)
            informers = prof.informers if prof is not None else None
            if informers is not None:
                pc = informers.priority_classes().get("default", name)
                if pc is None:
                    pc = informers.priority_classes().get("", name)
                if pc is not None:
                    return int(pc.value)
        return pod.spec.priority

    def _plain_admission_record(self) -> Admission:
        adm = self._plain_adm
        if adm is None:
            from kubernetes_tpu.scheduler.admission import plain_admission

            adm = plain_admission(self._admission_token)
            self._plain_adm = adm
        return adm

    def classify_pods_bulk(self, pods: List[Pod]) -> None:
        """One ingest pass over a watch frame's new pending pods (the
        event handlers' bulk classify): plain pods get their WHOLE
        ingest record -- spec memos, pack-ready row, band priority, and
        the shared Admission -- stamped in one native C pass
        (ingest_stamp; Python twin scheduler/admission.stamp_plain_pods
        behind KTPU_NATIVE_INGEST=0), and only the non-plain remainder
        runs the full per-pod classifier. With extenders configured the
        fast path is off: is_interested must see every pod."""
        if not pods:
            return
        rest_targets: List[Pod] = pods
        if not self.algorithm.extenders:
            from kubernetes_tpu import native as _native
            from kubernetes_tpu.scheduler.admission import (
                ingest_stamp_cfg,
                stamp_plain_pods,
            )

            plain = self._plain_admission_record()
            fn, expected = _native.ingest_fn("ingest_stamp")
            rest = None
            if fn is not None:
                cfg = self._ingest_cfg
                if cfg is None:
                    cfg = ingest_stamp_cfg(plain)
                    self._ingest_cfg = cfg
                try:
                    rest = fn(pods, cfg)
                except Exception:
                    # a fast-path failure must NEVER cost the frame its
                    # enqueue (the caller adds to the queue right after
                    # this): count it and run the twin
                    logger.exception("native ingest_stamp failed")
                    metrics.ingest_native_fallbacks.inc(
                        site="classify-stamp"
                    )
            elif expected:
                metrics.ingest_native_fallbacks.inc(site="classify-stamp")
            if rest is None:
                rest = stamp_plain_pods(pods, plain)
            self.admissions_classified += len(pods) - len(rest)
            rest_targets = [pods[i] for i in rest]
        for pod in rest_targets:
            try:
                self.classify_pod(pod)
            except Exception:
                logger.exception("classifying pod %s", pod.key())

    def attach_volume_counts(self, pod: Pod) -> None:
        """Resolve + memoize a BOUND pod's attachable-volume counts
        before it enters the cache (event handlers call this on the
        cache side of the frame): NodeInfo.add_pod reads the memo into
        the node's in-use accounting. Column registration for in-use
        names happens on the dispatcher thread inside
        NodeTensorCache.update (it scans NodeInfo.volume_in_use)."""
        if not pod.spec.volumes or "_volcount_memo" in pod.__dict__:
            return
        from kubernetes_tpu.plugins.volumes import classify_pod_volumes

        try:
            _reason, counts = classify_pod_volumes(pod, self._listers())
        except Exception:  # noqa: BLE001 - never block the cache path
            logger.exception("volume counts for %s", pod.key())
            counts = ()
        pod.__dict__["_volcount_memo"] = counts

    def _ensure_vol_columns(self, adm: Admission) -> None:
        """Register the record's volume resources as tensor columns.
        Dispatcher-thread only: schema growth must never race the
        packer (registration bumps dims.version, so the next
        NodeTensorCache.update full-repacks with the new column)."""
        if adm.vol_counts:
            dims = self.tensor_cache.dims
            for name, _qty in adm.vol_counts:
                dims.volume_column(name)

    def _admission_of(self, pod: Pod) -> Admission:
        """The pop-time admission read: a memo hit is a dict get; a miss
        (new object, foreign token) or a stale volume classification
        (PVC binding landed mid-queue) re-classifies. Dispatcher-thread
        only (it registers volume columns)."""
        adm = pod.__dict__.get("_admission")
        if adm is not None and adm.token is self._admission_token:
            if adm.pinned or not adm.has_pvc:
                return adm
            if adm.volume_gen == self._volume_topo_gen:
                self._ensure_vol_columns(adm)
                return adm
            self.reclassifications += 1
        adm = self.classify_pod(pod)
        self._ensure_vol_columns(adm)
        return adm

    def _memo_admissions(self, solver_infos: List[PodInfo]) -> List[Admission]:
        """Admission records for a dispatched batch, without the
        staleness re-check: routing was decided at pop time, and the
        record's feature bits describe the same pod object either way."""
        out = []
        token = self._admission_token
        for pi in solver_infos:
            adm = pi.pod.__dict__.get("_admission")
            if adm is None or adm.token is not token:
                adm = self.classify_pod(pi.pod)
                self._ensure_vol_columns(adm)
            out.append(adm)
        return out

    def _live_window(self) -> float:
        """Window source handed to pop_batch when the adaptive
        controller is attached. The queue calls it at every window
        wakeup, so the controller is re-polled MID-WINDOW (still
        interval-gated, and re-entrant on the queue's RLock since this
        runs on the dispatcher thread): a shrink decided while a drain
        is waiting lands on that drain immediately, while the queue
        clamps the deadline so a grow never extends it."""
        ab = self.autobatch
        if ab is not None:
            ab.maybe_step(self)
        return self.batch_window

    def attach_autobatch(self, controller) -> None:
        """Wire an AutoBatchController (streaming/autobatch.py) into the
        dispatch loop: EVERY controller rung joins the warmup compile
        set so rung switches never pay JIT latency mid-run (warmup also
        measures each rung's solve cost, and the controller's
        ``calibrate`` prunes rungs that don't pay), and the controller's
        current outputs are applied immediately."""
        self.autobatch = controller
        for rung in getattr(
            controller, "rungs",
            (controller.latency_batch, controller.max_batch),
        ):
            self._warmup_pads.add(int(rung))
        self._warmup_pads.add(int(controller.max_batch))
        self.batch_window = controller.window
        self.dispatch_batch_cap = controller.batch_cap
        self.solve_pad = controller.batch_cap

    def _stage_add(self, name: str, seconds: float) -> None:
        # lock-free on the hot path: each thread owns its accumulator
        # dict; the lock is only taken once per thread to register it
        d = getattr(self._stage_local, "d", None)
        if d is None:
            d = {}
            self._stage_local.d = d
            with self._stage_lock:
                self._stage_dicts.append(d)
        d[name] = d.get(name, 0.0) + seconds

    @property
    def stage_seconds(self) -> dict:
        """Merged per-stage wall-clock totals across every accumulating
        thread (dispatcher, committer, bind pool). dict.copy() is atomic
        under the GIL, so a concurrent _stage_add never corrupts the
        merge -- at worst the freshest increment lands in the next
        read."""
        with self._stage_lock:
            dicts = [d.copy() for d in self._stage_dicts]
        out: dict = {}
        for d in dicts:
            for k, v in d.items():
                out[k] = out.get(k, 0.0) + v
        return out

    @property
    def mesh_solver_tier(self) -> str:
        """Which mesh tier the run ACTUALLY solved on, for the perf
        matrix's ``solver_mesh_tier`` label: "pallas" once any batch
        rode the shard_map'd Pallas tier, else "xla" (the GSPMD twin --
        either KTPU_MESH_PALLAS=0, an ineligible shape, or every pallas
        attempt faulted to the twin). Empty off-mesh."""
        if self.mesh is None:
            return ""
        if self.ladder.solves_by_tier.get(TIER_PALLAS):
            return "pallas"
        return "xla"

    def _pending_has_ports(self) -> bool:
        with self._pending_cv:
            return any(p.get("has_ports") for p in self._pending_q)

    def _pending_has_scoring_terms(self) -> bool:
        with self._pending_cv:
            return any(p.get("has_scoring_terms") for p in self._pending_q)

    def _ensure_committer(self) -> None:
        if self._committer is None:
            self._committer_stop = False
            self._committer = threading.Thread(
                target=self._committer_loop, name="batch-committer",
                daemon=True,
            )
            self._committer.start()

    def _stop_committer(self) -> None:
        with self._pending_cv:
            self._committer_stop = True
            self._pending_cv.notify_all()
        if self._committer is not None:
            self._committer.join(timeout=10)
            if self._committer.is_alive():
                # the join timed out: the committer is wedged (most
                # likely a hung result download over the serving link).
                # Silence here would strand in-flight batches invisibly
                # -- log, count, and raise the degraded-health flag so
                # operators and the health endpoint see it.
                logger.error(
                    "committer thread failed to join within 10s; "
                    "%d batch(es) may be stranded in flight",
                    len(self._pending_q),
                )
                metrics.commit_join_timeouts.inc()
                metrics.degraded_health.set(
                    1, reason="committer_join_timeout"
                )
                flightrecorder.dump_on_degraded("committer_join_timeout")
                self.commit_degraded = True
            self._committer = None

    def _committer_loop(self) -> None:
        """Completes dispatched batches in FIFO order: the ~100ms serving
        link round trip per result download happens here, off the
        dispatcher thread (which is already packing the next batch). A
        batch stays at the queue head until fully committed so
        _drain_pending and the dispatch-time pending checks see it."""
        while True:
            with self._pending_cv:
                while not self._pending_q and not self._committer_stop:
                    self._pending_cv.wait()
                if not self._pending_q and self._committer_stop:
                    return
                p = self._pending_q[0]
            try:
                p["committing"] = True
                self._complete_solve(p)
            except SchedulerCrashed:
                self._simulate_crash()  # no recovery: the process "died"
            except Exception:
                logger.exception("batch commit crashed")
                self._recover_failed_batch(p)
            finally:
                with self._pending_cv:
                    self._pending_q.popleft()
                    self._pending_cv.notify_all()

    def _recover_failed_batch(self, p) -> None:
        """A committer crash (serving-link error mid-download, commit
        bug) must not strand the batch's pods as Pending-forever: every
        pod not already assumed goes back through the failure path
        (requeue with backoff + condition), and the device carry is
        dropped since the batch's true placements are unknown."""
        with self._shadow_lock:
            self._dev.invalidate_carry()
        try:
            if self._deferred_preempt:
                self._flush_deferred_preemptions()
        except Exception:
            logger.exception("flushing deferred preemptions on recovery")
        prof = self.profiles.get(
            p["solver_infos"][0].pod.spec.scheduler_name
        )
        for pi in p["solver_infos"]:
            try:
                if prof is None or self.cache.is_assumed_pod(pi.pod):
                    continue
                self.record_scheduling_failure(
                    prof, pi, "batch commit failed", "SchedulerError", "",
                    p["cycle"],
                )
            except Exception:
                logger.exception("recovering pod %s", pi.pod.key())

    def _solve_pipelined(
        self, solver_infos: List[PodInfo], pod_scheduling_cycle: int
    ) -> None:
        """Dispatch this batch and enqueue it for the committer thread;
        blocks only when MAX_INFLIGHT batches are already in flight.
        Gang batches take the synchronous path: the quorum fixup
        (SURVEY stage 6 all-or-nothing group masks) may re-solve, which
        must not race in-flight batches."""
        if any(
            pi.pod.metadata.labels.get(POD_GROUP_LABEL)
            for pi in solver_infos
        ):
            self._drain_pending()
            self._solve_and_commit(solver_infos, pod_scheduling_cycle)
            return
        pending = self._dispatch_solve(solver_infos, pod_scheduling_cycle)
        if pending is None:
            return
        self._ensure_committer()
        with self._pending_cv:
            while len(self._pending_q) >= self.max_inflight:
                self._pending_cv.wait()
            if self._pending_q:
                # the solve launched against the shadow-EXPECTED state
                # of still-uncommitted batches: a speculative link in
                # the chain (a commit divergence rewinds it via the
                # row-patch path instead of a drain)
                self.speculative_launches += 1
                metrics.speculative_launches.inc()
            self._pending_q.append(pending)
            self._pending_cv.notify_all()

    def _drain_pending(self) -> None:
        """Block until every in-flight batch has committed (the host
        cache then reflects every dispatched placement)."""
        if self._committer is None:
            while self._pending_q:
                pend = self._pending_q.popleft()
                try:
                    self._complete_solve(pend)
                except Exception:
                    logger.exception("drain commit failed")
                    self._recover_failed_batch(pend)
            return
        with self._pending_cv:
            while self._pending_q:
                self._pending_cv.wait()

    # -- device-state generation handshake ----------------------------------

    def _explain_rows(self, changed, host_req, host_nzr):
        """Under ``_shadow_lock``: is every changed row's host content
        explained by the shadow expectation at some committer-trail
        depth? The host may trail the shadow by a suffix of
        ``pending_deltas`` (batches mirrored but whose cache assume the
        host pack predates) -- peel them newest-first until the changed
        rows match. Returns ``(ok, divergent_rows, keep)``: on a match
        ``keep`` is the number of newest deltas still unconfirmed; on a
        mismatch ``divergent_rows`` holds the depth-0 mismatches and
        ``keep`` is 0 when NO pending delta touches them (the mismatch
        is genuinely external, so a row scatter-fix is exact -- the
        device carry always equals the shadow once every dispatched
        batch has mirrored) or None when one does (the row may merely
        be host-lagging; only a full resync is safe)."""
        ds = self._dev
        if changed.size == 0:
            # no repacked rows: nothing to confirm, keep every delta
            return True, None, len(ds.pending_deltas)
        exp_req = ds.req_shadow[changed]
        exp_nzr = ds.nzr_shadow[changed]
        h_req = host_req[changed]
        h_nzr = host_nzr[changed]
        row_ok = np.all(exp_req == h_req, axis=1) & np.all(
            exp_nzr == h_nzr, axis=1
        )
        if row_ok.all():
            return True, None, 0
        div_rows = changed[~row_ok]
        pos = {int(r): j for j, r in enumerate(changed)}
        keep = 0
        for rows, req_rows, nzr_rows in reversed(ds.pending_deltas):
            keep += 1
            for j, r in enumerate(rows.tolist()):
                jj = pos.get(int(r))
                if jj is not None:
                    exp_req[jj] -= req_rows[j]
                    exp_nzr[jj] -= nzr_rows[j]
            if (
                np.all(exp_req == h_req, axis=1)
                & np.all(exp_nzr == h_nzr, axis=1)
            ).all():
                return True, None, keep
        div_set = set(div_rows.tolist())
        lagging = any(
            int(r) in div_set
            for rows, _req_rows, _nzr_rows in ds.pending_deltas
            for r in rows
        )
        return False, div_rows, (None if lagging else 0)

    def _adopt_membership_rows(self, member, host_req, host_nzr):
        """Under ``_shadow_lock``, with nothing in flight (so the device
        carry equals the shadow): adopt host truth for churned row slots
        into the shadow expectation and scrub them from the pending
        ring (their pre-churn deltas can never be confirmed -- the slot
        belongs to a different node now). Returns the subset whose
        device content (== pre-adoption shadow) actually differs and
        therefore must ride the didx scatter."""
        ds = self._dev
        diff = ~(
            np.all(ds.req_shadow[member] == host_req[member], axis=1)
            & np.all(ds.nzr_shadow[member] == host_nzr[member], axis=1)
        )
        fix = member[diff]
        ds.req_shadow[member] = host_req[member]
        ds.nzr_shadow[member] = host_nzr[member]
        if ds.pending_deltas:
            mset = set(member.tolist())
            scrubbed = collections.deque(
                maxlen=ds.pending_deltas.maxlen
            )
            for rows, req_rows, nzr_rows in ds.pending_deltas:
                keepm = np.fromiter(
                    (int(r) not in mset for r in rows),
                    dtype=bool, count=len(rows),
                )
                if keepm.all():
                    scrubbed.append((rows, req_rows, nzr_rows))
                elif keepm.any():
                    scrubbed.append(
                        (rows[keepm], req_rows[keepm], nzr_rows[keepm])
                    )
                # entries fully on churned slots drop: nothing left to
                # confirm
            ds.pending_deltas = scrubbed
        return fix

    def _negotiate_device_state(
        self, nt, node_requested, node_nzr, overlaid,
        allow_scatter, pending_exists, unmirrored_exists=None,
    ):
        """Decide how this dispatch's node state reaches the device and
        reconcile the handshake bookkeeping. Returns None when in-flight
        batches block the decision (caller drains and redispatches), else
        ``{"static_ok", "carry_ok", "didx", "sidx", "member"}``:

        - carry_ok + empty deltas: pure reuse, nothing node-sized rides
          the link.
        - carry_ok + didx/sidx rows: reuse, with externally changed rows
          (divergences / allocatable updates) patched onto the resident
          state by the in-buffer scatter (ops/assignment.py). Membership
          churn (node add/remove claiming/retiring slots in place, see
          NodeTensorCache) rides the same scatter -- sidx patches alloc
          AND valid, didx resets the slot's requested state -- and is an
          EXPECTED reset, never counted as a divergence.
        - not carry_ok: full [N, R] requested upload (``state_uploads``);
          not static_ok additionally re-uploads allocatable+valid.

        The mesh path rides the same scatters through the sharded twin
        (each delta row lands on exactly one node shard);
        ``allow_scatter=False`` is the KTPU_MESH_DELTA=0 escape hatch
        that restores the PR-5 counted full-upload fallback.

        ``unmirrored_exists`` is the speculative-chain relaxation: the
        membership-adopt and scatter-fix paths only need the device
        carry to EQUAL the shadow, which holds as soon as every
        in-flight batch has mirrored -- commits may still be running.
        Only the full-upload path (which takes HOST truth as the new
        carry, so every placement must have landed in the cache) still
        gates on ``pending_exists``. Defaults to ``pending_exists``
        (the conservative pre-pipelining behavior) when not given.
        """
        if unmirrored_exists is None:
            unmirrored_exists = pending_exists
        ds = self._dev
        d = nt.delta
        empty = np.zeros(0, dtype=np.int64)
        with self._shadow_lock:
            layout_ok = (
                d is not None
                and ds.alloc_dev is not None
                and ds.alloc_shadow is not None
                and ds.layout_epoch == d.layout_epoch
                and ds.alloc_shadow.shape == nt.allocatable.shape
            )
            alloc_rows = empty
            member = empty
            member_fix = empty
            carry = "dead"
            div_rows = None
            keep = 0
            if layout_ok:
                changed = self.tensor_cache.rows_changed_since(
                    ds.validated_epoch
                )
                member = self.tensor_cache.membership_rows_since(
                    ds.validated_epoch
                )
                if member.size and allow_scatter and unmirrored_exists:
                    # churned slots cannot be reconciled while an
                    # UNMIRRORED batch is in flight: it may have placed
                    # onto a now-retired slot, and adopting host truth
                    # under it would desync the mirror. Once every
                    # in-flight batch has mirrored the carry equals the
                    # shadow and the adopt+scatter is exact, so the
                    # caller only needs to await mirrors (cheap), not a
                    # full drain.
                    return None
                nonmember = changed
                if member.size:
                    nonmember = np.setdiff1d(changed, member)
                if nonmember.size:
                    diff = ~np.all(
                        nt.allocatable[nonmember]
                        == ds.alloc_shadow[nonmember],
                        axis=1,
                    )
                    alloc_rows = nonmember[diff]
                if member.size:
                    # membership rows always ride the static scatter:
                    # alloc content AND validity flip with slot identity
                    alloc_rows = np.union1d(alloc_rows, member)
                if (
                    not overlaid
                    and ds.req_dev is not None
                    and ds.req_shadow is not None
                ):
                    if member.size and not allow_scatter:
                        carry = "dead"  # mesh: counted full upload
                    else:
                        if member.size:
                            member_fix = self._adopt_membership_rows(
                                member, node_requested, node_nzr
                            )
                        ok, div_rows, keep = self._explain_rows(
                            nonmember, node_requested, node_nzr
                        )
                        carry = "reuse" if ok else "diverged"
            static_full = (
                not layout_ok
                or alloc_rows.size > DELTA_ROW_BUCKET
                or (alloc_rows.size > 0 and not allow_scatter)
            )
            fix_rows = empty
            diverged = carry == "diverged"
            if diverged:
                if (
                    allow_scatter
                    and not static_full
                    and div_rows.size <= DELTA_ROW_BUCKET
                    and keep == 0  # no pending delta touches a div row
                    and not unmirrored_exists
                ):
                    # resolvable in place: with every in-flight batch
                    # mirrored the carry equals the shadow, so setting
                    # the divergent rows to host truth on device is
                    # exact even with commits still running -- the
                    # speculative chain's cheap rewind (a bind
                    # conflict / quota refund / conflict-requeue
                    # re-solves only against these patched rows)
                    fix_rows = div_rows
                else:
                    carry = "dead"  # resolve by full upload (or drain)
            didx_rows = member_fix
            if fix_rows.size:
                didx_rows = np.union1d(member_fix, fix_rows)
            if didx_rows.size > DELTA_ROW_BUCKET:
                # too many row patches: full upload. `diverged` keeps
                # its value -- a genuine divergence resolved by this
                # upload must still be counted, even when the overflow
                # came from the membership rows
                carry = "dead"
                fix_rows = empty
                didx_rows = empty
            reusable = not static_full and (
                carry == "reuse" or fix_rows.size > 0
            )
            if pending_exists and not reusable:
                # the device carry is ahead of the host by the in-flight
                # placements; uploading host state now would re-place
                # them. Land everything first, then redo the dispatch.
                return None
            if reusable:
                # the fix path requires an empty ring, so keep is only
                # meaningful (a match depth) on the pure-reuse path
                for _ in range(len(ds.pending_deltas) - (keep or 0)):
                    ds.pending_deltas.popleft()
                if alloc_rows.size:
                    ds.alloc_shadow[alloc_rows] = nt.allocatable[alloc_rows]
                    if ds.valid_shadow is not None:
                        ds.valid_shadow[alloc_rows] = nt.valid[alloc_rows]
                if fix_rows.size:
                    ds.req_shadow[fix_rows] = node_requested[fix_rows]
                    ds.nzr_shadow[fix_rows] = node_nzr[fix_rows]
                    self.carry_divergences += 1
                    metrics.carry_divergences.inc()
                    if pending_exists:
                        # the expected deltas diverged under an active
                        # speculative chain and the carry was repaired
                        # in place: the cheap rewind, not a drain
                        self.speculative_rewinds += 1
                        metrics.speculative_rewinds.inc(
                            reason="row_patch"
                        )
                if member.size:
                    self.membership_row_patches += int(member.size)
                ds.validated_epoch = d.epoch
                self.state_reuses += 1
                self.delta_rows_uploaded += int(
                    alloc_rows.size + didx_rows.size
                )
                return {
                    "static_ok": True,
                    "carry_ok": True,
                    "didx": didx_rows,
                    "sidx": alloc_rows,
                    "member": int(member.size),
                }
            # upload path
            if diverged:
                self.carry_divergences += 1
                metrics.carry_divergences.inc()
            static_ok = not static_full and alloc_rows.size == 0
            if not static_ok:
                ds.layout_epoch = (
                    d.layout_epoch if d is not None else -1
                )
                ds.alloc_shadow = nt.allocatable.copy()
                ds.valid_shadow = np.array(nt.valid, dtype=bool)
            ds.req_shadow = node_requested.copy()
            ds.nzr_shadow = node_nzr.copy()
            ds.pending_deltas.clear()
            ds.validated_epoch = d.epoch if d is not None else -1
            self.state_uploads += 1
            return {
                "static_ok": static_ok,
                "carry_ok": False,
                "didx": empty,
                "sidx": empty,
                "member": 0,
            }

    def _compress_decision(
        self, neg, constrained, overlaid, node_requested, node_nzr,
        batch_load16,
    ) -> bool:
        """Engage the int16 carry for THIS dispatch only when it is
        provably lossless: the largest resident column value (shadow
        maxima post-negotiate, or the upload source on a cold path)
        plus this batch's and every unmirrored in-flight batch's
        worst-case column load must stay inside the int16 guard band.
        Converts the resident carry on a mode flip (one tiny on-device
        kernel each way, both warmed) and books the disengage reasons.
        Constrained/overlaid dispatches always run uncompressed -- the
        constrained ladder keeps its one-int32-signature contract."""
        ds = self._dev
        resident16 = (
            ds.req_dev is not None
            and getattr(ds.req_dev, "dtype", None) == jnp.int16
        )
        want = not constrained and not overlaid
        if want:
            with self._shadow_lock:
                if neg["carry_ok"] and ds.req_shadow is not None:
                    resident = max(
                        int(ds.req_shadow.max(initial=0)),
                        int(ds.nzr_shadow.max(initial=0)),
                    )
                else:
                    resident = max(
                        int(node_requested.max(initial=0)),
                        int(node_nzr.max(initial=0)),
                    )
            load = batch_load16 + self._inflight_load16()
            want = resident + load <= _CARRY_COMPRESS_CEILING
            if not want and resident16:
                metrics.carry_compress_disengages.inc(reason="range")
        elif resident16:
            metrics.carry_compress_disengages.inc(reason="mode")
        if neg["carry_ok"] and ds.req_dev is not None:
            if want and not resident16:
                ds.req_dev, ds.nzr_dev = compress_carry(
                    ds.req_dev, ds.nzr_dev
                )
            elif not want and resident16:
                ds.req_dev, ds.nzr_dev = decompress_carry(
                    ds.req_dev, ds.nzr_dev
                )
        metrics.carry_compressed.set(1.0 if want else 0.0)
        return want

    def _dispatch_solve(
        self,
        solver_infos: List[PodInfo],
        pod_scheduling_cycle: int,
        inactive_uids=None,
        raise_on_exhaust: bool = False,
    ):
        """Pack + upload + dispatch one solver batch. Returns a pending
        record for _complete_solve, or None when the batch was routed to
        the sequential path. Paths that read host-side cluster state the
        in-flight batch would change (spread counts, nominee overlays,
        incompatible clusters) drain the pipeline first.

        ``raise_on_exhaust`` (the bisection sub-solve mode): a ladder
        exhaustion re-raises to the caller -- after the carry-state
        un-booking -- instead of routing the batch to containment or
        the sequential floor (the bisection loop owns that batch's
        disposition)."""
        timeline.mark(f"dispatch_start b={len(solver_infos)}")
        if not raise_on_exhaust:
            inj0 = get_injector()
            if inj0 is not None and inj0.should_fire(
                FaultPoint.DEVICE_LOST
            ):
                self._on_device_lost()
        with self._shadow_lock:
            # under the lock: the committer bumps this too, and a lost
            # increment would blind the carry audit's race detector
            self._dispatch_seq += 1
        t_pack = time.perf_counter()
        # -- flight-recorder span: one per dispatch (a gang re-solve or
        # drain-redispatch is honestly its own span), with the per-pod
        # linkage (uid -> batch id, queue-wait, attempts) that makes a
        # pod's whole pod-to-bind path one join
        if flightrecorder.ENABLED:
            now_m = time.monotonic()
            span = flightrecorder.begin_batch(
                len(solver_infos),
                pods=[
                    (pi.pod.metadata.uid,
                     max(0.0, now_m - pi.timestamp), pi.attempts)
                    for pi in solver_infos
                ],
            )
            pop_note = self._pop_note
            if pop_note is not None:
                self._pop_note = None
                work, pop_waited, t_pop0 = pop_note
                # the drain blocks for arrivals first, then drains:
                # wait span at t_pop0, work span after it
                if pop_waited:
                    span.stage("pop_wait", pop_waited, t0=t_pop0)
                span.stage("pop_batch", work, t0=t_pop0 + pop_waited)
            if inactive_uids:
                span.note(gang_redispatch=True)
            if raise_on_exhaust:
                span.note(bisect=True)
        else:
            span = flightrecorder.NULL_SPAN
        pods = [pi.pod for pi in solver_infos]
        # poison manifestation: any stamped pod in the dispatch fails
        # every ladder tier (PoisonError), driving the exhaustion the
        # bisection containment hangs off; a sub-batch WITHOUT the
        # stamped pod solves normally -- exactly the signature the
        # O(log B) search isolates on
        poison_key = None
        if get_injector() is not None:
            for pod_p in pods:
                if pod_is_poisoned(pod_p):
                    poison_key = pod_p.key()
                    break
        # batch-level constraint aggregates from the cached admission
        # feature bits (scheduler/admission.py): any() over memo reads
        # instead of re-walking every spec per dispatch
        adms = self._memo_admissions(solver_infos)
        has_hard_spread = any(a.hard_spread for a in adms)
        batch_ports = any(a.ports for a in adms)
        has_affinity_terms = any(a.affinity_req for a in adms)
        has_affinity = has_affinity_terms or batch_ports
        has_required_anti = any(a.required_anti for a in adms)
        prof0 = self.profiles.get(pods[0].spec.scheduler_name)
        # gated on the profile actually scoring with InterPodAffinity --
        # otherwise the ipa family packs nothing and draining for it
        # would serialize the pipeline for free
        ipa_weight = (
            prof0.score_plugin_weights().get("InterPodAffinity", 0)
            if prof0 is not None
            else 0
        )
        score_dynamic = (
            any(a.score_soft for a in adms)
            or (
                bool(ipa_weight)
                and any(a.score_pref for a in adms)
            )
            or batch_selector_spread_live(
                pods, prof0.informers if prof0 is not None else None
            )
        )
        # this batch's pods become symmetric scorers for later batches
        # once placed (preferred terms, and required affinity terms via
        # hardPodAffinityWeight)
        has_scoring_terms = bool(ipa_weight) and any(
            a.scoring_terms for a in adms
        )
        nominated_by_node = self.queue.all_nominated_pods_by_node()

        def drained(reason_predicate: bool) -> bool:
            """Land every in-flight batch when the predicate holds, then
            rebuild the drain-sensitive inputs (nominee overlay source;
            callers refresh the snapshot themselves when they hold one).
            Returns True when a drain happened."""
            nonlocal nominated_by_node
            if not reason_predicate or not self._pending_exists():
                return False
            self.pipeline_drains += 1
            self._drain_pending()
            # the drain can assume previously nominated pods (dropping
            # their nomination) and nominate new ones via preemption --
            # rebuild the overlay source from the post-drain state
            nominated_by_node = self.queue.all_nominated_pods_by_node()
            return True

        nominee_uids = (
            {
                p.metadata.uid
                for noms in nominated_by_node.values()
                for p in noms
            }
            if nominated_by_node else set()
        )
        drained(
            has_hard_spread or has_affinity_terms or score_dynamic
            # a port batch must see in-flight PORT placements committed
            # into the static mask; port-free in-flight batches cannot
            # conflict, so they don't force the drain
            or (batch_ports and self._pending_has_ports())
            # an in-flight batch carrying required anti-affinity or
            # scoring-relevant terms imposes symmetric constraints this
            # batch can only see once its placements are committed
            or self._pending_has_required_anti()
            or self._pending_has_scoring_terms()
            # a batch RETRYING preemption nominees must see the fully
            # committed post-eviction state, or in-flight placements
            # race it onto the freed capacity and cascade re-preemption
            # (the old answer -- drain while ANY nomination lived --
            # serialized every post-preemption dispatch; this drains
            # only the nominees' own retry batches)
            or any(
                pi.pod.metadata.uid in nominee_uids
                for pi in solver_infos
            )
        )

        snapshot = self.algorithm.snapshot
        self.cache.update_snapshot(snapshot)
        # existing pods with required anti-affinity constrain EVERY
        # incoming pod symmetrically (filtering.go:404) -- such clusters
        # need the affinity tensors even for batches without affinity, and
        # their counts must include any in-flight placements
        if not has_affinity_terms and cluster_has_required_anti_affinity(
            snapshot
        ):
            has_affinity = True
            has_affinity_terms = True
            if drained(True):
                self.cache.update_snapshot(snapshot)
        # existing pods with symmetric scoring terms make EVERY batch's
        # preferred-affinity family live (scoring.go:111): the in-flight
        # counts must land before packing
        cluster_ipa = bool(ipa_weight) and cluster_has_affinity_scoring(
            snapshot
        )
        if not score_dynamic and cluster_ipa:
            score_dynamic = True
            if drained(True):
                self.cache.update_snapshot(snapshot)
                cluster_ipa = cluster_has_affinity_scoring(snapshot)
        if nominated_by_node and (
            has_hard_spread or has_affinity or score_dynamic
            # a CONSTRAINED nominee (required (anti-)affinity / spread)
            # imposes symmetric constraints the resource-only overlay
            # can't express even for a plain batch
            or any(
                p.spec.affinity is not None
                and (
                    p.spec.affinity.pod_affinity is not None
                    or p.spec.affinity.pod_anti_affinity is not None
                )
                or p.spec.topology_spread_constraints
                for noms in nominated_by_node.values()
                for p in noms
            )
        ):
            # ADVICE r2 (medium): nominees are overlaid as RESOURCES
            # only; the affinity/spread/score count tensors pack from
            # the snapshot, which excludes them, so a constrained device
            # batch could violate a nominee's symmetric constraints.
            # The host path runs _add_nominated_pods exactly
            # (generic_scheduler.go:535) -- take it for this rare
            # combination (active nominations + constraints on either
            # side).
            self._drain_pending()
            self.nominee_constrained_fallbacks += 1
            span.finish(
                tier=TIER_SEQUENTIAL, routed="nominee_constrained"
            )
            for pi in solver_infos:
                self.pods_fallback += 1
                self.attempt_schedule(pi)
            return None
        with timeline.span("nt.update"):
            nt = self.tensor_cache.update(snapshot)
        with timeline.span("pack_pod_batch"):
            batch = pack_pod_batch(
                pods, nt.dims,
                timestamps=[pi.timestamp for pi in solver_infos],
            )
        with timeline.span("static_mask"):
            mask_rows, mask_index = static_mask_compact(pods, snapshot, nt)
        # pods requesting resources no node advertises are unsatisfiable:
        # point them at a dedicated all-False row
        if batch.unsatisfiable.any():
            mask_rows = np.concatenate(
                [mask_rows, np.zeros((1, nt.capacity), dtype=bool)]
            )
            mask_index = mask_index.copy()
            mask_index[batch.unsatisfiable] = mask_rows.shape[0] - 1

        # Nominated-pod overlay: reserve capacity for preemption nominees
        # (the batch analogue of _add_nominated_pods' virtual add,
        # generic_scheduler.go:535). Conservatively reserves for ALL
        # nominees EXCEPT pods already being placed: this batch's own
        # members and pods inside in-flight batches (their placement
        # rides the device carry; overlaying them too would double-count
        # and spuriously starve nodes -- the old answer was a full
        # pipeline drain per dispatch while ANY nomination lived, which
        # serialized the dispatcher against the committer for the whole
        # post-preemption burst).
        node_requested, node_nzr = nt.requested, nt.non_zero_requested
        # skip the overlay for pods being placed RIGHT NOW: this batch's
        # members and pods inside dispatched-but-not-yet-committing
        # batches (their placement rides the device carry; overlaying
        # them too over-reserves their nodes and cascades spurious
        # preemption). The mid-COMMIT head batch is NOT excluded: its
        # failures are being requeued with live nominations by the
        # deferred wave at this very moment, and their reservations
        # must stand.
        batch_uids = {pi.pod.metadata.uid for pi in solver_infos}
        with self._pending_cv:
            for pend in self._pending_q:
                if not pend.get("committing"):
                    batch_uids.update(
                        pi.pod.metadata.uid
                        for pi in pend["solver_infos"]
                    )
        overlay_pods = []
        overlay_rows = []
        for node_name, nominated in nominated_by_node.items():
            if node_name not in nt.names:
                continue
            j = nt.row(node_name)
            for npod in nominated:
                if npod.metadata.uid in batch_uids:
                    continue
                overlay_pods.append(npod)
                overlay_rows.append(j)
        overlaid = bool(overlay_pods)
        if overlaid:
            node_requested = node_requested.copy()
            node_nzr = node_nzr.copy()
            nbatch = pack_pod_batch(overlay_pods, nt.dims)
            np.add.at(
                node_requested, np.asarray(overlay_rows), nbatch.requests
            )
            np.add.at(
                node_nzr, np.asarray(overlay_rows),
                nbatch.non_zero_requests,
            )

        b = batch.size
        # fixed solve shape: every batch pads to max_batch so the solver
        # JITs exactly once per (node-bucket, variant). The adaptive
        # controller may floor the pad at its current rung instead --
        # small batches then run a proportionally cheaper solve -- so
        # the signature set is {warmed rungs} + {max_batch} plus the
        # defensive oversize bucket. Warmup compiles the BASIC layouts
        # for every rung; constrained layouts warm at max_batch only
        # (the pre-existing latency-rung tradeoff: rare enough that
        # the one-time compile lands on demand), so a batch whose
        # aggregates say constraint families may pack never ESCALATES
        # to a mid rung -- it takes the max_batch signature as before.
        pad_floor = self.solve_pad
        if not pad_floor or b > pad_floor:
            # escalate to the smallest pre-compiled rung that fits
            # (ladder-aware: an oversize plain batch lands on the next
            # warmed rung up instead of jumping straight to the
            # max_batch signature); anything past every warmed rung,
            # or possibly-constrained, takes the max_batch signature
            may_constrain = (
                has_hard_spread or has_affinity or score_dynamic
                or has_scoring_terms
            )
            fitting = [p for p in self._warmup_pads if p >= b]
            pad_floor = (
                min(fitting) if fitting and not may_constrain
                else self.max_batch
            )
        padded = max(
            pad_floor, POD_BUCKET * math.ceil(b / POD_BUCKET)
        )
        order = batch.order
        # -- tenant fairness bias (scheduler/tenancy.py): within each
        # priority level, re-merge the solve order so the tenant with
        # the lowest virtual dominant share places next -- the solve
        # order IS the arbitration point of the sequential-replay scan,
        # so every tier (pallas/XLA/mesh/host-greedy) honors the bias
        # with zero kernel changes. Single-tenant batches exit after
        # one namespace sweep.
        tt = self.tenant_shares
        if tt is not None and b > 1:
            from kubernetes_tpu.scheduler.tenancy import fair_order

            tt.refresh_capacity(nt)
            order = fair_order(order, pods, batch.priorities, tt)
        req = np.zeros((padded, nt.dims.num_dims), dtype=np.int32)
        nzr = np.zeros((padded, 2), dtype=np.int32)
        midx = np.zeros(padded, dtype=np.int32)
        active = np.zeros(padded, dtype=bool)
        req[:b] = batch.requests[order]
        nzr[:b] = batch.non_zero_requests[order]
        midx[:b] = mask_index[order]
        active[:b] = True
        if inactive_uids:
            # gang quorum fixup: masked group members solve to NO_NODE
            for k in range(b):
                if (
                    solver_infos[int(order[k])].pod.metadata.uid
                    in inactive_uids
                ):
                    active[k] = False
        u = mask_rows.shape[0]
        u_padded = MASK_ROW_BUCKET * math.ceil(u / MASK_ROW_BUCKET)
        rows = np.zeros((u_padded, nt.capacity), dtype=bool)
        rows[:u] = mask_rows

        # hard topology-spread constraints solve on device via the
        # group-count scan (ops/topology.py); required (anti-)affinity via
        # the count-tensor replay (ops/affinity.py)
        # non-resource score plugins: pack when they can influence ranking
        # (dynamic families already forced a pipeline drain above, so the
        # snapshot these counts come from includes in-flight placements)
        ordered_pods = [pods[int(i)] for i in order]
        try:
            hard_w = 1
            if prof0 is not None:
                ipa_plugin = prof0.plugin_instance("InterPodAffinity")
                hard_w = getattr(
                    ipa_plugin, "hard_pod_affinity_weight", 1
                ) if ipa_plugin is not None else 1
            score_batch = pack_score_batch(
                ordered_pods, snapshot, nt,
                prof0.informers if prof0 is not None else None,
                prof0.score_plugin_weights() if prof0 is not None else {},
                hard_pod_affinity_weight=hard_w,
                cluster_affinity_scoring=cluster_ipa,
            )
        except ScoreEnvelopeExceeded:
            # the sequential path filters against the host cache, which
            # must include every in-flight placement
            self.envelope_fallbacks += 1
            self._drain_pending()
            span.finish(tier=TIER_SEQUENTIAL, routed="score_envelope")
            for pi in solver_infos:
                self.pods_fallback += 1
                self.attempt_schedule(pi)
            return None

        spread = None
        affinity = None
        if has_hard_spread:
            spread = pack_spread_batch(ordered_pods, snapshot, nt)
            if spread is None:
                # envelope exceeded: host path keeps full correctness
                self.envelope_fallbacks += 1
                span.finish(
                    tier=TIER_SEQUENTIAL, routed="spread_envelope"
                )
                for pi in solver_infos:
                    self.pods_fallback += 1
                    self.attempt_schedule(pi)
                return None
        if has_affinity:
            affinity = pack_affinity_batch(ordered_pods, snapshot, nt)
            if affinity is None and has_affinity_terms:
                # envelope exceeded (real affinity/exist rows expected
                # but the packer bailed): the host path keeps full
                # correctness -- port-only batches fall through to the
                # port-row builder instead
                self.envelope_fallbacks += 1
                span.finish(
                    tier=TIER_SEQUENTIAL, routed="affinity_envelope"
                )
                for pi in solver_infos:
                    self.pods_fallback += 1
                    self.attempt_schedule(pi)
                return None
            if batch_ports:
                # within-batch host-port conflicts ride synthetic anti
                # rows (ops/affinity.add_host_port_rows); existing-pod
                # conflicts are already in the static mask
                affinity = add_host_port_rows(
                    ordered_pods, snapshot, nt, affinity
                )
                if affinity is None:
                    # port-row envelope exceeded: the sequential filter
                    # must see every in-flight placement committed (a
                    # port-only batch may not have drained above)
                    self._drain_pending()
                    self.envelope_fallbacks += 1
                    span.finish(
                        tier=TIER_SEQUENTIAL, routed="port_envelope"
                    )
                    for pi in solver_infos:
                        self.pods_fallback += 1
                        self.attempt_schedule(pi)
                    return None

        dt_pack = time.perf_counter() - t_pack
        self._stage_add("pack", dt_pack)
        span.stage("pack", dt_pack, t0=t_pack)
        span.note(padded=padded)
        solve_timer = metrics.SinceTimer(metrics.batch_solve_duration)

        # preemption prewarm: when the batch's most demanding request
        # fits on NO node right now, failures (and a preemption wave)
        # are coming -- build + upload the victim pack on a helper
        # thread WHILE the solve runs, instead of paying the ~0.25s
        # pack + ~5MB upload inside the wave
        if self.preemptor is not None and b:
            free_nodes = nt.allocatable - node_requested  # [N, R]
            req_max = req[:b].max(axis=0)
            if not (
                (free_nodes >= req_max).all(axis=1) & nt.valid
            ).any():
                self.preemptor.prewarm_pack_async()

        constrained = (
            spread is not None
            or affinity is not None
            or score_batch is not None
        )
        if constrained and nt.capacity > CONSTRAINED_NODE_CAP:
            self._drain_pending()
            self.envelope_fallbacks += 1
            span.finish(
                tier=TIER_SEQUENTIAL, routed="constrained_node_cap"
            )
            for pi in solver_infos:
                self.pods_fallback += 1
                self.attempt_schedule(pi)
            return None

        # -- device-state generation handshake (see _DeviceNodeState) -------
        # Runs after every route-to-host bail-out above: it reconciles the
        # shadow bookkeeping on the assumption that the decided upload /
        # scatter actually reaches the device this dispatch.
        ds = self._dev
        neg = self._negotiate_device_state(
            nt, node_requested, node_nzr, overlaid,
            allow_scatter=self.mesh is None or self.mesh_delta,
            pending_exists=self._pending_exists(),
            unmirrored_exists=self._unmirrored_exists(),
        )
        if neg is None and self._await_mirrors():
            # the blocked path (membership adopt / divergence repair)
            # only needs the carry to equal the shadow, which holds the
            # moment every in-flight batch has MIRRORED -- so wait for
            # the mirrors (the committer signals them; typically a few
            # ms) and renegotiate before paying a full pipeline drain
            retry = self._negotiate_device_state(
                nt, node_requested, node_nzr, overlaid,
                allow_scatter=self.mesh is None or self.mesh_delta,
                pending_exists=self._pending_exists(),
                unmirrored_exists=False,
            )
            if retry is not None:
                self.speculative_rewinds += 1
                metrics.speculative_rewinds.inc(reason="mirror_wait")
            neg = retry
        if neg is None:
            # the handshake needs an upload but the device carry is ahead
            # of the host by the in-flight batches (node churn, bind
            # failure, dead carry): land them, then redo this dispatch
            # from the fresh host state
            if self._pending_exists():
                self.speculative_rewinds += 1
                metrics.speculative_rewinds.inc(reason="drain")
            self._drain_pending()
            span.finish(routed="drain_redispatch")
            return self._dispatch_solve(
                solver_infos, pod_scheduling_cycle,
                inactive_uids=inactive_uids,
            )
        static_ok = neg["static_ok"]
        carry_ok = neg["carry_ok"]
        span.note(
            carry=(
                "delta" if carry_ok and (
                    neg["didx"].size or neg["sidx"].size
                ) else "reuse" if carry_ok else "upload"
            ),
            delta_rows=int(neg["didx"].size + neg["sidx"].size),
        )
        compress = False
        batch_load16 = 0
        if self.carry_compress_enabled:
            batch_load16 = _batch_load16(req, nzr, b)
            compress = self._compress_decision(
                neg, constrained, overlaid, node_requested, node_nzr,
                batch_load16,
            )
            if compress:
                span.note(compressed=True)
        if self.mesh is None or self.mesh_delta:
            # single-buffer upload: over the serving link every device_put
            # operand pays its own round trip (~40-90ms each); the whole
            # batch -- including a constrained batch's ~40 family count
            # tensors, which used to pay ~1s of per-leaf link round trips
            # under host CPU contention -- rides ONE int32 buffer,
            # re-sliced (and bitcast for float tensors) on device
            # (ops/assignment.py solve_packed). On a mesh the buffer
            # uploads replicated while the resident node state stays
            # SHARDED over the node axis; the delta-scatter slots apply
            # shard-locally in the sharded twin, so steady-state churn
            # costs O(DELTA_ROW_BUCKET) on the link regardless of N
            pieces = [
                ("req", req),
                ("nzr", nzr),
                ("midx", midx),
                ("active", active.astype(np.int32)),
                # on a mesh the rows ship as a separate bool operand,
                # column-sharded host-side (ops/host_masks.py) -- each
                # shard uploads only its [U, N/P] mask columns
                ("rows", mask_rows_upload(rows, self.mesh)),
            ]
            if not static_ok:
                pieces.append(("alloc", nt.allocatable))
                pieces.append(("valid", nt.valid.astype(np.int32)))
            if not carry_ok:
                if compress:
                    # cold/refresh upload with the gate engaged: the
                    # carry ships packed int16 ('h' kind, half the
                    # link bytes) and stays int16 on device
                    pieces.append(
                        ("req_state", node_requested.astype(np.int16))
                    )
                    pieces.append(("nzr_state", node_nzr.astype(np.int16)))
                else:
                    pieces.append(("req_state", node_requested))
                    pieces.append(("nzr_state", node_nzr))
            else:
                # steady state: the resident [N, R] tensors stay on
                # device; only the changed-row scatter rides the buffer
                pieces += _delta_slot_pieces(
                    nt.capacity, nt.dims.num_dims,
                    fix_rows=neg["didx"], alloc_rows=neg["sidx"],
                    node_requested=node_requested, node_nzr=node_nzr,
                    allocatable=nt.allocatable, valid=nt.valid,
                    compress=compress,
                )
            if constrained:
                from kubernetes_tpu.ops.assignment import ConstPiece

                def fam_pieces(prefix, packed_arrs, noop_arrs):
                    """Present families ride the buffer; absent ones
                    become ConstPiece markers (free on-device constants
                    instead of ~1MB of uploaded zeros/sentinels). On a
                    MESH absent families ride as real zero arrays
                    instead: every ConstPiece combo is its own layout
                    (= its own multi-second GSPMD compile), and the
                    mesh contract is ONE constrained jit signature per
                    mesh shape -- the upload cost of the noop tensors
                    is what the pre-delta mesh path always paid."""
                    if packed_arrs is not None:
                        for i, a in enumerate(packed_arrs):
                            pieces.append((f"{prefix}{i}", np.asarray(a)))
                    elif self.mesh is not None:
                        for i, a in enumerate(noop_arrs):
                            pieces.append((f"{prefix}{i}", np.asarray(a)))
                    else:
                        for i, a in enumerate(noop_arrs):
                            pieces.append(
                                (f"{prefix}{i}", ConstPiece.from_uniform(a))
                            )

                fam_pieces(
                    "sp",
                    pad_spread_tensors(spread, padded)
                    if spread is not None else None,
                    noop_spread_tensors(padded, nt.capacity),
                )
                fam_pieces(
                    "af",
                    pad_affinity_tensors(affinity, padded)
                    if affinity is not None else None,
                    noop_affinity_tensors(padded, nt.capacity),
                )
                fam_pieces(
                    "sc",
                    pad_score_tensors(score_batch, padded)
                    if score_batch is not None else None,
                    noop_score_tensors(padded, nt.capacity),
                )
            # pass None for pieces riding the buffer so the jit sees one
            # stable signature per layout (a stale device ref would fork
            # a needless compile variant)
            solve_mode = "constrained" if constrained else self.solver_mode

            def run_device(allow_pallas: bool):
                if poison_key is not None:
                    raise PoisonError(poison_key)
                inj = get_injector()
                if inj is not None:
                    hang = inj.hang_seconds_maybe(
                        FaultPoint.DEVICE_SOLVE_HANG
                    )
                    if hang > 0:
                        time.sleep(hang)
                    inj.raise_maybe(FaultPoint.DEVICE_SOLVE)
                return solve_packed(
                    pieces,
                    ds.alloc_dev if static_ok else None,
                    ds.valid_dev if static_ok else None,
                    ds.req_dev if carry_ok else None,
                    ds.nzr_dev if carry_ok else None,
                    config=self.solver_config,
                    mode=solve_mode,
                    allow_pallas=allow_pallas,
                    mesh=self.mesh,
                    compress=compress,
                )

            def run_host_greedy():
                if poison_key is not None:
                    # the malformed row poisons the host replay too (it
                    # packs from the same arrays); only the per-pod
                    # sequential oracle fails it ALONE
                    raise PoisonError(poison_key)
                a, r_out, z_out = host_greedy_assign(
                    nt.allocatable, node_requested, node_nzr, nt.valid,
                    req, nzr, rows, midx, active,
                    config=self.solver_config,
                )
                return a, r_out, z_out, None, None

            attempts = [
                (t, (lambda ap=(t == TIER_PALLAS): run_device(ap)))
                for t in self._device_tiers(
                    solve_mode, padded, nt.capacity, nt.dims.num_dims,
                    u_padded,
                )
            ]
            # the host tier needs host state that reflects EVERY
            # placement; with batches in flight the device carry is
            # ahead of node_requested, so the tier is only offered when
            # nothing is pending (exhaustion with pending batches drains
            # and redispatches from fresh host state instead)
            if not constrained and not self._pending_exists():
                attempts.append((TIER_HOST_GREEDY, run_host_greedy))
            # pre-solve carry refs: the gang quorum fixup restores these
            # to rewind a re-solved batch to its pre-batch device state
            # without a re-upload (only exact when no row fixes rode
            # this dispatch)
            carry_in = (
                (ds.req_dev, ds.nzr_dev)
                if carry_ok and not neg["didx"].size
                else None
            )
            try:
                t_solve = time.perf_counter()
                with timeline.span("solve_dispatch"):
                    tier, out = self.ladder.run(
                        attempts, label=f"batch b={b}"
                    )
                dt_solve = time.perf_counter() - t_solve
                self._stage_add("device_solve", dt_solve)
                span.stage("device_solve", dt_solve, t0=t_solve)
                if flightrecorder.trace_active():
                    # the device's own track, next to the host threads
                    flightrecorder.trace_span(
                        f"solve b={b}", t_solve, dt_solve,
                        track="device",
                        args={"batch": span.batch_id, "tier": tier}
                        if span else None,
                    )
                self._jit_watch.refresh()
            except LadderExhausted as exhaust_err:
                with self._shadow_lock:
                    ds.invalidate_carry()
                    # no jitted solve LANDED, so the booked upload /
                    # scatter never became device state: un-book the
                    # counters (a drain-and-redispatch would book the
                    # batch again). A device tier that uploaded and then
                    # failed still paid the link traffic; that cost is
                    # attributed by solves_by_tier/breaker metrics, not
                    # here -- state_uploads counts established state.
                    if carry_ok:
                        self.state_reuses -= 1
                        self.delta_rows_uploaded -= int(
                            neg["didx"].size + neg["sidx"].size
                        )
                        self.membership_row_patches -= neg["member"]
                    else:
                        self.state_uploads -= 1
                    if neg["sidx"].size or not static_ok:
                        # the alloc row patch / full static upload never
                        # reached the device (no solve ran) but the
                        # shadow already claims it: drop the resident
                        # alloc so the next dispatch re-uploads instead
                        # of trusting it
                        ds.alloc_dev = None
                        ds.valid_dev = None
                if raise_on_exhaust:
                    # bisection sub-solve: the caller owns this group's
                    # disposition (split further or isolate)
                    span.finish(routed="bisect_exhausted")
                    raise
                if self._pending_exists():
                    # in-flight batches blocked the host tier: land them
                    # (the committer's own recovery handles their
                    # failures), then redo this dispatch from fresh host
                    # state with the breakers now routing around the
                    # sick tiers
                    self._drain_pending()
                    span.finish(routed="exhausted_redispatch")
                    return self._dispatch_solve(
                        solver_infos, pod_scheduling_cycle,
                        inactive_uids=inactive_uids,
                    )
                return self._contain_exhausted_batch(
                    solver_infos, pod_scheduling_cycle, span,
                    inactive_uids,
                    poisoned=isinstance(
                        exhaust_err.__cause__, PoisonError
                    ),
                )
            assignments_dev, req_out, nzr_out, alloc_out, valid_out = out
            if tier == TIER_HOST_GREEDY:
                # the host tier solved from host state and no jitted
                # solve ran: undo any bookkeeping that assumed the
                # device saw this dispatch (incl. the link-traffic
                # counters -- no upload / row scatter actually happened)
                with self._shadow_lock:
                    if carry_ok:
                        self.delta_rows_uploaded -= int(
                            neg["didx"].size + neg["sidx"].size
                        )
                        self.membership_row_patches -= neg["member"]
                    else:
                        self.state_uploads -= 1
                    if neg["sidx"].size or not static_ok:
                        # alloc patch / full static upload never landed
                        ds.alloc_dev = None
                        ds.valid_dev = None
                    if (
                        carry_ok
                        and not neg["didx"].size
                        and not overlaid
                        and ds.req_dev is not None
                    ):
                        # the host tier was only offered with nothing in
                        # flight and a validated carry, so its input
                        # state EQUALS the device carry: scatter-add its
                        # own assignment output onto the resident state
                        # (ops/assignment.apply_assignment_delta) and
                        # keep the carry warm instead of dropping it
                        ds.req_dev, ds.nzr_dev = apply_assignment_delta(
                            ds.req_dev, ds.nzr_dev,
                            np.asarray(
                                assignments_dev, dtype=np.int32
                            ),
                            req, nzr,
                        )
                    else:
                        ds.invalidate_carry()
            else:
                # a jitted solve LANDED: the booked upload / scatter is
                # established device state -- mirror the internal
                # counters into the (monotonic) Prometheus series now,
                # when the booking is final (the host-tier / exhausted
                # branches un-book the attributes and book nothing here)
                if carry_ok:
                    if neg["didx"].size or neg["sidx"].size:
                        metrics.delta_rows_uploaded.inc(
                            int(neg["didx"].size + neg["sidx"].size)
                        )
                else:
                    metrics.state_uploads.inc()
                    if self._device_lost_at is not None:
                        self._note_device_rebuilt()
                if compress:
                    # link bytes the int16 packing kept off the wire
                    # this dispatch (half of what the int32 form ships)
                    metrics.carry_compress_bytes_saved.inc(
                        2 * DELTA_ROW_BUCKET * (nt.dims.num_dims + 2)
                        if carry_ok
                        else 2 * (node_requested.size + node_nzr.size)
                    )
                if not static_ok:
                    ds.alloc_dev, ds.valid_dev = alloc_out, valid_out
                elif neg["sidx"].size:
                    # the in-buffer scatter patched the resident alloc
                    # (and, for membership churn, the valid mask); keep
                    # the patched refs
                    ds.alloc_dev, ds.valid_dev = alloc_out, valid_out
                try:
                    assignments_dev.copy_to_host_async()
                except AttributeError:
                    pass
                if overlaid:
                    ds.invalidate_carry()
                else:
                    ds.req_dev, ds.nzr_dev = req_out, nzr_out
            span.note(tier=tier)
            return {
                "tier": tier,
                "carry_in": carry_in,
                "span": span,
                "solver_infos": list(solver_infos),
                "has_required_anti": has_required_anti,
                "has_ports": batch_ports,
                "has_scoring_terms": has_scoring_terms,
                "order": order,
                "assignments_dev": assignments_dev,
                "download": self._eager_download(assignments_dev),
                "req": req,
                "nzr": nzr,
                "b": b,
                "names": nt.names,
                "num_nodes": nt.num_nodes,
                "snapshot": snapshot,
                "cycle": pod_scheduling_cycle,
                "overlaid": overlaid,
                "solve_timer": solve_timer,
                "mask_rows": mask_rows,
                "mask_index_solved": midx,
                "load16": batch_load16,
            }

        # -- KTPU_MESH_DELTA=0 fallback: the PR-5 mesh path ----------------
        # one batched host->device transfer for everything we must
        # upload; every node-state change resolves as a counted full
        # upload (allow_scatter=False above). Kept as the escape hatch
        # for mesh shapes where the sharded-twin compile is suspect.
        to_upload = [req, nzr, rows, midx, active]
        shardings = None
        if self.mesh is not None:
            shardings = [
                self._sh_repl, self._sh_repl, self._sh_rows,
                self._sh_repl, self._sh_repl,
            ]
        if not static_ok:
            to_upload += [nt.allocatable, nt.valid]
            if shardings is not None:
                shardings += [self._sh_node2, self._sh_node1]
        if not carry_ok:
            to_upload += [node_requested, node_nzr]
            if shardings is not None:
                shardings += [self._sh_node2, self._sh_node2]
        if shardings is not None:
            uploaded = jax.device_put(tuple(to_upload), tuple(shardings))
        else:
            uploaded = jax.device_put(tuple(to_upload))
        it = iter(uploaded)
        req_d, nzr_d, rows_d, midx_d, active_d = (
            next(it), next(it), next(it), next(it), next(it)
        )
        if not static_ok:
            ds.alloc_dev, ds.valid_dev = next(it), next(it)
        if not carry_ok:
            # shadow bookkeeping already reconciled by the handshake
            # (_negotiate_device_state); the mesh path has no row-scatter
            # variant, so every change resolves as a counted full upload
            req_state_d, nzr_state_d = next(it), next(it)
        else:
            req_state_d, nzr_state_d = ds.req_dev, ds.nzr_dev

        common_args = (
            ds.alloc_dev, req_state_d, nzr_state_d, ds.valid_dev,
            req_d, nzr_d, rows_d, midx_d, active_d,
        )
        try:
            if poison_key is not None:
                raise PoisonError(poison_key)
            inj = get_injector()
            if inj is not None:
                inj.raise_maybe(FaultPoint.DEVICE_SOLVE)
            t_solve = time.perf_counter()
            assignments_dev, req_out, nzr_out = self._mesh_solve(
                common_args, spread, affinity, score_batch, padded, nt
            )
            dt_solve = time.perf_counter() - t_solve
            self._stage_add("device_solve", dt_solve)
            span.stage("device_solve", dt_solve, t0=t_solve)
            self._jit_watch.refresh()
        except Exception as mesh_err:
            with self._shadow_lock:
                ds.invalidate_carry()
            if raise_on_exhaust:
                # bisection sub-solve on the legacy mesh path: the
                # caller owns the group's disposition
                span.finish(routed="bisect_exhausted")
                raise
            self._drain_pending()
            if isinstance(mesh_err, PoisonError):
                # the legacy mesh path has no ladder, but a typed
                # poison must still reach containment instead of
                # storming the sequential floor on every retry
                return self._contain_exhausted_batch(
                    solver_infos, pod_scheduling_cycle, span,
                    inactive_uids, poisoned=True,
                )
            # untyped persistent mesh failure (ROADMAP item 6a): the
            # FIRST fall for this batch keeps the transient-tolerant
            # sequential floor below, but an identical batch falling
            # again is a crash loop -- route it through the containment
            # disposition (which books exhausted_crashloops and forces
            # bisection / quarantine) instead of storming the floor on
            # every retry
            if self._note_exhaust_sig(solver_infos):
                logger.warning(
                    "legacy mesh solve failed repeatedly for the same "
                    "%d-pod batch; engaging containment",
                    len(solver_infos),
                )
                return self._contain_exhausted_batch(
                    solver_infos, pod_scheduling_cycle, span,
                    inactive_uids, poisoned=False,
                )
            # otherwise: no pallas/host tier distinction -- a failed
            # sharded solve steps straight down to the sequential oracle
            logger.exception("mesh solve failed; sequential fallback")
            metrics.solver_fallbacks.inc(
                tier=TIER_SEQUENTIAL, reason="mesh_solve_error"
            )
            flightrecorder.mark(
                "fallback", tier=TIER_SEQUENTIAL,
                reason="mesh_solve_error",
            )
            span.finish(tier=TIER_SEQUENTIAL, routed="mesh_solve_error")
            self.ladder.record_sequential(len(solver_infos))
            for pi in solver_infos:
                self.pods_fallback += 1
                self.attempt_schedule(pi)
            return None
        if not carry_ok:
            metrics.state_uploads.inc()
            if self._device_lost_at is not None:
                self._note_device_rebuilt()
        # start the result transfer now so it overlaps host commit work
        try:
            assignments_dev.copy_to_host_async()
        except AttributeError:
            pass
        if overlaid:
            # nominee reservations are virtual: the post-scan state
            # includes them, so it must not become the carry
            ds.invalidate_carry()
        else:
            ds.req_dev, ds.nzr_dev = req_out, nzr_out

        span.note(tier=TIER_XLA)
        return {
            "tier": TIER_XLA,  # mesh solves are plain XLA lowerings
            "carry_in": (
                (req_state_d, nzr_state_d) if carry_ok else None
            ),
            "span": span,
            "download": self._eager_download(assignments_dev),
            # copy: the caller's list is cleared after dispatch returns
            "solver_infos": list(solver_infos),
            "has_required_anti": has_required_anti,
            "has_ports": batch_ports,
            "has_scoring_terms": has_scoring_terms,
            "order": order,
            "assignments_dev": assignments_dev,
            "req": req,
            "nzr": nzr,
            "b": b,
            "names": nt.names,
            "num_nodes": nt.num_nodes,
            "snapshot": snapshot,
            "cycle": pod_scheduling_cycle,
            "overlaid": overlaid,
            "solve_timer": solve_timer,
            "mask_rows": mask_rows,
            "mask_index_solved": midx,
        }

    # -- blast-radius containment (robustness/containment.py) ----------------

    def _note_exhaust_sig(self, solver_infos: List[PodInfo]) -> bool:
        """Track the exhausted-batch uid signature; True when the SAME
        batch has now fallen whole at least twice in a row (a retry
        storm, not a transient). Shared by the ladder path and the
        legacy KTPU_MESH_DELTA=0 mesh path (ROADMAP item 6a: an untyped
        persistent mesh failure used to fall whole to the sequential
        floor on EVERY retry without ever tripping the detector)."""
        sig = frozenset(
            pi.pod.metadata.uid for pi in solver_infos
        )
        if sig and sig == self._last_exhaust_sig:
            self._exhaust_repeats += 1
        else:
            self._last_exhaust_sig = sig
            self._exhaust_repeats = 1
        return self._exhaust_repeats >= 2

    def _contain_exhausted_batch(
        self, solver_infos: List[PodInfo], pod_scheduling_cycle: int,
        span, inactive_uids, poisoned: bool = False,
    ):
        """Disposition of a ladder-exhausted batch with nothing in
        flight. Tracks the crash-loop signature (an identical batch
        exhausting twice in a row is a retry storm, not a transient),
        then: multi-pod batches take the bisection search, a
        crash-looping singleton goes straight to quarantine, and
        everything else (containment off, gang batches, first-time
        singletons) keeps the sequential-floor fallback."""
        crashloop = self._note_exhaust_sig(solver_infos)
        if crashloop:
            metrics.exhausted_crashloops.inc()
            flightrecorder.mark(
                "exhausted_crashloop", pods=len(solver_infos),
                repeats=self._exhaust_repeats,
            )
            logger.warning(
                "ladder_exhausted crash loop: the same %d-pod batch "
                "exhausted %d times in a row; engaging containment",
                len(solver_infos), self._exhaust_repeats,
            )
        cc = self.containment_config
        gang = any(
            pi.pod.metadata.labels.get(POD_GROUP_LABEL)
            for pi in solver_infos
        )
        if not cc.enabled or inactive_uids or gang:
            # gang batches never bisect (a split would break the
            # all-or-nothing quorum semantics); the sequential path
            # keeps full correctness for them
            return self._exhausted_sequential(
                solver_infos, pod_scheduling_cycle, span
            )
        if len(solver_infos) == 1:
            if crashloop or poisoned:
                # the singleton itself is the poison (typed cause, or
                # the same batch exhausting repeatedly): no batch left
                # to protect, but redispatching it forever is the
                # retry storm -- strike it into quarantine
                span.finish(routed="quarantine")
                self._quarantine_isolated(
                    solver_infos[0],
                    reason="poison" if poisoned else "crashloop",
                )
                return None
            # first exhaustion of a singleton may be transient (breaker
            # cool-offs, a blocked host tier): one sequential attempt
            return self._exhausted_sequential(
                solver_infos, pod_scheduling_cycle, span
            )
        span.finish(routed="bisect")
        self._bisect_batch(
            solver_infos, pod_scheduling_cycle, force=crashloop
        )
        return None

    def _exhausted_sequential(
        self, solver_infos: List[PodInfo], pod_scheduling_cycle: int,
        span,
    ):
        """The pre-containment floor: the whole batch runs the per-pod
        sequential oracle."""
        metrics.solver_fallbacks.inc(
            tier=TIER_SEQUENTIAL, reason="ladder_exhausted"
        )
        flightrecorder.mark(
            "fallback", tier=TIER_SEQUENTIAL,
            reason="ladder_exhausted",
        )
        span.finish(
            tier=TIER_SEQUENTIAL, routed="ladder_exhausted"
        )
        self.ladder.record_sequential(len(solver_infos))
        logger.warning(
            "solver ladder exhausted; %d pods take the "
            "sequential oracle path", len(solver_infos),
        )
        for pi in solver_infos:
            self.pods_fallback += 1
            self.attempt_schedule(pi)
        return None

    def _bisect_batch(
        self, solver_infos: List[PodInfo], pod_scheduling_cycle: int,
        force: bool = False,
    ) -> None:
        """O(log B) poison isolation: split the exhausted batch and
        re-solve each half synchronously on the already-warm pad rungs
        (sub-batches pad to the smallest warmed rung that fits, so no
        sub-solve compiles). Halves that solve COMMIT at their normal
        device tier -- the healthy pods' blast radius ends here; halves
        that exhaust again split further until the offenders are
        singletons, which go to the quarantine ledger.

        Systemic-failure guard: ``bisect_abort_after`` isolated
        singletons with ZERO successful sub-solves means every subset
        fails -- a sick device, not a poison signature -- and the run
        aborts to the sequential floor. ``force`` (set by the
        crash-loop detector) disables the guard: a batch that already
        exhausted repeatedly must not keep redispatching."""
        cc = self.containment_config
        t0 = time.perf_counter()
        self.bisections += 1
        metrics.bisections.inc()
        flightrecorder.mark(
            "bisect_start", pods=len(solver_infos), force=force
        )
        mid = len(solver_infos) // 2
        work: "collections.deque" = collections.deque(
            [solver_infos[:mid], solver_infos[mid:]]
        )
        # (pod_info, typed_poison) -- a singleton isolated by a TYPED
        # PoisonError always quarantines; untyped isolations are only
        # trusted once some sibling sub-solve succeeded (else they are
        # indistinguishable from a systemic device failure)
        isolated: List[Tuple[PodInfo, bool]] = []
        done_uids: set = set()
        successes = 0
        subsolves = 0
        aborted = False

        def untyped_isolated() -> int:
            return sum(1 for _pi, typed in isolated if not typed)

        while work:
            if (
                not force
                and successes == 0
                and untyped_isolated() >= cc.bisect_abort_after
            ):
                aborted = True
                break
            group = list(work.popleft())
            subsolves += 1
            metrics.bisect_subsolves.inc()
            try:
                pending = self._dispatch_solve(
                    group, pod_scheduling_cycle, raise_on_exhaust=True
                )
            except SchedulerCrashed:
                raise
            except Exception as sub_err:  # noqa: BLE001 - split again
                if len(group) == 1:
                    # LadderExhausted-from-PoisonError (ladder paths)
                    # or a bare PoisonError (legacy mesh path)
                    typed = isinstance(sub_err, PoisonError) or (
                        isinstance(sub_err, LadderExhausted)
                        and isinstance(sub_err.__cause__, PoisonError)
                    )
                    isolated.append((group[0], typed))
                    flightrecorder.mark(
                        "bisect_isolated",
                        pod=group[0].pod.metadata.uid,
                        typed=typed,
                    )
                else:
                    m = len(group) // 2
                    # left-first DFS: committed groups land in the
                    # original pod order, so healthy placements match
                    # the no-poison batch bit-for-bit
                    work.appendleft(group[m:])
                    work.appendleft(group[:m])
                continue
            if pending is None:
                # the dispatch itself routed the group (envelope bails,
                # nested containment): those paths already disposed of
                # every pod
                successes += 1
                done_uids.update(
                    pi.pod.metadata.uid for pi in group
                )
                continue
            try:
                self._complete_solve(pending)
            except SchedulerCrashed:
                raise
            except Exception:  # noqa: BLE001 - download/commit failure
                # not an exhaustion: the standard recovery requeues the
                # group (a genuinely poisoned member re-trips
                # containment on its next pass)
                logger.exception("bisect sub-solve completion failed")
                self._recover_failed_batch(pending)
                done_uids.update(
                    pi.pod.metadata.uid for pi in group
                )
                continue
            successes += 1
            done_uids.update(pi.pod.metadata.uid for pi in group)
        dt_ms = (time.perf_counter() - t0) * 1000.0
        # post-loop systemic check too: a batch SMALLER than the abort
        # threshold can drain the work deque with zero successes and
        # only untyped isolations -- that is still "every subset
        # failed", not a poison signature
        if (
            not aborted
            and not force
            and successes == 0
            and untyped_isolated() > 0
        ):
            aborted = True
        if aborted:
            metrics.bisect_aborts.inc()
            # typed-poison singletons quarantine even on an aborted
            # run (the cause is attributable); everything else --
            # untyped isolations and unprocessed work -- takes the
            # sequential floor
            typed_pis = [pi for pi, typed in isolated if typed]
            for pi in typed_pis:
                done_uids.add(pi.pod.metadata.uid)
            remaining = [
                pi for pi in solver_infos
                if pi.pod.metadata.uid not in done_uids
            ]
            flightrecorder.mark(
                "bisect_abort", pods=len(solver_infos),
                isolated=len(isolated), subsolves=subsolves,
                remaining=len(remaining), ms=round(dt_ms, 3),
            )
            logger.warning(
                "bisection aborted after %d failed sub-solves with no "
                "success (systemic failure); %d pods take the "
                "sequential path", subsolves, len(remaining),
            )
            for pi in typed_pis:
                self._quarantine_isolated(pi, reason="poison")
            self.ladder.record_sequential(len(remaining))
            for pi in remaining:
                self.pods_fallback += 1
                self.attempt_schedule(pi)
            return
        flightrecorder.mark(
            "bisect_done", pods=len(solver_infos),
            isolated=len(isolated), subsolves=subsolves,
            ms=round(dt_ms, 3),
        )
        for pi, typed in isolated:
            self._quarantine_isolated(
                pi, reason="poison" if typed else "bisect"
            )

    def _quarantine_isolated(self, pi: PodInfo, reason: str) -> None:
        """Route one isolated pod through the quarantine ledger and
        surface the event on the pod (Warning event; the PARK
        additionally writes the typed PodQuarantined condition)."""
        # a quarantined pod holds no capacity: its quota charge (taken
        # at pop) must not pin the namespace ledger while it sits out
        self._quota_refund(pi.pod, "quarantine")
        self.pods_quarantined += 1
        disposition = self.quarantine.isolate(pi, reason=reason)
        prof = self.profiles.get(pi.pod.spec.scheduler_name)
        if prof is not None:
            try:
                prof.recorder.eventf(
                    pi.pod, "Warning", "Quarantined",
                    f"pod isolated by blast-radius containment "
                    f"({reason}); disposition: {disposition}",
                )
            except Exception:  # noqa: BLE001 - events are best-effort
                logger.exception(
                    "quarantine event for %s", pi.pod.key()
                )

    # -- carry integrity audit + device-loss rebuild -------------------------

    def audit_carry(self) -> str:
        """One carry-integrity sweep: checksum the device-resident
        req/nzr (and alloc/valid when resident) against the host shadow
        with two cheap on-device int32 reductions per array; the full
        [N, R] download happens only on mismatch. Corruption heals
        through the counted-upload path (carry drop -> next dispatch
        re-uploads), never silently. Runs from the
        ControlPlaneReconciler sweep; safe to call from any thread.

        Returns the disposition: "idle" (nothing resident), "busy"
        (in-flight state with no auditable snapshot), "raced" (a
        dispatch/commit moved the state mid-sweep), "clean", or
        "mismatch" (healed).

        A SATURATED pipeline no longer defers the audit to quiescence:
        while batches are in flight, the FIRST UNMIRRORED pending
        record's ``carry_in`` refs are audited instead of the live
        carry. Those refs are immutable device arrays (dispatch
        REASSIGNS ``ds.req_dev``, never mutates it) snapshotting the
        device state that record's solve consumed -- which must equal
        the host shadows exactly until that record's own commit passes
        the shadow-mutation point (the mirror, flagged ``mirrored``
        under this lock), because the committer lands batches in FIFO
        order and the req/nzr shadows mutate ONLY at the mirror. The
        coarse ``committing`` flag is deliberately NOT the gate: the
        committer raises it the instant it grabs the head, long before
        the mirror (the whole device download sits between), and gating
        on it would answer "busy" for nearly every sweep under
        saturation. Staleness is therefore bounded by pipeline depth,
        not by the arrival rate ever pausing: corruption stamped into
        the newest resident carry is seen when the batch that consumed
        it reaches the front of the unmirrored window, at most
        MAX_INFLIGHT commits later. Only req/nzr are audited under
        load (the alloc row patch CAN land on the resident alloc while
        batches are in flight); "busy" remains only for windows whose
        front record has no carry reuse (cold uploads, row-fix
        dispatches) or whose every record has already mirrored."""
        ds = self._dev
        under_load = False
        head = None
        seq = 0
        alloc_dev = valid_dev = None
        shadow_ref = None
        with self._shadow_lock:
            if ds.req_dev is None or ds.req_shadow is None:
                metrics.carry_audit_sweeps.inc(disposition="idle")
                return "idle"
            if self._pending_exists():
                head = self._pending_first_unmirrored()
                carry = (
                    head.get("carry_in") if head is not None else None
                )
                if head is None or carry is None:
                    metrics.carry_audit_sweeps.inc(disposition="busy")
                    return "busy"
                under_load = True
                shadow_ref = ds.req_shadow
                req_dev, nzr_dev = carry
                host = {
                    "req": _audit_checksum_host(ds.req_shadow),
                    "nzr": _audit_checksum_host(ds.nzr_shadow),
                }
            else:
                seq = self._dispatch_seq
                req_dev, nzr_dev = ds.req_dev, ds.nzr_dev
                alloc_dev, valid_dev = ds.alloc_dev, ds.valid_dev
                # host checksums under the lock: the shadows mutate in
                # place at commit time
                host = {
                    "req": _audit_checksum_host(ds.req_shadow),
                    "nzr": _audit_checksum_host(ds.nzr_shadow),
                }
                if alloc_dev is not None and ds.alloc_shadow is not None:
                    host["alloc"] = _audit_checksum_host(ds.alloc_shadow)
                if valid_dev is not None and ds.valid_shadow is not None:
                    host["valid"] = _audit_checksum_host(ds.valid_shadow)
        self.carry_audits += 1
        # device reductions OUTSIDE the lock (the refs are immutable
        # arrays; a racing dispatch reassigns, never mutates)
        dev_handles = {"req": _audit_checksum_dev(req_dev),
                       "nzr": _audit_checksum_dev(nzr_dev)}
        if "alloc" in host:
            dev_handles["alloc"] = _audit_checksum_dev(alloc_dev)
        if "valid" in host:
            dev_handles["valid"] = _audit_checksum_dev(valid_dev)
        dev = {
            name: (int(np.asarray(s)), int(np.asarray(ws)))
            for name, (s, ws) in dev_handles.items()
        }
        with self._shadow_lock:
            if under_load:
                # the snapshot is comparable until OUR record's mirror
                # lands (the only in-order in-place writer of the
                # req/nzr shadows) or a cold upload reassigns the
                # shadow arrays -- both happen under this lock, so
                # either landing mid-reduction is caught here. The
                # coarse ``committing`` flag is irrelevant: the whole
                # download phase is audit-safe.
                raced = (
                    head.get("mirrored")
                    or ds.req_shadow is not shadow_ref
                )
            else:
                raced = (
                    self._dispatch_seq != seq
                    or self._pending_exists()
                    or ds.req_dev is not req_dev
                )
            if raced:
                metrics.carry_audit_sweeps.inc(disposition="raced")
                return "raced"
            mismatched = [n for n in dev if dev[n] != host[n]]
            if not mismatched:
                metrics.carry_audit_sweeps.inc(disposition="clean")
                return "clean"
            # full compare only on mismatch: name the divergent rows
            # for the flight record, then heal
            rows: List[int] = []
            try:
                if "req" in mismatched:
                    diff = ~np.all(
                        np.asarray(req_dev) == ds.req_shadow, axis=1
                    )
                    rows = np.flatnonzero(diff)[:16].tolist()
                elif "nzr" in mismatched:
                    diff = ~np.all(
                        np.asarray(nzr_dev) == ds.nzr_shadow, axis=1
                    )
                    rows = np.flatnonzero(diff)[:16].tolist()
            except Exception:  # noqa: BLE001 - row detail is best-effort
                logger.exception("carry audit row compare failed")
            for name in mismatched:
                metrics.carry_audit_mismatches.inc(array=name)
            flightrecorder.mark(
                "carry_audit", arrays=",".join(sorted(mismatched)),
                rows=rows, in_flight=len(self._pending_q),
            )
            if "req" in mismatched or "nzr" in mismatched:
                ds.invalidate_carry()
            if "alloc" in mismatched or "valid" in mismatched:
                ds.alloc_dev = None
                ds.valid_dev = None
            metrics.carry_audit_heals.inc()
            self.carry_audit_heals += 1
        metrics.carry_audit_sweeps.inc(disposition="mismatch")
        logger.warning(
            "carry integrity audit: device-resident %s diverged from "
            "the host shadow (rows %s); healed via the counted-upload "
            "path", ",".join(sorted(mismatched)), rows,
        )
        return "mismatch"

    def _corrupt_carry_row(self) -> None:
        """CARRY_CORRUPT fired: flip bits in one device-resident carry
        row WITHOUT touching the host shadow -- silent corruption only
        the integrity audit can see (the generation handshake compares
        host state against the shadow, never the device)."""
        inj = get_injector()
        with self._shadow_lock:
            ds = self._dev
            if ds.req_dev is None:
                return
            n = int(ds.req_dev.shape[0])
            if n == 0:
                return
            fired = (
                inj.fired_count(FaultPoint.CARRY_CORRUPT)
                if inj is not None else 1
            )
            row = (fired * 131) % n
            ds.req_dev = ds.req_dev.at[row, 0].add(1 << 20)
        flightrecorder.mark("carry_corrupt", row=row)
        logger.warning(
            "injected carry corruption on resident row %d", row
        )

    def _on_device_lost(self) -> None:
        """DEVICE_LOST fired: every device-resident buffer is gone.
        Drop all resident state + shadows, flag the in-flight batches
        (their results are garbage; the committer's recovery requeues
        their pods through the PR-1 machinery), drain, and let the
        current dispatch rebuild from the host cache through the
        existing cold-upload path. Detection -> rebuilt is metered into
        ``scheduler_tpu_device_rebuild_ms``."""
        self._device_lost_at = time.perf_counter()
        metrics.device_lost_events.inc()
        metrics.degraded_health.set(1, reason="device_lost")
        flightrecorder.mark("device_lost")
        logger.error(
            "device lost: dropping resident state, requeueing "
            "in-flight batches, rebuilding from the host cache"
        )
        with self._pending_cv:
            for p in self._pending_q:
                p["device_lost"] = True
        with self._shadow_lock:
            ds = self._dev
            ds.alloc_dev = None
            ds.valid_dev = None
            ds.alloc_shadow = None
            ds.valid_shadow = None
            ds.layout_epoch = -1
            ds.invalidate_carry()
        self._drain_pending()

    def _note_device_rebuilt(self) -> None:
        """The first full upload after a device loss landed under a
        jitted solve: the resident state is rebuilt."""
        at = self._device_lost_at
        if at is None:
            return
        self._device_lost_at = None
        dt_ms = (time.perf_counter() - at) * 1000.0
        metrics.device_rebuild_ms.observe(dt_ms)
        metrics.degraded_health.set(0, reason="device_lost")
        flightrecorder.mark("device_rebuilt", ms=round(dt_ms, 3))
        logger.warning(
            "device state rebuilt from host cache %.1fms after loss",
            dt_ms,
        )

    @staticmethod
    def _eager_download(assignments_dev):
        """Start the device->host result copy at dispatch time (host
        tiers already hand back numpy -- nothing to transfer)."""
        if isinstance(assignments_dev, np.ndarray):
            return None
        if not _EAGER_DOWNLOAD_OK:
            # a starved host (<=2 cores) has no spare core to run the
            # copy thread: the overlap becomes pure GIL contention with
            # the dispatcher/committer (measured ~10% slower end-to-end)
            return None
        return _EagerDownload(assignments_dev)

    def _mesh_solve(
        self, common_args, spread, affinity, score_batch, padded, nt
    ):
        """One sharded solve on the mesh (unconstrained or constrained);
        factored out of _dispatch_solve so the caller can guard it."""
        if spread is None and affinity is None and score_batch is None:
            solver = (
                sinkhorn_assign
                if self.solver_mode == "sinkhorn"
                else greedy_assign_compact
            )
            return solver(*common_args, config=self.solver_config)
        # the packers saw the pods already in solve order
        if spread is not None:
            sp_tensors = pad_spread_tensors(spread, padded)
        else:
            sp_tensors = noop_spread_tensors(padded, nt.capacity)
        if affinity is not None:
            af_tensors = pad_affinity_tensors(affinity, padded)
        else:
            af_tensors = noop_affinity_tensors(padded, nt.capacity)
        if score_batch is not None:
            sc_tensors = pad_score_tensors(score_batch, padded)
        else:
            sc_tensors = noop_score_tensors(padded, nt.capacity)
        # common_args carries (mask_rows, mask_index) in compact form;
        # the constrained kernel takes the same layout
        if self.mesh is not None:
            # constraint tensors are small: replicate on the mesh
            sp_dev, af_dev, sc_dev = jax.device_put(
                (sp_tensors, af_tensors, sc_tensors), self._sh_repl
            )
        else:
            sp_dev, af_dev, sc_dev = jax.device_put(
                (sp_tensors, af_tensors, sc_tensors)
            )
        return greedy_assign_constrained(
            *common_args, tuple(sp_dev), tuple(af_dev), tuple(sc_dev),
            config=self.solver_config,
        )

    def _complete_solve(self, p) -> None:
        """Download the assignments, mirror the scan's node-state deltas
        into the host shadow (same int32 arithmetic), then run the batched
        commit pipeline.

        The download is the other blocking device interaction (a wedged
        serving link hangs np.asarray forever), so it runs under the same
        wall-clock watchdog as the solve, and the result is validated
        before it drives commits: garbage indices from a sick device
        (NaN-score argmax artifacts) must degrade, not bind pods to
        phantom nodes. Failures raise; the callers route the batch
        through _recover_failed_batch (requeue, never strand)."""
        if p.get("device_lost"):
            # the device died with this batch in flight: its result
            # buffers are gone/garbage. Raise so the caller's recovery
            # requeues every pod (the PR-1 machinery); the carry was
            # already dropped by _on_device_lost.
            sp = p.get("span") or flightrecorder.NULL_SPAN
            sp.finish(routed="device_lost")
            raise RuntimeError(
                "device lost with this batch in flight; requeueing"
            )
        tier = p.get("tier", TIER_XLA)
        breaker = self.ladder.breakers.get(tier)
        timeout = (
            self.ladder.config.solve_timeout_seconds
            if tier in (TIER_PALLAS, TIER_XLA)
            and self.ladder.config.enabled
            else 0.0
        )

        def download():
            eager = p.get("download")
            if eager is not None:
                # copy already in flight since dispatch; await it
                return eager.result()
            return np.asarray(p["assignments_dev"])

        fspan = p.get("span") or flightrecorder.NULL_SPAN
        try:
            t_dl = time.perf_counter()
            with timeline.span("download"):
                assignments = self.ladder.watchdog.call(
                    download, timeout, tier=tier
                )
            dt_dl = time.perf_counter() - t_dl
            self._stage_add("download", dt_dl)
            fspan.stage("download", dt_dl, t0=t_dl)
        except SolveTimeout:
            if breaker is not None:
                breaker.force_open()
            metrics.solver_fallbacks.inc(
                tier=TIER_SEQUENTIAL, reason=f"{tier}_download_timeout"
            )
            flightrecorder.mark(
                "fallback", tier=TIER_SEQUENTIAL,
                reason=f"{tier}_download_timeout",
            )
            fspan.finish(routed="download_timeout")
            raise
        except Exception:
            if breaker is not None:
                breaker.record_failure()
            raise
        inj = get_injector()
        if inj is not None:
            assignments = inj.corrupt_assignments_maybe(
                FaultPoint.SOLVE_GARBAGE, assignments
            )
        head = assignments[: p["b"]]
        if head.size and (
            (head < NO_NODE).any() or (head >= len(p["names"])).any()
        ):
            # out-of-range node indices: the solve result is garbage
            if breaker is not None:
                breaker.record_failure()
            metrics.solver_fallbacks.inc(
                tier=TIER_SEQUENTIAL, reason=f"{tier}_garbage_result"
            )
            flightrecorder.mark(
                "fallback", tier=TIER_SEQUENTIAL,
                reason=f"{tier}_garbage_result",
            )
            fspan.finish(routed="garbage_result")
            raise RuntimeError(
                f"solve on tier {tier!r} returned out-of-range "
                f"assignments; discarding the batch result"
            )
        p["solve_timer"].observe()
        b = p["b"]
        metrics.batch_size.observe(b)
        ds = self._dev
        with self._shadow_lock:
            # the audit race-detector: a commit moving the shadow (or
            # landing a batch) invalidates any checksum window spanning
            # this moment. ``mirrored`` marks THIS record as past the
            # shadow-mutation point -- the under-load audit compares the
            # first unmirrored record's carry_in against the shadows,
            # so the flag must flip under the same lock as the mirror.
            self._dispatch_seq += 1
            p["mirrored"] = True
            if not p["overlaid"] and ds.req_shadow is not None:
                # mirror the batch's own placements into the running
                # expectation (same int32 arithmetic as the scan carry)
                # and remember the per-row delta: the dispatcher's
                # handshake subtracts it while the host cache still
                # trails this commit. O(B*R) in-place -- the retired
                # shadow_gens ring copied the full [N, R] per batch.
                # The compact+scatter hot loop runs in native
                # _hotpath.c (mirror_scatter; numpy twin behind
                # KTPU_NATIVE_INGEST=0, differentially tested).
                delta = _mirror_scatter(
                    assignments, b, p["req"], p["nzr"],
                    ds.req_shadow, ds.nzr_shadow,
                )
                if delta is not None:
                    ds.pending_deltas.append(delta)
        # wake dispatchers parked in _await_mirrors at MIRROR time: the
        # commit/bind API transactions below can be hundreds of ms away,
        # and the speculative renegotiation only needs the mirror
        with self._pending_cv:
            self._pending_cv.notify_all()
        if inj is not None and inj.should_fire(FaultPoint.CARRY_CORRUPT):
            self._corrupt_carry_row()
        t_commit = time.perf_counter()
        with timeline.span("commit_batch"):
            self._commit_batch(
                p["solver_infos"], p["order"], assignments, p["names"],
                p["num_nodes"], p["snapshot"], p["cycle"],
                mask_info=(p.get("mask_rows"), p.get("mask_index_solved")),
                gang_failed_uids=p.get("gang_failed_uids"),
                span=fspan,
            )
        dt_commit = time.perf_counter() - t_commit
        self._stage_add("commit", dt_commit)
        fspan.stage("commit", dt_commit, t0=t_commit)
        if flightrecorder.trace_active():
            # the committer's own named track: in the Perfetto artifact
            # this span overlaps the "device" track's next solve span,
            # making the solve/commit pipeline overlap visible
            flightrecorder.trace_span(
                f"commit b={b}", t_commit, dt_commit,
                track="committer",
                args={"batch": getattr(fspan, "batch_id", None)},
            )
        fspan.finish()
        if (
            self._prewarm_next_commit
            and not self._deferred_preempt
            and self.preemptor is not None
        ):
            # the wave's preemptors just bound: refresh the victim pack
            # in the background so the next contention burst finds it
            # (and its device upload) warm
            self._prewarm_next_commit = False
            self.preemptor.prewarm_pack_async()

    # -- batched commit ------------------------------------------------------

    def _commit_batch(
        self,
        solver_infos: List[PodInfo],
        order: np.ndarray,
        assignments: np.ndarray,
        names: List[str],
        num_nodes: int,
        snapshot,
        pod_scheduling_cycle: int,
        mask_info=None,
        gang_failed_uids=None,
        span=None,
    ) -> None:
        """Post-solve pipeline for the whole batch: Reserve -> assume ->
        Permit (scheduler.go:615-660 semantics preserved), then ONE async
        binding task that commits every default-binder pod in a single
        bulk transaction; non-default binds (extenders, custom bind
        plugins, Permit waiters) take the per-pod binding cycle.

        Pods for which every Reserve/Permit plugin is a declared no-op
        (Framework.plugins_relevant) skip the per-pod plugin pipeline and
        are assumed in one bulk cache transaction -- the batch commit is
        otherwise the profile-run hot loop of the 10k burst."""
        b = len(solver_infos)
        if span is None:
            span = flightrecorder.NULL_SPAN
        # schedule_batch flushes at profile boundaries, so the whole batch
        # shares one profile (batch.py:242)
        prof = self.profiles.get(solver_infos[0].pod.spec.scheduler_name)
        if prof is None:
            logger.error(
                "no profile for %s", solver_infos[0].pod.key()
            )
            return
        extenders = self.algorithm.extenders
        bulk_ok = (
            prof.uses_default_binder_only() and self._bind_pool is not None
        )
        # hoisted out of the per-pod loop: binder extenders (normally
        # none) and the relevance tables (empty table =>
        # plugins_relevant is False for every pod, no call needed)
        binder_extenders = [e for e in extenders if e.is_binder()]
        reserve_maybe = prof.relevance_entries("reserve")
        permit_maybe = prof.relevance_entries("permit")

        plain_pis: List[PodInfo] = []  # placed pods on the bulk path ...
        clones: List = []  # ... their assumed clones ...
        hosts: List[str] = []  # ... and target nodes (parallel lists)
        slow: List[Tuple[PodInfo, int, int]] = []  # (pod_info, choice, k)

        # -- fused fast path: when no per-pod gate can fire (default
        # binder only, no gang masking, no binder extenders, and no
        # reserve/permit plugin relevant to ANY pod in the batch -- one
        # any() probe instead of three checks per pod), the whole
        # classification collapses to numpy: one stable argsort over the
        # assignment row splits NO_NODE from placed AND groups the
        # placed slots by target node (the grouped order feeds the
        # cache's per-node bulk assume), and one native pass
        # (commit_gather) gathers PodInfos + assumed clones + hosts.
        fast = bulk_ok and not gang_failed_uids and not binder_extenders
        if fast and (reserve_maybe or permit_maybe):
            fast = not any(
                (
                    reserve_maybe
                    and prof.plugins_relevant("reserve", pi.pod)
                )
                or (
                    permit_maybe
                    and prof.plugins_relevant("permit", pi.pod)
                )
                for pi in solver_infos
            )
        if fast:
            with timeline.span("commit.gather"):
                head = np.asarray(assignments[:b])
                grp = np.argsort(head, kind="stable")
                n_unplaced = int((head == NO_NODE).sum())
                placed = grp[n_unplaced:]
                order_np = np.asarray(order)
                order2 = order_np[placed].tolist()
                assign2 = head[placed].tolist()
                gather = (
                    _commit_gather
                    if _commit_gather is not None
                    else _commit_gather_py
                )
                plain_pis, clones, hosts = gather(
                    solver_infos, order2, assign2,
                    names if isinstance(names, list) else list(names),
                )
            if n_unplaced:
                slow = [
                    (solver_infos[int(order_np[k])], NO_NODE, k)
                    for k in grp[:n_unplaced].tolist()
                ]
        else:
            # numpy scalar -> int conversion in one C pass each (only
            # the per-pod loop reads them)
            order_l = order.tolist()
            assign_l = assignments.tolist()
            plain: List[Tuple[PodInfo, str]] = []  # (pod_info, host)
            for k in range(b):
                pi = solver_infos[order_l[k]]
                choice = assign_l[k]
                if (
                    gang_failed_uids
                    and pi.pod.metadata.uid in gang_failed_uids
                ):
                    # quorum-masked gang member: no placement, no
                    # preemption (the group chose not to place; a
                    # PodGroupMemberAdd wakeup retries once the group
                    # can assemble)
                    metrics.schedule_attempts.inc(result="unschedulable")
                    span.bump("gang_masked")
                    self.record_scheduling_failure(
                        prof, pi,
                        "pod group cannot reach minMember this cycle",
                        "Unschedulable", "", pod_scheduling_cycle,
                    )
                    self.pods_solved_on_device += 1
                    continue
                if choice == NO_NODE:
                    slow.append((pi, choice, k))
                    continue
                pod = pi.pod
                if (
                    bulk_ok
                    and not (
                        reserve_maybe
                        and prof.plugins_relevant("reserve", pod)
                    )
                    and not (
                        permit_maybe
                        and prof.plugins_relevant("permit", pod)
                    )
                    and not (
                        binder_extenders
                        and any(
                            e.is_interested(pod) for e in binder_extenders
                        )
                    )
                ):
                    plain.append((pi, names[choice]))
                else:
                    slow.append((pi, choice, k))
            if plain:
                with timeline.span("commit.clone"):
                    if _assume_clones is not None:
                        clones = _assume_clones(
                            [pi.pod for pi, _ in plain],
                            [host for _, host in plain],
                        )
                    else:
                        clones = []
                        for pi, host in plain:
                            assumed = pi.pod.assumed_clone()
                            assumed.spec.node_name = host
                            clones.append(assumed)
                plain_pis = [pi for pi, _ in plain]
                hosts = [host for _, host in plain]

        bulk: List[Tuple] = []
        deferred: List[Tuple] = []  # sync-mode Permit waiters
        if plain_pis:
            with timeline.span("commit.assume"):
                # on the fast path the argsort grouped the clones by
                # target node, so the cache lands them as per-node runs
                # (one node lookup + one generation bump per run)
                errs = self.cache.assume_pods(clones)
            self.queue.delete_nominated_pods_if_exist(clones)
            # CycleState is built lazily in the binding cycle (only
            # pre_bind/unreserve/post_bind plugins and failure paths read
            # it; the plain burst has none)
            if any(errs):
                for pi, assumed, host, err in zip(
                    plain_pis, clones, hosts, errs
                ):
                    if err is not None:
                        self.record_scheduling_failure(
                            prof, pi, str(err), "SchedulerError", "",
                            pod_scheduling_cycle,
                        )
                        continue
                    bulk.append((prof, None, pi, assumed, host))
            else:
                bulk = [
                    (prof, None, pi, assumed, host)
                    for pi, assumed, host in zip(plain_pis, clones, hosts)
                ]
            self.pods_solved_on_device += len(plain_pis)
            span.bump("placed", len(plain_pis))

        failed_group: List[Tuple[PodInfo, FitError]] = []
        cluster_anti = None
        # live nodes only: with the slot layout, num_nodes counts free
        # (retired) slots too, and the "0/N nodes are available" message
        # must not claim more nodes than the cluster has
        live_nodes = sum(1 for n in names if n)
        # statuses are a pure function of the (deduplicated) mask row:
        # identical unschedulable pods share one dict
        statuses_by_row: dict = {}
        for pi, choice, k in slow:
            if choice == NO_NODE:
                adm = pi.pod.__dict__.get("_admission")
                if adm is not None and adm.vol_counts:
                    # the additive volume-count columns are CONSERVATIVE
                    # (a handle shared across resident pods counts once
                    # per pod), so a device reject of a countable-volume
                    # pod may be a false negative. Pin the pod host-only
                    # and requeue straight to the activeQ: the next
                    # cycle runs the exact per-node oracle (CSILimits /
                    # in-tree unique-handle sets), which either places
                    # it or produces the true unschedulable verdict.
                    pi.pod.__dict__["_admission"] = adm.as_host_only(
                        "volume-count-reject"
                    )
                    self.volume_reject_retries += 1
                    span.bump("volume_retries")
                    self.record_scheduling_failure(
                        prof, pi,
                        "countable-volume pod rejected by the device "
                        "solve; re-checking on the host path",
                        "Unschedulable", "", pod_scheduling_cycle,
                        skip_backoff=True,
                    )
                    continue
                coord = self.partition_coordinator
                if coord is not None and coord.try_spill(pi.pod):
                    # cross-partition spill: this stack's node slice has
                    # no room (or no feasible node) -- the pod is
                    # re-stamped to a sibling partition and forwarded
                    # through the apiserver, so preemption and backoff
                    # wait until every partition has had a look (its
                    # new home stack's quota gate re-charges it there)
                    self._quota_refund(pi.pod, "spill")
                    self.pods_solved_on_device += 1
                    span.bump("spilled")
                    continue
            state = CycleState()
            state.write(SNAPSHOT_STATE_KEY, snapshot)
            if choice == NO_NODE:
                metrics.schedule_attempts.inc(result="unschedulable")
                span.bump("no_node")
                # per-node reason codes (SURVEY section 7 hardest-part d,
                # generic_scheduler.go:1033): nodes rejected by the
                # STATIC mask (label/taint/name/unschedulable mismatch)
                # can never be helped by preemption -- mark them
                # UnschedulableAndUnresolvable so
                # nodes_where_preemption_might_help prunes like the
                # reference instead of scanning every node
                statuses = {}
                # host-port pods: the static row folds NodePorts in, and
                # a port conflict IS resolvable by evicting the holder
                # (generic_scheduler.go:940 re-runs filters with victims
                # removed) -- leave statuses empty so preemption scans
                # every node instead of wrongly pruning them
                if (
                    mask_info is not None
                    and mask_info[0] is not None
                    and not pod_host_ports(pi.pod)
                ):
                    m_rows, m_idx = mask_info
                    ridx = int(m_idx[k])
                    statuses = statuses_by_row.get(ridx)
                    if statuses is None:
                        statuses = {
                            names[int(j)]:
                            Status.unschedulable_and_unresolvable(
                                "node(s) didn't match the static "
                                "feasibility mask"
                            )
                            for j in np.flatnonzero(
                                ~m_rows[ridx][:num_nodes]
                            )
                            # free (retired) slots are masked off too
                            # but are not nodes
                            if names[int(j)]
                        }
                        statuses_by_row[ridx] = statuses
                fit_err = FitError(pi.pod, live_nodes, statuses)
                self.pods_solved_on_device += 1
                # device-eligible failures preempt as ONE group (one
                # device round trip via Preemptor.preempt_batch); the
                # rest take the per-pod host path
                if self.preemptor is not None:
                    if cluster_anti is None:
                        from kubernetes_tpu.ops.affinity import (
                            cluster_has_required_anti_affinity,
                        )

                        cluster_anti = cluster_has_required_anti_affinity(
                            snapshot
                        )
                    if self.preemptor.device_eligible(
                        prof, pi.pod, cluster_anti=cluster_anti
                    ):
                        failed_group.append((pi, fit_err))
                        continue
                # populate PreFilter state so host preemption's victim
                # simulation can run the full filter pipeline (the
                # sequential path gets this from algorithm.schedule)
                prof.run_pre_filter_plugins(state, pi.pod)
                self.handle_fit_error(
                    prof, state, pi, fit_err, pod_scheduling_cycle
                )
                continue
            host = names[choice]
            assumed = self.reserve_assume_permit(
                prof, state, pi, host, pod_scheduling_cycle
            )
            self.pods_solved_on_device += 1
            if assumed is None:
                continue
            span.bump("placed")
            waiting = prof.get_waiting_pod(assumed.metadata.uid) is not None
            binder_extender = any(
                e.is_binder() and e.is_interested(assumed)
                for e in extenders
            )
            if (
                waiting
                or binder_extender
                or not prof.uses_default_binder_only()
                or self._bind_pool is None
            ):
                # per-pod binding cycle (wait-on-permit / custom binds)
                if self._bind_pool is not None:
                    with self._inflight_lock:
                        self._inflight_binds += 1
                    self._bind_pool.submit(
                        self._binding_cycle_safe, prof, state, pi, assumed,
                        host, pod_scheduling_cycle,
                    )
                elif waiting:
                    # synchronous binding + a Permit waiter: running the
                    # cycle inline would block THIS loop on
                    # wait_on_permit while the quorum it waits for is
                    # later in the same batch (deadlock until the permit
                    # timeout); defer until every pod is assumed
                    deferred.append(
                        (prof, state, pi, assumed, host)
                    )
                else:
                    self._binding_cycle(
                        prof, state, pi, assumed, host, pod_scheduling_cycle
                    )
            else:
                bulk.append((prof, state, pi, assumed, host))
        if failed_group:
            # a burst that overflows the cluster fails across SEVERAL
            # in-flight batches; preempting per batch pays the wave's
            # fixed costs (state pack, result round trip) repeatedly and
            # fragments the nomination replay. While more solver batches
            # are queued behind this one (FIFO committer), park the
            # failures; the LAST in-flight batch preempts the whole
            # accumulated group in one device wave.
            if not self._deferred_preempt:
                self._deferred_since = time.monotonic()
            self._deferred_preempt.extend(
                (prof, pi, fe, pod_scheduling_cycle)
                for pi, fe in failed_group
            )
        if self._deferred_preempt:
            with self._pending_cv:
                more_inflight = len(self._pending_q) > 1
            # the burst is still streaming when the activeQ holds more
            # pods or batches are in flight; hold the wave for them --
            # bounded by age and size so a trickle of unschedulable
            # pods cannot starve preemption
            burst_live = (
                more_inflight or self.queue.active_count() > 0
            )
            flush_anyway = (
                len(self._deferred_preempt) >= self.max_batch
                or time.monotonic() - self._deferred_since > 0.3
            )
            if not burst_live or flush_anyway:
                self._flush_deferred_preemptions()
        if bulk:
            with self._inflight_lock:
                self._inflight_binds += 1
            self._bind_pool.submit(
                self._bulk_binding_cycle_safe, bulk, pod_scheduling_cycle,
                snapshot, span,
            )
        for prof_d, state_d, pi_d, assumed_d, host_d in deferred:
            self._binding_cycle(
                prof_d, state_d, pi_d, assumed_d, host_d,
                pod_scheduling_cycle,
            )

    def _flush_deferred_preemptions(self) -> None:
        """Run one preemption wave for every parked failure, grouped by
        profile (preempt_batch is profile-scoped), then requeue the pods
        with their nominations."""
        parked = self._deferred_preempt
        self._deferred_preempt = []
        # preempt_batch (and the host-side nomination fold inside the
        # device wave) require priority-DESC order; parked failures from
        # several batches can interleave priorities
        parked.sort(key=lambda t: (-t[1].pod.spec.priority, t[1].timestamp))
        by_prof: dict = {}
        for prof, pi, fe, cycle in parked:
            by_prof.setdefault(id(prof), (prof, []))[1].append(
                (pi, fe, cycle)
            )
        for prof, items in by_prof.values():
            victim_uids: Optional[List[str]] = []
            try:
                with timeline.span("preempt_wave"):
                    nominated, victim_uids = self.preemptor.preempt_batch(
                        prof, [(pi.pod, fe) for pi, fe, _ in items]
                    )
            except Exception:
                logger.exception("batched device preemption failed")
                nominated = [""] * len(items)
            evict_ok = victim_uids is not None
            flightrecorder.mark(
                "preemption_wave", pods=len(items),
                nominated=sum(1 for n in nominated if n),
                victims=len(victim_uids or ()),
                tier=getattr(self.preemptor, "wave_solver_tier", ""),
            )
            # wait (bounded) for the evictions to propagate from the
            # watch into the cache: the nominated pods retry WITHOUT
            # backoff below -- their failure was just resolved by this
            # wave's evictions, so backing off would only add the full
            # 1s initial-backoff round trip to every preemption -- and
            # an instant retry against a cache that still holds the
            # victims would waste a scheduling cycle
            if victim_uids:
                with timeline.span("victim_wait"):
                    deadline = time.monotonic() + 0.5
                    pending = list(victim_uids)
                    while pending and time.monotonic() < deadline:
                        pending = [
                            u for u in pending
                            if self.cache.has_pod_uid(u)
                        ]
                        if pending:
                            time.sleep(0.002)
            with timeline.span("preempt_requeue"):
                for (pi, fe, cycle), node in zip(items, nominated):
                    if self.cache.has_pod_uid(pi.pod.metadata.uid):
                        # stale parked record: the pod bound during the
                        # deferral window (an informer update re-added
                        # it); requeueing it would double-place a
                        # running pod
                        continue
                    self.record_scheduling_failure(
                        prof, pi, str(fe), "Unschedulable", node, cycle,
                        # no-backoff retry only when the wave actually
                        # evicted: otherwise the failure is persistent
                        # and the 1s backoff must damp it
                        skip_backoff=bool(node) and evict_ok,
                    )
            if any(nominated):
                # once these preemptors bind, the cluster is full again:
                # refresh the victim pack so the NEXT contention wave
                # finds it (and its device upload) already warm
                self._prewarm_next_commit = True

    def _bind_bulk_with_retry(self, assumed_list):
        """bind_assumed_bulk with retry-with-backoff around TRANSACTION
        failures (apiserver unavailable, injected conflict burst).
        Per-slot errors are the API's answer, not a transport failure --
        they return to the caller, whose per-slot handling already does
        forget + Unreserve + requeue. On terminal transaction failure
        every slot becomes an error so no pod is silently stranded
        assumed."""
        policy = self.ladder.config.retry
        coord = self.partition_coordinator
        binder = coord.identity if coord is not None else None
        attempt = 0
        while True:
            attempt += 1
            try:
                inj = get_injector()
                if inj is not None:
                    inj.raise_maybe(FaultPoint.BIND_CONFLICT)
                if binder is not None:
                    return self.client.bind_assumed_bulk(
                        assumed_list, binder=binder
                    )
                # keyword omitted off the partitioned path: test/bench
                # doubles that stub the client keep their old signature
                return self.client.bind_assumed_bulk(assumed_list)
            except Exception as e:  # noqa: BLE001 - transaction failure
                # max_attempts counts TOTAL attempts (ladder semantics)
                if attempt >= max(1, policy.max_attempts):
                    logger.exception(
                        "bulk bind failed terminally after %d attempts",
                        attempt,
                    )
                    return [(i, e) for i in range(len(assumed_list))]
                metrics.bind_retries.inc()
                self.ladder.config.sleep(
                    policy.backoff_for_attempt(attempt)
                )

    def _absorb_bind_conflict(
        self, prof, state, pi, assumed, host, err, pod_scheduling_cycle,
        span=None,
    ) -> None:
        """Absorb one typed bind conflict into the ledger: forget the
        optimistic reservation, release plugin state, then route by
        apiserver truth -- a pod that turned out ALREADY bound (a
        sibling stack won the race, or our own retried commit landed)
        is satisfied and records nothing; anything else requeues for
        another attempt. Exactly one disposition bucket per conflict:
        ``bind_conflicts_absorbed == conflict_requeues +
        conflict_stale_binds`` is a tier-1 invariant."""
        kind = getattr(err, "kind", "already-bound")
        self.bind_conflicts_absorbed += 1
        metrics.bind_conflicts_absorbed.inc(kind=kind)
        if span is not None:
            span.bump("conflicts")
        flightrecorder.mark(
            "bind_conflict", conflict=kind, pod=assumed.metadata.uid,
        )
        self._forget(assumed)
        prof.run_unreserve_plugins(state, assumed, host)
        live = None
        try:
            live = self.client.get_pod(
                assumed.metadata.namespace, assumed.metadata.name
            )
        except KeyError:
            pass  # deleted: nothing left to place
        except Exception:
            logger.exception(
                "conflict disposition read for %s", assumed.key()
            )
        if (
            live is not None
            and live.spec.node_name
            and live.metadata.uid == assumed.metadata.uid
        ):
            # satisfied elsewhere: the informer delivers the bound pod
            # into the cache; requeueing would double-schedule it
            self.conflict_stale_binds += 1
            return
        self.conflict_requeues += 1
        if live is None:
            return  # deleted while conflicting: requeue bucket, no add
        try:
            self.record_scheduling_failure(
                prof, pi, str(err), "BindConflict", "",
                pod_scheduling_cycle,
            )
            # a typed conflict is a TRANSIENT coordination race (fence
            # window, sibling overlap), not a cluster-state failure: no
            # future cluster event is guaranteed to wake the pod, so
            # parking it unschedulable could strand it for the 60s
            # flush. Route it to the backoff queue instead -- it retries
            # on the exponential backoff clock.
            self.queue.move_pods_to_active_or_backoff_queue(
                [pi], "BindConflictRetry"
            )
        except Exception:
            logger.exception("requeueing conflicted pod %s", pi.pod.key())

    def _bulk_binding_cycle_safe(
        self, items, pod_scheduling_cycle, snapshot=None, span=None
    ) -> None:
        try:
            self._bulk_binding_cycle(
                items, pod_scheduling_cycle, snapshot, span
            )
        except SchedulerCrashed:
            # simulated process death: halt with NO cleanup (the items
            # stay assumed-but-unbound; the next incarnation recovers)
            self._simulate_crash()
        except Exception:
            logger.exception("bulk binding cycle crashed")
        finally:
            with self._inflight_lock:
                self._inflight_binds -= 1
                self._inflight_lock.notify_all()

    def _bulk_binding_cycle(
        self, items, pod_scheduling_cycle, snapshot=None, span=None
    ) -> None:
        """One API transaction commits the batch (the pipelined bulk
        analogue of BindingREST.Create, storage.go:142). PreBind still
        runs per pod (skipped when every PreBind plugin declares itself
        a no-op for the pod); per-binding conflicts fail only their own
        pod.

        Plain pods arrive with ``state is None``: a CycleState is built
        only on the paths that read one (relevant pre_bind/post_bind
        plugins, unreserve on failure) -- the framework contract is
        per-pod state, and a fresh snapshot-seeded state is exactly what
        the eager path carried for these pods."""
        # the pre_bind gate must consider every profile in the bulk:
        # schedule_batch flushes on scheduler_name change today, but a
        # mixed bulk silently skipping another profile's PreBind plugins
        # would be a correctness bug, not a perf loss
        profs = {id(t[0]): t[0] for t in items}
        any_pre_bind = any(
            prof.relevance_entries("pre_bind") for prof in profs.values()
        )

        def mk_state():
            state = CycleState()
            state.write(SNAPSHOT_STATE_KEY, snapshot)
            return state

        if any_pre_bind:
            ready = []
            for prof, state, pi, assumed, host in items:
                if prof.plugins_relevant("pre_bind", assumed):
                    if state is None:
                        state = mk_state()
                    status = prof.run_pre_bind_plugins(state, assumed, host)
                else:
                    status = None
                if status is not None and not status.is_success():
                    self._forget(assumed)
                    prof.run_unreserve_plugins(state, assumed, host)
                    self.record_scheduling_failure(
                        prof, pi, status.message(), "SchedulerError", "",
                        pod_scheduling_cycle,
                    )
                    continue
                ready.append((prof, state, pi, assumed, host))
            if not ready:
                return
        else:
            ready = items
        inj = get_injector()
        if inj is not None:
            # the whole bulk is assumed but not yet bound -- the window
            # a process death strands (restart e2e drives this point)
            inj.crash_maybe(FaultPoint.CRASH_BETWEEN_ASSUME_AND_BIND)
        # commit-time lease fencing: verify ownership IMMEDIATELY before
        # the bulk transaction. A deposed leader (failed renews, standby
        # already holds the lease) must not commit placements computed
        # under its stale view -- abort and requeue; the pods are already
        # in the new leader's queue via its informers.
        if not self._fence_ok():
            metrics.fencing_aborts.inc()
            flightrecorder.mark("fencing_abort", pods=len(ready))
            logger.warning(
                "lease lost before bulk bind; fencing %d pod(s)",
                len(ready),
            )
            for prof, state, pi, assumed, host in ready:
                self._forget(assumed)
                prof.run_unreserve_plugins(
                    state if state is not None else mk_state(),
                    assumed, host,
                )
                self.record_scheduling_failure(
                    prof, pi, "lease lost before commit; fenced",
                    "SchedulerError", "", pod_scheduling_cycle,
                )
            return
        # partitioned commit fencing: the multi-lease holds_lease()
        # probe, run IMMEDIATELY before the bulk transaction. Pods on
        # partitions this stack no longer holds (handoff, lapsed lease
        # mid-dispatch) are absorbed as typed conflicts -- requeued,
        # never committed under a stale ownership view.
        coord = self.partition_coordinator
        if coord is not None and ready:
            fenced = coord.fence_hosts([t[4] for t in ready])
            if fenced:
                metrics.fencing_aborts.inc(len(fenced))
                flightrecorder.mark(
                    "fencing_abort", pods=len(fenced),
                    fence="partition",
                )
                kept = []
                fenced_pis = []
                for i, item in enumerate(ready):
                    if i not in fenced:
                        kept.append(item)
                        continue
                    prof_f, state_f, pi_f, assumed_f, host_f = item
                    self.bind_conflicts_absorbed += 1
                    self.conflict_requeues += 1
                    metrics.bind_conflicts_absorbed.inc(
                        kind="partition-fence"
                    )
                    if span is not None:
                        span.bump("conflicts")
                    flightrecorder.mark(
                        "bind_conflict", conflict="partition-fence",
                        pod=assumed_f.metadata.uid,
                    )
                    self._forget(assumed_f)
                    prof_f.run_unreserve_plugins(
                        state_f if state_f is not None else mk_state(),
                        assumed_f, host_f,
                    )
                    self.record_scheduling_failure(
                        prof_f, pi_f,
                        f"partition of node {host_f} not held at "
                        f"commit; fenced", "BindConflict", "",
                        pod_scheduling_cycle,
                    )
                    fenced_pis.append(pi_f)
                # fence conflicts are transient (a lease mid-handoff):
                # retry on the backoff clock instead of parking
                # unschedulable with no wake event in sight
                self.queue.move_pods_to_active_or_backoff_queue(
                    fenced_pis, "BindConflictRetry"
                )
                ready = kept
                if not ready:
                    return
        assumed_list = [t[3] for t in ready]
        bind_timer = metrics.SinceTimer(metrics.binding_duration)
        with timeline.span("bind_bulk"):
            errors = self._bind_bulk_with_retry(assumed_list)
        bind_timer.observe()
        if errors:
            failed = dict(errors)
            bound = []
            for i, item in enumerate(ready):
                err = failed.get(i)
                if err is None:
                    bound.append(item)
                    continue
                prof, state, pi, assumed, host = item
                if isinstance(err, ApiConflict):
                    # typed conflict (already-bound / uid-mismatch /
                    # foreign-partition): the optimistic-concurrency
                    # answer of a multi-active control plane, absorbed
                    # through the requeue path -- never a scheduler
                    # error, never silently dropped
                    self._absorb_bind_conflict(
                        prof,
                        state if state is not None else mk_state(),
                        pi, assumed, host, err, pod_scheduling_cycle,
                        span=span,
                    )
                    continue
                metrics.schedule_attempts.inc(result="error")
                self._forget(assumed)
                prof.run_unreserve_plugins(
                    state if state is not None else mk_state(),
                    assumed, host,
                )
                self.record_scheduling_failure(
                    prof, pi, str(err), "SchedulerError", "",
                    pod_scheduling_cycle,
                )
            bound_assumed = [t[3] for t in bound]
        else:
            bound = ready
            bound_assumed = assumed_list
        if not bound:
            return
        with timeline.span("finish_binding_bulk"):
            self.cache.finish_binding_bulk(bound_assumed)
        if any(p.has_plugins("post_bind") for p in profs.values()):
            for prof, state, pi, assumed, host in bound:
                if prof.has_plugins("post_bind"):
                    prof.run_post_bind_plugins(
                        state if state is not None else mk_state(),
                        assumed, host,
                    )
        # single-profile bulks take the batched-recorder fast path; a
        # mixed bulk passes recorder=None so _emit_bound's fallback
        # routes each event through the pod's own profile recorder
        recorder = bound[0][0].recorder if len(profs) == 1 else None
        with timeline.span("events+metrics"):
            self._emit_bound(recorder, bound)
        # arm the bind-ack ledger: each committed bind is pending until
        # its Running ack arrives over the watch (zombie-kubelet
        # detection -- scheduler/bindack.py)
        tracker = getattr(self, "bind_ack_tracker", None)
        if tracker is not None:
            tracker.track_bound([
                (
                    assumed.metadata.namespace, assumed.metadata.name,
                    assumed.metadata.uid, host,
                )
                for _, _, _, assumed, host in bound
            ])

    def _emit_bound(self, recorder, bound) -> None:
        if hasattr(recorder, "scheduled_many"):
            recorder.scheduled_many([a for _, _, _, a, _ in bound])
        elif hasattr(recorder, "eventf_many"):
            recorder.eventf_many(
                [
                    (
                        assumed, "Normal", "Scheduled",
                        f"Successfully assigned "
                        f"{assumed.metadata.namespace}/"
                        f"{assumed.metadata.name} to {host}",
                    )
                    for _, _, _, assumed, host in bound
                ]
            )
        else:
            for prof, state, pi, assumed, host in bound:
                prof.recorder.eventf(
                    assumed, "Normal", "Scheduled",
                    f"Successfully assigned "
                    f"{assumed.metadata.namespace}/"
                    f"{assumed.metadata.name} to {host}",
                )
        # batched success metrics (one lock hold per histogram)
        metrics.schedule_attempts.inc(len(bound), result="scheduled")
        metrics.pod_scheduling_attempts.observe_many(
            [pi.attempts for _, _, pi, _, _ in bound]
        )
        now = time.monotonic()
        durations = [
            max(0.0, now - pi.initial_attempt_timestamp)
            for _, _, pi, _, _ in bound
            if pi.initial_attempt_timestamp
        ]
        metrics.pod_scheduling_duration.observe_many(durations)
        # live pod-to-bind quantile sketch (P-squared): the same stream
        # the histogram sees, but queryable as p50/p99 gauges
        metrics.observe_pod_to_bind(durations)

    # -- warmup --------------------------------------------------------------

    def warmup(self) -> None:
        """Compile every solver variant for the current cluster shape so
        no measured batch pays JIT latency (the reference harness similarly
        schedules warm-up pods before b.ResetTimer,
        scheduler_perf_test.go:130).

        With the adaptive controller attached, its latency-rung solve
        pad is compiled too (basic path only -- constrained families on
        the latency rung are rare enough that the one-time compile can
        land on demand), so a controller rung switch never pays JIT
        latency inside a measured window."""
        snapshot = self.algorithm.snapshot
        self.cache.update_snapshot(snapshot)
        nt = self.tensor_cache.update(snapshot)
        if nt.capacity == 0:
            return
        extra = sorted(
            int(p) for p in self._warmup_pads
            if p and int(p) != self.max_batch
        )
        for padded in [self.max_batch] + extra:
            self._warmup_at(nt, padded, full=padded == self.max_batch)
        # seal the jit-cache watchdog: every signature compiled from
        # here on is a mid-run recompile (counted AND flight-recorded)
        self._jit_watch.seal()
        if self.autobatch is not None and hasattr(
            self.autobatch, "calibrate"
        ):
            # rung-ladder calibration (ROADMAP item-2a residual): the
            # controller drops candidate rungs whose measured solve
            # cost is not meaningfully cheaper than the rung above --
            # every surviving rung is already compiled by the loop
            # above, so a rung switch never pays JIT mid-run
            self.autobatch.calibrate(dict(self.pad_solve_seconds))

    def _warmup_at(self, nt, padded: int, full: bool) -> None:
        n = nt.capacity
        r = nt.dims.num_dims
        if self.mesh is not None and self.mesh_delta:
            self._warmup_mesh_packed(nt, padded, full)
            return
        host = (
            nt.allocatable, nt.requested, nt.non_zero_requested, nt.valid,
            np.zeros((padded, r), dtype=np.int32),
            np.zeros((padded, 2), dtype=np.int32),
            np.zeros((MASK_ROW_BUCKET, n), dtype=bool),
            np.zeros(padded, dtype=np.int32),
            np.zeros(padded, dtype=bool),
        )
        if self.mesh is not None:
            common = jax.device_put(
                host,
                (
                    self._sh_node2, self._sh_node2, self._sh_node2,
                    self._sh_node1, self._sh_repl, self._sh_repl,
                    self._sh_rows, self._sh_repl, self._sh_repl,
                ),
            )
        else:
            common = jax.device_put(host)
        if self.solver_mode == "sinkhorn":
            out = sinkhorn_assign(*common, config=self.solver_config)
            jax.block_until_ready(out)
        out = greedy_assign_compact(*common, config=self.solver_config)
        jax.block_until_ready(out)
        if self.mesh is None:
            # compile every packed-upload layout the run loop can hit:
            # cold (static+carry ride the buffer), carry-refresh, and
            # steady-state carry-reuse
            base = [
                ("req", np.zeros((padded, r), dtype=np.int32)),
                ("nzr", np.zeros((padded, 2), dtype=np.int32)),
                ("midx", np.zeros(padded, dtype=np.int32)),
                ("active", np.zeros(padded, dtype=np.int32)),
                ("rows", np.zeros((MASK_ROW_BUCKET, n), dtype=np.int32)),
            ]
            static_pieces = [
                ("alloc", np.zeros((n, r), dtype=np.int32)),
                ("valid", np.zeros(n, dtype=np.int32)),
            ]
            carry_pieces = [
                ("req_state", np.zeros((n, r), dtype=np.int32)),
                ("nzr_state", np.zeros((n, 2), dtype=np.int32)),
            ]
            # steady-state dispatches always carry the (indices, rows)
            # delta-scatter slots (empty slots drop on device), so the
            # run loop hits exactly ONE steady signature per mode
            delta_slots = _delta_slot_pieces(n, r)
            cold = solve_packed(
                base + static_pieces + carry_pieces, None, None, None, None,
                config=self.solver_config, mode=self.solver_mode,
            )
            jax.block_until_ready(cold)
            _, _, _, alloc_d, valid_d = cold
            refresh = solve_packed(
                base + carry_pieces, alloc_d, valid_d, None, None,
                config=self.solver_config, mode=self.solver_mode,
            )
            jax.block_until_ready(refresh)
            _, req_d, nzr_d, _, _ = refresh
            steady = solve_packed(
                base + delta_slots, alloc_d, valid_d, req_d, nzr_d,
                config=self.solver_config, mode=self.solver_mode,
            )
            jax.block_until_ready(steady)
            # measured per-pad solve cost (post-compile): feeds the
            # AutoBatchController rung-ladder calibration, so the rungs
            # reflect what THIS cluster shape actually pays per pad.
            # Median of 3 -- a single sample absorbing a GC pause would
            # prune a rung on one run and keep it on the next, making
            # the ladder (and the controller trajectory) nondeterministic
            samples = []
            for _ in range(3):
                t0 = time.perf_counter()
                jax.block_until_ready(solve_packed(
                    base + delta_slots, alloc_d, valid_d, req_d, nzr_d,
                    config=self.solver_config, mode=self.solver_mode,
                ))
                samples.append(time.perf_counter() - t0)
            self.pad_solve_seconds[padded] = sorted(samples)[1]
            if self.carry_compress_enabled:
                # compressed-carry signatures (ISSUE 18): the range
                # gate can engage/disengage mid-run, so the int16
                # variants of the cold/refresh/steady basic layouts --
                # plus the on-device convert kernels the mode flips run
                # -- must all be warm, or the first engage pays a
                # mid-run compile the jit-cache watchdog would flag
                carry16 = [
                    ("req_state", np.zeros((n, r), dtype=np.int16)),
                    ("nzr_state", np.zeros((n, 2), dtype=np.int16)),
                ]
                delta16 = _delta_slot_pieces(n, r, compress=True)
                cold16 = solve_packed(
                    base + static_pieces + carry16, None, None, None,
                    None, config=self.solver_config,
                    mode=self.solver_mode, compress=True,
                )
                jax.block_until_ready(cold16)
                refresh16 = solve_packed(
                    base + carry16, alloc_d, valid_d, None, None,
                    config=self.solver_config, mode=self.solver_mode,
                    compress=True,
                )
                jax.block_until_ready(refresh16)
                _, req16, nzr16, _, _ = refresh16
                steady16 = solve_packed(
                    base + delta16, alloc_d, valid_d, req16, nzr16,
                    config=self.solver_config, mode=self.solver_mode,
                    compress=True,
                )
                jax.block_until_ready(steady16)
                jax.block_until_ready(compress_carry(req_d, nzr_d))
                jax.block_until_ready(decompress_carry(req16, nzr16))
                # the host-greedy tier's carry keep-warm with an int16
                # resident carry (dtype-preserving delta apply)
                jax.block_until_ready(apply_assignment_delta(
                    req16, nzr16,
                    np.full(padded, NO_NODE, dtype=np.int32),
                    np.zeros((padded, r), dtype=np.int32),
                    np.zeros((padded, 2), dtype=np.int32),
                ))
        if not full:
            # extra (latency-rung) pads warm the basic path only
            return
        noops = (
            noop_spread_tensors(padded, n),
            noop_affinity_tensors(padded, n),
            noop_score_tensors(padded, n),
        )
        if self.mesh is not None:
            sp_dev, af_dev, sc_dev = jax.device_put(noops, self._sh_repl)
            out = greedy_assign_constrained(
                *common, tuple(sp_dev), tuple(af_dev), tuple(sc_dev),
                config=self.solver_config,
            )
            jax.block_until_ready(out)
        else:
            if n > CONSTRAINED_NODE_CAP:
                return  # constrained batches route to the host path
            # compile the packed constrained layouts the run loop can hit
            # (cold / carry-refresh / steady), mirroring the basic-path
            # variants above -- a first constrained batch must not pay a
            # multi-second XLA compile inside the measured window
            fam = (
                [(f"sp{i}", np.asarray(a)) for i, a in enumerate(noops[0])]
                + [(f"af{i}", np.asarray(a)) for i, a in enumerate(noops[1])]
                + [(f"sc{i}", np.asarray(a)) for i, a in enumerate(noops[2])]
            )
            c_cold = solve_packed(
                base + static_pieces + carry_pieces + fam,
                None, None, None, None,
                config=self.solver_config, mode="constrained",
            )
            jax.block_until_ready(c_cold)
            c_refresh = solve_packed(
                base + carry_pieces + fam, alloc_d, valid_d, None, None,
                config=self.solver_config, mode="constrained",
            )
            jax.block_until_ready(c_refresh)
            c_steady = solve_packed(
                base + delta_slots + fam, alloc_d, valid_d, req_d, nzr_d,
                config=self.solver_config, mode="constrained",
            )
            jax.block_until_ready(c_steady)
            # family-combo layouts (absent families ride as ConstPiece
            # device constants): the kernel specializes per PRESENT
            # family combo (pallas_constrained.live_caps), so warm the
            # steady-carry variant of every combo a measured phase can
            # hit -- 2^3 - 1, each a distinct Caps and pallas compile
            from kubernetes_tpu.ops.assignment import ConstPiece

            fam_groups = {"sp": noops[0], "af": noops[1], "sc": noops[2]}
            combos = (
                ("sp",), ("af",), ("sc",),
                ("sp", "af"), ("sp", "sc"), ("af", "sc"),
            )  # the triple is already warmed by c_cold/refresh/steady
            for live in combos:
                fam_one = []
                for prefix, arrs in fam_groups.items():
                    for i, a in enumerate(arrs):
                        fam_one.append(
                            (f"{prefix}{i}", np.asarray(a))
                            if prefix in live
                            else (
                                f"{prefix}{i}",
                                ConstPiece.from_uniform(a),
                            )
                        )
                out_one = solve_packed(
                    base + delta_slots + fam_one, alloc_d, valid_d,
                    req_d, nzr_d,
                    config=self.solver_config, mode="constrained",
                )
                jax.block_until_ready(out_one)

    def _warmup_mesh_packed(self, nt, padded: int, full: bool) -> None:
        """Sharded-twin warmup: compile every packed-upload layout the
        MESH run loop can hit -- cold (static+carry ride the replicated
        buffer, resharded once on device), carry-refresh, and
        steady-state delta-scatter -- for BOTH mesh tiers (the
        shard_map'd Pallas tier the ladder attempts first when
        mesh_pallas_candidate holds, and the GSPMD XLA twin the
        breakers fall back to), plus the single constrained layout.
        Absent families ride as real zero tensors on the mesh
        (fam_pieces), so the constrained dispatch has exactly ONE
        signature per (state-variant, mesh shape): the multichip
        dryrun's zero-recompile probe (mesh_packed_cache_size) pins
        that the steady phase never compiles past this set -- the probe
        covers the Pallas-tier signatures too, since both tiers share
        the one jitted mesh solver. The steady solve is re-run timed
        post-compile (pad_solve_seconds, on the tier dispatch will
        actually use) for the AutoBatchController rung ladder."""
        from kubernetes_tpu.ops.assignment import mesh_pallas_candidate

        n = nt.capacity
        r = nt.dims.num_dims
        base = [
            ("req", np.zeros((padded, r), dtype=np.int32)),
            ("nzr", np.zeros((padded, 2), dtype=np.int32)),
            ("midx", np.zeros(padded, dtype=np.int32)),
            ("active", np.zeros(padded, dtype=np.int32)),
            ("rows", mask_rows_upload(
                np.zeros((MASK_ROW_BUCKET, n), dtype=bool), self.mesh
            )),
        ]
        static_pieces = [
            ("alloc", np.zeros((n, r), dtype=np.int32)),
            ("valid", np.zeros(n, dtype=np.int32)),
        ]
        carry_pieces = [
            ("req_state", np.zeros((n, r), dtype=np.int32)),
            ("nzr_state", np.zeros((n, 2), dtype=np.int32)),
        ]
        delta_slots = _delta_slot_pieces(n, r)
        tiers = [False]  # the GSPMD twin always warms (breaker target)
        if mesh_pallas_candidate(self.solver_mode, n, self.mesh):
            tiers.insert(0, True)
        alloc_d = valid_d = req_d = nzr_d = None
        for allow_pallas in tiers:
            kw = dict(
                config=self.solver_config, mode=self.solver_mode,
                mesh=self.mesh, allow_pallas=allow_pallas,
            )
            cold = solve_packed(
                base + static_pieces + carry_pieces,
                None, None, None, None, **kw,
            )
            jax.block_until_ready(cold)
            _, _, _, alloc_d, valid_d = cold
            refresh = solve_packed(
                base + carry_pieces, alloc_d, valid_d, None, None, **kw
            )
            jax.block_until_ready(refresh)
            _, req_d, nzr_d, _, _ = refresh
            steady = solve_packed(
                base + delta_slots, alloc_d, valid_d, req_d, nzr_d, **kw
            )
            jax.block_until_ready(steady)
            if allow_pallas is not tiers[0]:
                continue
            # median of 3 (see _warmup_at) on the FIRST-attempt tier:
            # one noisy sample must not make the calibrated ladder
            # nondeterministic run-to-run
            samples = []
            for _ in range(3):
                t0 = time.perf_counter()
                jax.block_until_ready(solve_packed(
                    base + delta_slots, alloc_d, valid_d, req_d, nzr_d,
                    **kw,
                ))
                samples.append(time.perf_counter() - t0)
            self.pad_solve_seconds[padded] = sorted(samples)[1]
        if not full or n > CONSTRAINED_NODE_CAP:
            # latency rungs warm the basic path only; over the
            # constrained node cap every constrained batch routes host
            return
        noops = (
            noop_spread_tensors(padded, n),
            noop_affinity_tensors(padded, n),
            noop_score_tensors(padded, n),
        )
        fam = (
            [(f"sp{i}", np.asarray(a)) for i, a in enumerate(noops[0])]
            + [(f"af{i}", np.asarray(a)) for i, a in enumerate(noops[1])]
            + [(f"sc{i}", np.asarray(a)) for i, a in enumerate(noops[2])]
        )
        ckw = dict(
            config=self.solver_config, mode="constrained", mesh=self.mesh,
        )
        jax.block_until_ready(solve_packed(
            base + static_pieces + carry_pieces + fam,
            None, None, None, None, **ckw,
        ))
        jax.block_until_ready(solve_packed(
            base + carry_pieces + fam, alloc_d, valid_d, None, None,
            **ckw,
        ))
        jax.block_until_ready(solve_packed(
            base + delta_slots + fam, alloc_d, valid_d, req_d, nzr_d,
            **ckw,
        ))

    # -- loop ---------------------------------------------------------------

    def run(self) -> None:
        from kubernetes_tpu.utils.gc_tuning import GCBatchGuard

        self.queue.run()
        self._gc_guard = GCBatchGuard()
        try:
            while not self._stop.is_set():
                # in-flight batches land on the committer thread, so the
                # dispatcher can always block for the next arrivals
                self.schedule_batch(timeout=0.5, pipeline=True)
            self._drain_pending()
            self._stop_committer()
        finally:
            guard, self._gc_guard = self._gc_guard, None
            guard.close()
