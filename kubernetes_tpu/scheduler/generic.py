"""Generic (per-pod, sequential) scheduling algorithm -- the oracle path.

Reference: /root/reference/pkg/scheduler/core/generic_scheduler.go
(Schedule :150, findNodesThatFitPod :414, findNodesThatPassFilters :429,
numFeasibleNodesToFind :390, prioritizeNodes :626, selectHost :235,
podPassesFiltersOnNode :570 with the 2-pass nominated-pods logic).

On TPU this whole pipeline is replaced by vectorized masks/scores + batched
assignment (kubernetes_tpu.ops.assignment); adaptive node sampling is
deliberately NOT used there -- full vectorized evaluation is cheaper than
divergence on TPU (SURVEY.md section 2.5).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.cache.cache import SchedulerCache
from kubernetes_tpu.cache.node_info import NodeInfo
from kubernetes_tpu.cache.snapshot import Snapshot
from kubernetes_tpu.config.types import (
    MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND,
    MIN_FEASIBLE_NODES_TO_FIND,
)
from kubernetes_tpu.framework.interface import (
    CycleState,
    FitError,
    NodeToStatusMap,
    Status,
    StatusCode,
)
from kubernetes_tpu.framework.runtime import Framework

SNAPSHOT_STATE_KEY = "__snapshot__"


@dataclass
class ScheduleResult:
    """Reference generic_scheduler.go:107."""

    suggested_host: str = ""
    evaluated_nodes: int = 0
    feasible_nodes: int = 0


class GenericScheduler:
    def __init__(
        self,
        cache: SchedulerCache,
        snapshot: Optional[Snapshot] = None,
        percentage_of_nodes_to_score: int = 0,
        nominated_pods_lister=None,
        extenders: Optional[list] = None,
        rng: Optional[random.Random] = None,
    ) -> None:
        self.cache = cache
        self.snapshot = snapshot or Snapshot()
        self.percentage_of_nodes_to_score = percentage_of_nodes_to_score
        self.next_start_node_index = 0
        self.nominated_pods_lister = nominated_pods_lister  # PriorityQueue
        self.extenders = extenders or []
        self.rng = rng or random.Random()

    # -- entry point (generic_scheduler.go:150 Schedule) --------------------

    def schedule(
        self, prof: Framework, state: CycleState, pod: Pod
    ) -> ScheduleResult:
        self.cache.update_snapshot(self.snapshot)
        state.write(SNAPSHOT_STATE_KEY, self.snapshot)
        num_nodes = self.snapshot.num_nodes()
        if num_nodes == 0:
            raise FitError(pod, 0, {})

        status = prof.run_pre_filter_plugins(state, pod)
        if status is not None and not status.is_success():
            if status.is_unschedulable():
                raise FitError(
                    pod, num_nodes, {"": status}
                )
            raise RuntimeError(status.message())

        feasible, statuses = self.find_nodes_that_fit_pod(prof, state, pod)
        if not feasible:
            raise FitError(pod, num_nodes, statuses)
        if len(feasible) == 1:
            return ScheduleResult(
                suggested_host=feasible[0].node_name,
                evaluated_nodes=1 + len(statuses),
                feasible_nodes=1,
            )

        priority_list = self.prioritize_nodes(prof, state, pod, feasible)
        host = self.select_host(priority_list)
        return ScheduleResult(
            suggested_host=host,
            evaluated_nodes=len(feasible) + len(statuses),
            feasible_nodes=len(feasible),
        )

    # -- filtering ----------------------------------------------------------

    def num_feasible_nodes_to_find(self, num_all_nodes: int) -> int:
        """Adaptive search truncation (generic_scheduler.go:390)."""
        if (
            num_all_nodes < MIN_FEASIBLE_NODES_TO_FIND
            or self.percentage_of_nodes_to_score >= 100
        ):
            return num_all_nodes
        adaptive_percentage = self.percentage_of_nodes_to_score
        if adaptive_percentage <= 0:
            basic_percentage = 50
            adaptive_percentage = basic_percentage - num_all_nodes // 125
            if adaptive_percentage < MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND:
                adaptive_percentage = MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND
        num_nodes = num_all_nodes * adaptive_percentage // 100
        if num_nodes < MIN_FEASIBLE_NODES_TO_FIND:
            return MIN_FEASIBLE_NODES_TO_FIND
        return num_nodes

    def find_nodes_that_fit_pod(
        self, prof: Framework, state: CycleState, pod: Pod
    ) -> Tuple[List[NodeInfo], NodeToStatusMap]:
        """generic_scheduler.go:414 + :429 findNodesThatPassFilters."""
        all_nodes = self.snapshot.list_node_infos()
        num_all = len(all_nodes)
        num_to_find = self.num_feasible_nodes_to_find(num_all)
        feasible: List[NodeInfo] = []
        statuses: NodeToStatusMap = {}

        if not prof.has_filter_plugins():
            # length check preserves round-robin semantics (:447)
            start = self.next_start_node_index % num_all
            feasible = [all_nodes[(start + i) % num_all] for i in range(num_to_find)]
            self.next_start_node_index = (start + num_to_find) % num_all
        else:
            checked = 0
            for i in range(num_all):
                if len(feasible) >= num_to_find:
                    break
                ni = all_nodes[(self.next_start_node_index + i) % num_all]
                checked += 1
                fits, status = self.pod_passes_filters_on_node(
                    prof, state, pod, ni
                )
                if fits:
                    feasible.append(ni)
                elif status is not None:
                    statuses[ni.node_name] = status
            self.next_start_node_index = (
                self.next_start_node_index + checked
            ) % num_all

        feasible = self._find_nodes_that_pass_extenders(pod, feasible, statuses)
        return feasible, statuses

    def pod_passes_filters_on_node(
        self, prof: Framework, state: CycleState, pod: Pod, node_info: NodeInfo
    ) -> Tuple[bool, Optional[Status]]:
        """2-pass filter with nominated pods (generic_scheduler.go:570):
        pass 1 with higher/equal-priority nominated pods virtually added,
        pass 2 without (only needed when pass 1 added some)."""
        status: Optional[Status] = None
        pod_added = False
        state_to_use = state
        info_to_use = node_info
        for i in range(2):
            if i == 0:
                pod_added, state_to_use, info_to_use = self._add_nominated_pods(
                    prof, pod, state, node_info
                )
            elif not pod_added:
                break
            else:
                state_to_use, info_to_use = state, node_info
            statuses = prof.run_filter_plugins(state_to_use, pod, info_to_use)
            if statuses:
                status = self._merge_statuses(statuses)
                return False, status
        return True, status

    def _add_nominated_pods(
        self, prof: Framework, pod: Pod, state: CycleState, node_info: NodeInfo
    ) -> Tuple[bool, CycleState, NodeInfo]:
        """generic_scheduler.go:535 addNominatedPods."""
        if self.nominated_pods_lister is None:
            return False, state, node_info
        nominated = self.nominated_pods_lister.nominated_pods_for_node(
            node_info.node_name
        )
        if not nominated:
            return False, state, node_info
        node_info_out = node_info.clone()
        state_out = state.clone()
        added = False
        for p in nominated:
            if (
                p.spec.priority >= pod.spec.priority
                and p.metadata.uid != pod.metadata.uid
            ):
                node_info_out.add_pod(p)
                prof.run_pre_filter_extension_add_pod(
                    state_out, pod, p, node_info_out
                )
                added = True
        return added, state_out, node_info_out

    @staticmethod
    def _merge_statuses(statuses: Dict[str, Status]) -> Status:
        """PluginToStatus.Merge (framework interface.go:103): reasons
        accumulate; UnschedulableAndUnresolvable dominates Unschedulable."""
        code = StatusCode.UNSCHEDULABLE
        reasons: List[str] = []
        for s in statuses.values():
            if s.code == StatusCode.ERROR:
                code = StatusCode.ERROR
            elif (
                s.code == StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE
                and code != StatusCode.ERROR
            ):
                code = StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE
            reasons.extend(s.reasons)
        return Status(code, *reasons)

    def _find_nodes_that_pass_extenders(
        self, pod: Pod, feasible: List[NodeInfo], statuses: NodeToStatusMap
    ) -> List[NodeInfo]:
        """generic_scheduler.go:502: HTTP extenders filter after in-tree."""
        for extender in self.extenders:
            if not feasible:
                break
            if not extender.is_interested(pod):
                continue
            feasible, failed = extender.filter(pod, feasible)
            for name, reason in failed.items():
                statuses[name] = Status.unschedulable(reason)
        return feasible

    # -- scoring ------------------------------------------------------------

    def prioritize_nodes(
        self,
        prof: Framework,
        state: CycleState,
        pod: Pod,
        nodes: List[NodeInfo],
    ) -> List[Tuple[str, int]]:
        """generic_scheduler.go:626: returns [(node_name, total_score)]."""
        if not self.extenders and not prof.has_score_plugins():
            return [(ni.node_name, 1) for ni in nodes]

        status = prof.run_pre_score_plugins(state, pod, nodes)
        if status is not None and not status.is_success():
            raise RuntimeError(status.message())

        node_names = [ni.node_name for ni in nodes]
        scores_by_plugin, status = prof.run_score_plugins(state, pod, node_names)
        if status is not None and not status.is_success():
            raise RuntimeError(status.message())

        totals: Dict[str, int] = {name: 0 for name in node_names}
        for plugin_scores in scores_by_plugin.values():
            for ns in plugin_scores:
                totals[ns.name] += ns.score

        for extender in self.extenders:
            if not extender.is_interested(pod):
                continue
            ext_scores = extender.prioritize(pod, nodes)
            for name, score in ext_scores.items():
                totals[name] = totals.get(name, 0) + score

        return [(name, totals[name]) for name in node_names]

    def select_host(self, priority_list: List[Tuple[str, int]]) -> str:
        """Reservoir-sampled argmax among ties (generic_scheduler.go:235)."""
        if not priority_list:
            raise ValueError("empty priority list")
        selected, max_score = priority_list[0]
        ties = 1
        for name, score in priority_list[1:]:
            if score > max_score:
                max_score = score
                selected = name
                ties = 1
            elif score == max_score:
                ties += 1
                if self.rng.randrange(ties) == 0:
                    selected = name
        return selected
