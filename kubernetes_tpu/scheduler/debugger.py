"""Cache debugger: on-demand state dump + cache-vs-apiserver comparison.

Reference: /root/reference/pkg/scheduler/internal/cache/debugger/
(debugger.go:29 CacheDebugger, signal.go:25 SIGUSR2 listener, dumper.go:39
DumpAll, comparer.go CompareNodes/ComparePods) -- the reference's runtime
consistency checker for scheduler state.

The TPU build adds a tensor checksum comparison: the packed NodeTensor is
re-derived from a fresh snapshot and diffed against the cached one,
catching drift in the incremental row-repack path (the device-side
analogue of the cache comparer).
"""

from __future__ import annotations

import logging
import signal
from typing import Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)


class CacheDumper:
    """dumper.go:39 DumpAll."""

    def __init__(self, cache, queue) -> None:
        self.cache = cache
        self.queue = queue

    def dump_all(self) -> str:
        lines = ["Dump of cached NodeInfo:"]
        for name, pods in sorted(self.cache.dump().items()):
            lines.append(f"  node {name}: pods={sorted(pods)}")
        lines.append("Dump of scheduling queue:")
        for pod in self.queue.pending_pods():
            lines.append(f"  {pod.key()} priority={pod.spec.priority}")
        text = "\n".join(lines)
        logger.info("%s", text)
        return text


class CacheComparer:
    """comparer.go: diff cache/queue against the apiserver view."""

    def __init__(self, client, cache, queue) -> None:
        self.client = client
        self.cache = cache
        self.queue = queue

    def compare(self) -> Dict[str, List[str]]:
        """Returns {missed_nodes, redundant_nodes, missed_pods,
        redundant_pods} -- empty lists mean consistent."""
        nodes, _ = self.client.list_nodes()
        pods, _ = self.client.list_pods()
        cached = self.cache.dump()  # node -> [pod keys]

        api_nodes = {n.metadata.name for n in nodes}
        cache_nodes = set(cached)
        # scheduled pods only; pending ones live in the queue
        api_pods = {p.key() for p in pods if p.spec.node_name}
        queued = {p.key() for p in self.queue.pending_pods()}
        cache_pods = {key for pod_keys in cached.values() for key in pod_keys}

        result = {
            "missed_nodes": sorted(api_nodes - cache_nodes),
            "redundant_nodes": sorted(cache_nodes - api_nodes),
            "missed_pods": sorted(api_pods - cache_pods - queued),
            "redundant_pods": sorted(cache_pods - api_pods),
        }
        for k, v in result.items():
            if v:
                logger.warning("cache comparer: %s = %s", k, v)
        return result


class TensorComparer:
    """TPU addition: verify the incremental NodeTensor equals a from-
    scratch repack of the same snapshot."""

    def __init__(self, tensor_cache, snapshot) -> None:
        self.tensor_cache = tensor_cache
        self.snapshot = snapshot

    def compare(self) -> List[str]:
        from kubernetes_tpu.tensors import NodeTensorCache

        incremental = self.tensor_cache.update(self.snapshot)
        fresh = NodeTensorCache(
            dims=self.tensor_cache.dims,
            topology_encoder=self.tensor_cache.topology,
        ).update(self.snapshot)
        problems = []
        # compare per NAME: the incremental tensor's slot layout (free
        # rows, claimed headroom) legitimately orders rows differently
        # from a from-scratch pack of the same snapshot
        live = sorted(n for n in incremental.names if n)
        if live != sorted(fresh.names):
            problems.append("node membership mismatch")
        else:
            inc_rows = np.asarray(
                [incremental.row(n) for n in live], dtype=np.int64
            )
            fr_rows = np.asarray(
                [fresh.row(n) for n in live], dtype=np.int64
            )
            for field in ("allocatable", "requested", "non_zero_requested"):
                a = getattr(incremental, field)[inc_rows]
                b = getattr(fresh, field)[fr_rows]
                if not np.array_equal(a, b):
                    rows = np.where((a != b).any(axis=1))[0]
                    problems.append(
                        f"{field} mismatch on rows "
                        f"{[live[r] for r in rows[:5]]}"
                    )
        for p in problems:
            logger.warning("tensor comparer: %s", p)
        return problems


class CacheDebugger:
    """debugger.go:29 + signal.go:25: SIGUSR2 triggers compare + dump."""

    def __init__(
        self, client, cache, queue, tensor_cache=None, snapshot=None
    ) -> None:
        self.dumper = CacheDumper(cache, queue)
        self.comparer = CacheComparer(client, cache, queue)
        self.tensor_comparer = (
            TensorComparer(tensor_cache, snapshot)
            if tensor_cache is not None and snapshot is not None
            else None
        )

    def on_signal(self, signum=None, frame=None) -> None:
        self.comparer.compare()
        if self.tensor_comparer is not None:
            self.tensor_comparer.compare()
        self.dumper.dump_all()

    def listen_for_signal(self) -> None:
        signal.signal(signal.SIGUSR2, self.on_signal)
