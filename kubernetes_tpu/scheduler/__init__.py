"""Scheduler: the control loop(s).

Reference: /root/reference/pkg/scheduler/. Two execution profiles ship:

- the sequential host path (``scheduler.Scheduler.schedule_one``), a
  faithful port of scheduleOne semantics used as the correctness oracle;
- the TPU batch path (``batch.BatchScheduler``), which drains the activeQ
  in batches and solves placement as one vectorized assignment problem on
  device (kubernetes_tpu.ops).
"""

from kubernetes_tpu.scheduler.scheduler import Scheduler, new_scheduler

__all__ = ["Scheduler", "new_scheduler"]
