"""Scheduler: the scheduleOne control loop and its wiring.

Reference: /root/reference/pkg/scheduler/scheduler.go (Scheduler struct :79,
New :223, Run :363, scheduleOne :548, assume :474, bind :496,
recordSchedulingFailure :375) and pkg/scheduler/profile/profile.go.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional

import numpy as np

from kubernetes_tpu.api.types import Pod, PodCondition
from kubernetes_tpu.cache.cache import SchedulerCache
from kubernetes_tpu.cache.snapshot import Snapshot
from kubernetes_tpu.client.client import Client
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.config.types import KubeSchedulerProfile, Plugins
from kubernetes_tpu.framework.interface import (
    CycleState,
    FitError,
    PodInfo,
    Status,
    StatusCode,
)
from kubernetes_tpu.framework.registry import Registry
from kubernetes_tpu.framework.runtime import Framework
from kubernetes_tpu.plugins import new_in_tree_registry
from kubernetes_tpu.queue.scheduling_queue import PriorityQueue
from kubernetes_tpu.robustness.circuit import RetryPolicy
from kubernetes_tpu.robustness.faults import (
    FaultPoint,
    SchedulerCrashed,
    get_injector,
    poison_raise_maybe,
)
from kubernetes_tpu.scheduler.generic import GenericScheduler
from kubernetes_tpu.scheduler.provider import default_plugins
from kubernetes_tpu.utils import flightrecorder, metrics

logger = logging.getLogger(__name__)


class Scheduler:
    def __init__(
        self,
        cache: SchedulerCache,
        queue: PriorityQueue,
        algorithm: GenericScheduler,
        profiles: Dict[str, Framework],
        client: Optional[Client] = None,
        preemptor=None,
        async_binding: bool = True,
        bind_workers: int = 16,
    ) -> None:
        self.cache = cache
        self.queue = queue
        self.algorithm = algorithm
        self.profiles = profiles
        self.client = client
        self.preemptor = preemptor  # set by stage-7 wiring
        self.async_binding = async_binding
        self._bind_pool = (
            ThreadPoolExecutor(max_workers=bind_workers, thread_name_prefix="bind")
            if async_binding
            else None
        )
        self._stop = threading.Event()
        self._inflight_binds = 0
        self._inflight_lock = threading.Condition()
        # bind/commit retry policy (robustness/): transient API failures
        # retry with backoff before the terminal failure path (which
        # guarantees forget + Unreserve + requeue)
        self.bind_retry_policy = RetryPolicy()
        self._retry_sleep = time.sleep
        # commit-time lease fencing (PR-2 HA): when set (SchedulerApp
        # wires LeaderElector.holds_lease), every commit verifies lease
        # ownership immediately before binding and aborts + requeues when
        # deposed -- two live schedulers can never double-bind
        self.fencing_check: Optional[Callable[[], bool]] = None
        # set when an injected crash_between_assume_and_bind fired: the
        # process is "dead" -- the loop halts and NO cleanup runs
        self.crashed = False
        # multi-active partitioned scheduling (scheduler/partition.py):
        # when a coordinator is attached this stack owns a node-space
        # slice; event handlers, recovery sweeps, pop-time skips, and
        # commit fencing all consult it
        self.partition_coordinator = None
        # the conflict ledger: every typed bind conflict the committer
        # absorbs lands in exactly one disposition bucket --
        # requeued-for-retry or satisfied-elsewhere (the pod turned out
        # bound already). The tier-1 guard pins
        # absorbed == requeues + stale, so no conflict is silently lost.
        self.bind_conflicts_absorbed = 0
        self.conflict_requeues = 0
        self.conflict_stale_binds = 0
        # pods re-stamped and forwarded to a sibling partition because
        # their feasible nodes all live there
        self.pods_spilled = 0
        # -- multi-tenant fairness plane (scheduler/tenancy.py) ----------
        # the ResourceQuota admission gate (controllers/quota.py): when
        # attached, every popped pod charges its namespace ledger before
        # entering an attempt; exhausted namespaces park their pods
        # typed-QuotaExceeded. None = plane off (one is-None check).
        self.quota = None
        # the DRF dominant-share tracker: maintained from the bind
        # echoes (eventhandlers), consumed by the batched solve order
        self.tenant_shares = None
        self.quota_denials = 0
        # bind-ack ledger (scheduler/bindack.py): when attached, every
        # committed bind is pending until the node's Running ack arrives
        # over the watch; overdue pods are unbound back to the queue
        # (exactly once per incarnation). None = bind-and-forget.
        self.bind_ack_tracker = None

    # -- profile lookup (scheduler.go:741 profileForPod) --------------------

    def profile_for_pod(self, pod: Pod) -> Framework:
        prof = self.profiles.get(pod.spec.scheduler_name)
        if prof is None:
            raise KeyError(
                f"profile not found for scheduler name "
                f"{pod.spec.scheduler_name!r}"
            )
        return prof

    def _skip_pod_schedule(self, pod: Pod) -> bool:
        """scheduler.go:750 skipPodSchedule: deleting or already assumed.
        Also skips pods already CONFIRMED in the cache: a stale watch
        event (e.g. a pre-bind annotation write) can re-queue a pod that
        bound moments ago, and re-attempting it double-places it or --
        worse -- runs its failure/Unreserve path against the live
        placement's durable state."""
        if pod.metadata.deletion_timestamp is not None:
            return True
        if self.cache.is_assumed_pod(pod):
            return True
        if self.cache.has_pod_uid(pod.metadata.uid):
            return True
        coord = self.partition_coordinator
        if coord is not None and not coord.wants_pod(pod):
            # partitioned: the pod's home partition moved (spill
            # re-stamp, partition handoff) while it sat in our queue --
            # its new home stack schedules it
            return True
        return False

    # -- failure path (scheduler.go:375 recordSchedulingFailure) ------------

    def record_scheduling_failure(
        self,
        prof: Framework,
        pod_info: PodInfo,
        err_msg: str,
        reason: str,
        nominated_node: str,
        pod_scheduling_cycle: int,
        skip_backoff: bool = False,
    ) -> None:
        """``skip_backoff``: requeue straight to the activeQ -- used by
        the batched preemption path for pods whose failure was just
        resolved by the wave's own evictions (backoff exists to damp
        retries against a persistent failure, which this is not; the
        reference pays its 1s initial backoff here, scheduling_queue.go
        :643, purely because its preemption is asynchronous)."""
        pod = pod_info.pod
        # a requeued pod releases its in-flight quota charge (it
        # re-charges at its next pop): ``used`` stays bound + in-flight,
        # and the refund's headroom event may wake quota-parked peers
        self._quota_refund(pod, "requeue")
        prof.recorder.eventf(
            pod, "Warning", "FailedScheduling", err_msg
        )  # scheduler.go:378
        try:
            self.queue.add_unschedulable_if_not_present(
                pod_info, pod_scheduling_cycle, skip_backoff=skip_backoff
            )
        except KeyError:
            pass  # already requeued via an informer update
        if nominated_node:
            self.queue.update_nominated_pod_for_node(pod, nominated_node)
        if self.client is not None:
            try:
                def set_condition(p: Pod) -> None:
                    p.status.conditions = [
                        c for c in p.status.conditions if c.type != "PodScheduled"
                    ] + [
                        PodCondition(
                            type="PodScheduled",
                            status="False",
                            reason=reason,
                            message=err_msg,
                        )
                    ]
                    if nominated_node:
                        p.status.nominated_node_name = nominated_node

                self.client.update_pod_status(
                    pod.metadata.namespace, pod.metadata.name, set_condition
                )
            except Exception:
                logger.exception("updating pod condition for %s", pod.key())

    # -- multi-tenant quota gate (controllers/quota.py) ----------------------

    def _quota_refund(self, pod: Pod, reason: str) -> None:
        """Give back the pod's quota charge (no-op when the plane is
        off or the pod holds none); never raises -- a failed refund is
        parked on the controller's retry list, not lost."""
        qc = self.quota
        if qc is None:
            return
        try:
            qc.refund(pod, reason=reason)
        except Exception:
            logger.exception("quota refund for %s", pod.key())

    def _quota_admit(self, pod_info, pod_scheduling_cycle: int) -> bool:
        """The hard-quota admission gate, run once per popped pod when
        the plane is armed (callers check ``self.quota`` first, so the
        off state costs one is-None read). Granted pods proceed
        charged; exhausted namespaces park the pod typed-QuotaExceeded
        (released by quota/usage EVENTS, never polled). A transport
        failure fails CLOSED onto the backoff clock -- parking without
        a wake event would strand the pod."""
        qc = self.quota
        pod = pod_info.pod
        try:
            denial = qc.try_admit(pod)
        except Exception:  # noqa: BLE001 - injected api_unavailable etc.
            logger.exception("quota admission for %s", pod.key())
            prof = self.profiles.get(pod.spec.scheduler_name)
            if prof is not None:
                self.record_scheduling_failure(
                    prof, pod_info,
                    "quota admission check unavailable; retrying",
                    "QuotaError", "", pod_scheduling_cycle,
                )
            return False
        if not denial:
            return True
        self.quota_denials += 1
        self.queue.park_quota_exceeded(pod_info)
        qc.note_parked(pod, denial)
        prof = self.profiles.get(pod.spec.scheduler_name)
        if prof is not None:
            try:
                prof.recorder.eventf(
                    pod, "Warning", "FailedScheduling", denial
                )
            except Exception:  # noqa: BLE001 - events are best-effort
                pass
        return False

    # -- tenant dominant-share bookkeeping (scheduler/tenancy.py) ------------

    def note_pods_bound(self, pods: List[Pod]) -> None:
        """Bind echoes from the informer frames: the DRF tracker's
        incremental ``used`` update (covers our commits, sibling-stack
        commits, and the startup relist alike)."""
        tt = self.tenant_shares
        if tt is not None:
            tt.note_bound(pods)

    def note_pods_unbound(self, pods: List[Pod]) -> None:
        tt = self.tenant_shares
        if tt is not None:
            tt.note_unbound(pods)

    def note_node_capacity(self, node) -> None:
        """Node informer feed, ungated by partition ownership: the DRF
        capacity denominator stays cluster-wide in multi-active mode
        (ISSUE 18, residual 7(a))."""
        tt = self.tenant_shares
        if tt is not None:
            tt.note_node_capacity(node)

    def note_node_gone(self, name: str) -> None:
        tt = self.tenant_shares
        if tt is not None:
            tt.note_node_gone(name)

    # -- assume (scheduler.go:474) ------------------------------------------

    def assume(self, assumed: Pod, host: str) -> None:
        assumed.spec.node_name = host
        self.cache.assume_pod(assumed)
        self.queue.delete_nominated_pod_if_exists(assumed)

    # -- bind (scheduler.go:496) --------------------------------------------

    def _fence_ok(self) -> bool:
        """True when this scheduler may commit (no fencing configured, or
        the lease is verifiably still held). A False answer means the
        caller must abort the commit; the normal failure path then
        guarantees forget + Unreserve + requeue, and the pods land on
        whoever holds the lease now (their informers already queue
        them)."""
        check = self.fencing_check
        if check is None:
            return True
        try:
            return bool(check())
        except Exception:  # noqa: BLE001 - can't prove ownership: fence
            logger.exception("fencing check failed; aborting commit")
            return False

    def bind(
        self, prof: Framework, state: CycleState, assumed: Pod, host: str
    ) -> Optional[Status]:
        if not self._fence_ok():
            metrics.fencing_aborts.inc()
            flightrecorder.mark(
                "fencing_abort", pods=1, pod=assumed.metadata.uid
            )
            return Status.error(
                "lease lost before bind; commit fenced"
            )
        coord = self.partition_coordinator
        if coord is not None and not coord.may_bind(host):
            # partitioned commit fence on the per-pod path (Permit
            # waiters, custom binds): same fresh-probe rule as the bulk
            # committer; the binding cycle's failure path guarantees
            # forget + Unreserve + requeue
            metrics.fencing_aborts.inc()
            flightrecorder.mark(
                "fencing_abort", pods=1, pod=assumed.metadata.uid,
                fence="partition",
            )
            return Status.error(
                f"partition of node {host} not held at bind; fenced"
            )
        for extender in self.algorithm.extenders:
            if extender.is_binder() and extender.is_interested(assumed):
                try:
                    extender.bind(assumed, host)
                    self.cache.finish_binding(assumed)
                    return None
                except Exception as e:
                    return Status.error(str(e))
        status = self._bind_with_retry(prof, state, assumed, host)
        self.cache.finish_binding(assumed)
        if status is not None and status.code == StatusCode.SKIP:
            return Status.error("no bind plugin handled the pod")
        return status

    def _bind_with_retry(
        self, prof: Framework, state: CycleState, assumed: Pod, host: str
    ) -> Optional[Status]:
        """The bind plugins with retry-with-exponential-backoff around
        transient failures (API conflict/unavailable, injected
        bind_conflict). A terminal failure returns the error status; the
        binding cycle's existing failure path then guarantees forget +
        Unreserve + requeue -- a bind failure never strands a pod
        assumed-forever."""
        policy = self.bind_retry_policy
        attempt = 0
        while True:
            attempt += 1
            try:
                inj = get_injector()
                if inj is not None:
                    inj.raise_maybe(FaultPoint.BIND_CONFLICT)
                return prof.run_bind_plugins(state, assumed, host)
            except Exception as e:  # noqa: BLE001 - bind transport error
                # max_attempts counts TOTAL attempts (same semantics as
                # the solve ladder's in-place retries)
                if attempt >= max(1, policy.max_attempts):
                    return Status.error(
                        f"bind failed after {attempt} attempts: {e}"
                    )
                metrics.bind_retries.inc()
                self._retry_sleep(policy.backoff_for_attempt(attempt))

    # -- the loop -----------------------------------------------------------

    def schedule_one(self, timeout: Optional[float] = None) -> bool:
        """One iteration (scheduler.go:548). Returns False if no pod was
        popped (timeout/closed)."""
        pod_info = self.queue.pop(timeout=timeout)
        if pod_info is None:
            return False
        # skip-worthy pods (deleting / assumed / re-homed) must not
        # charge quota: attempt_schedule would drop them without a
        # failure path, so a charge here would never refund
        if self.quota is not None and not self._skip_pod_schedule(
            pod_info.pod
        ) and not self._quota_admit(
            pod_info, self.queue.scheduling_cycle
        ):
            return True  # parked typed-QuotaExceeded (or backoff-retried)
        self.attempt_schedule(pod_info)
        return True

    def handle_fit_error(
        self,
        prof: Framework,
        state: CycleState,
        pod_info: PodInfo,
        fit_err: FitError,
        pod_scheduling_cycle: int,
    ) -> None:
        """FitError branch of scheduleOne (scheduler.go:581-591):
        try preemption, then record the failure + nomination. In a
        partitioned stack, a pod that cannot place on OUR nodes spills
        to a sibling partition first -- its feasible nodes may simply
        live elsewhere; preemption and backoff apply only once every
        partition has had a look."""
        pod = pod_info.pod
        coord = self.partition_coordinator
        if coord is not None and coord.try_spill(pod):
            # re-homed to a sibling partition: ITS gate re-charges there
            self._quota_refund(pod, "spill")
            return
        nominated_node = ""
        if self.preemptor is not None:
            try:
                nominated_node = self.preemptor.preempt(
                    prof, state, pod, fit_err
                )
            except Exception:
                logger.exception("preemption for %s failed", pod.key())
        self.record_scheduling_failure(
            prof,
            pod_info,
            str(fit_err),
            "Unschedulable",
            nominated_node,
            pod_scheduling_cycle,
        )

    def attempt_schedule(self, pod_info: PodInfo) -> None:
        """Scheduling cycle for one popped pod: the body of scheduleOne."""
        pod_scheduling_cycle = self.queue.scheduling_cycle
        pod = pod_info.pod
        try:
            prof = self.profile_for_pod(pod)
        except KeyError as e:
            logger.error("%s", e)
            return
        if self._skip_pod_schedule(pod):
            return

        state = CycleState()
        state.write("__cycle_start__", time.perf_counter())
        timer = metrics.SinceTimer(metrics.scheduling_algorithm_duration)
        try:
            # poison-pod seam (robustness/faults.py): the sequential
            # path reproduces the reference's failure economics -- a
            # malformed pod fails ALONE here (SchedulerError -> requeue
            # with backoff), while batched dispatch needs the bisection
            # containment to get the same per-pod blast radius
            poison_raise_maybe(pod)
            result = self.algorithm.schedule(prof, state, pod)
        except FitError as fit_err:
            metrics.schedule_attempts.inc(result="unschedulable")
            self.handle_fit_error(
                prof, state, pod_info, fit_err, pod_scheduling_cycle
            )
            return
        except Exception as e:
            metrics.schedule_attempts.inc(result="error")
            logger.exception("scheduling %s failed", pod.key())
            self.record_scheduling_failure(
                prof, pod_info, str(e), "SchedulerError", "", pod_scheduling_cycle
            )
            return
        finally:
            timer.observe()
        self.finish_schedule(
            prof, state, pod_info, result.suggested_host, pod_scheduling_cycle
        )

    def reserve_assume_permit(
        self,
        prof: Framework,
        state: CycleState,
        pod_info: PodInfo,
        host: str,
        pod_scheduling_cycle: int,
    ) -> Optional[Pod]:
        """First half of the post-decision pipeline (scheduler.go:615-660):
        Reserve -> assume -> Permit. Returns the assumed pod on success
        (possibly parked in the Permit waiting map), None after a recorded
        failure. Shared by the sequential path and the batch commit."""
        pod = pod_info.pod
        assumed = pod.assumed_clone()

        # Reserve
        status = prof.run_reserve_plugins(state, assumed, host)
        if status is not None and not status.is_success():
            self.record_scheduling_failure(
                prof, pod_info, status.message(), "SchedulerError", "",
                pod_scheduling_cycle,
            )
            return None

        # Assume: the pod occupies the node in cache from here on.
        try:
            self.assume(assumed, host)
        except Exception as e:
            prof.run_unreserve_plugins(state, assumed, host)
            self.record_scheduling_failure(
                prof, pod_info, str(e), "SchedulerError", "", pod_scheduling_cycle
            )
            return None

        # Permit
        status = prof.run_permit_plugins(state, assumed, host)
        if (
            status is not None
            and not status.is_success()
            and status.code != StatusCode.WAIT
        ):
            reason = (
                "Unschedulable" if status.is_unschedulable() else "SchedulerError"
            )
            self._forget(assumed)
            prof.run_unreserve_plugins(state, assumed, host)
            self.record_scheduling_failure(
                prof, pod_info, status.message(), reason, "", pod_scheduling_cycle
            )
            return None
        return assumed

    def finish_schedule(
        self,
        prof: Framework,
        state: CycleState,
        pod_info: PodInfo,
        host: str,
        pod_scheduling_cycle: int,
    ) -> None:
        """Post-decision pipeline (scheduler.go:615-738): Reserve ->
        assume -> Permit -> async binding cycle. Shared by the sequential
        path and the TPU batch solver (which replaces only the
        filter/score/select stage)."""
        assumed = self.reserve_assume_permit(
            prof, state, pod_info, host, pod_scheduling_cycle
        )
        if assumed is None:
            return

        # Binding cycle: async goroutine in the reference (scheduler.go:666).
        if self._bind_pool is not None:
            with self._inflight_lock:
                self._inflight_binds += 1
            self._bind_pool.submit(
                self._binding_cycle_safe,
                prof,
                state,
                pod_info,
                assumed,
                host,
                pod_scheduling_cycle,
            )
        else:
            self._binding_cycle(
                prof, state, pod_info, assumed, host, pod_scheduling_cycle
            )
        return

    def _binding_cycle_safe(self, *args) -> None:
        try:
            self._binding_cycle(*args)
        except SchedulerCrashed:
            self._simulate_crash()
        except Exception:
            logger.exception("binding cycle crashed")
        finally:
            with self._inflight_lock:
                self._inflight_binds -= 1
                self._inflight_lock.notify_all()

    def _simulate_crash(self) -> None:
        """The crash_between_assume_and_bind point fired: the process is
        dead from here. Halt the scheduling loop and run NO cleanup --
        the assumed pod stays assumed, nothing is requeued; recovery is
        the next incarnation's job (it relists, adopts bound pods, and
        requeues the in-flight ones)."""
        logger.error(
            "injected crash between assume and bind; halting scheduler "
            "with no cleanup"
        )
        self.crashed = True
        self._stop.set()

    def _binding_cycle(
        self,
        prof: Framework,
        state: CycleState,
        pod_info: PodInfo,
        assumed: Pod,
        host: str,
        pod_scheduling_cycle: int,
    ) -> None:
        """scheduler.go:666-738: WaitOnPermit -> PreBind -> bind -> PostBind."""
        status = prof.wait_on_permit(assumed)
        if status is not None and not status.is_success():
            reason = (
                "Unschedulable" if status.is_unschedulable() else "SchedulerError"
            )
            self._forget(assumed)
            prof.run_unreserve_plugins(state, assumed, host)
            self.record_scheduling_failure(
                prof, pod_info, status.message(), reason, "", pod_scheduling_cycle
            )
            return

        status = prof.run_pre_bind_plugins(state, assumed, host)
        if status is not None and not status.is_success():
            self._forget(assumed)
            prof.run_unreserve_plugins(state, assumed, host)
            self.record_scheduling_failure(
                prof, pod_info, status.message(), "SchedulerError", "",
                pod_scheduling_cycle,
            )
            return

        inj = get_injector()
        if inj is not None:
            # the pod is assumed but not yet bound: exactly the window a
            # process death strands (restart e2e drives this point)
            inj.crash_maybe(FaultPoint.CRASH_BETWEEN_ASSUME_AND_BIND)
        bind_timer = metrics.SinceTimer(metrics.binding_duration)
        status = self.bind(prof, state, assumed, host)
        bind_timer.observe()
        if status is not None and not status.is_success():
            metrics.schedule_attempts.inc(result="error")
            self._forget(assumed)
            prof.run_unreserve_plugins(state, assumed, host)
            self.record_scheduling_failure(
                prof, pod_info, status.message(), "SchedulerError", "",
                pod_scheduling_cycle,
            )
            return
        self._record_bind_success(prof, state, pod_info, assumed, host)

    def _record_bind_success(
        self,
        prof: Framework,
        state: CycleState,
        pod_info: PodInfo,
        assumed: Pod,
        host: str,
    ) -> None:
        prof.run_post_bind_plugins(state, assumed, host)
        prof.recorder.eventf(
            assumed, "Normal", "Scheduled",
            f"Successfully assigned "
            f"{assumed.metadata.namespace}/{assumed.metadata.name} to "
            f"{host}",
        )  # scheduler.go:544
        metrics.schedule_attempts.inc(result="scheduled")
        metrics.pod_scheduling_attempts.observe(pod_info.attempts)
        # PodInfo timestamps come from the queue's monotonic clock
        now = time.monotonic()
        if pod_info.initial_attempt_timestamp:
            duration = max(
                0.0, now - pod_info.initial_attempt_timestamp
            )
            metrics.pod_scheduling_duration.observe(duration)
            metrics.observe_pod_to_bind(duration)
        try:
            cycle_start = state.read("__cycle_start__")
        except KeyError:
            pass
        else:
            metrics.e2e_scheduling_duration.observe(
                max(0.0, time.perf_counter() - cycle_start)
            )

    def _forget(self, assumed: Pod) -> None:
        try:
            self.cache.forget_pod(assumed)
        except Exception:
            logger.exception("forgetting pod %s", assumed.key())

    def wait_for_inflight_binds(self, timeout: float = 30.0) -> bool:
        """Test/bench helper: block until async binding cycles drain."""
        deadline = time.monotonic() + timeout
        with self._inflight_lock:
            while self._inflight_binds > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._inflight_lock.wait(remaining)
        return True

    def run(self) -> None:
        """Blocking loop (scheduler.go:363)."""
        self.queue.run()
        while not self._stop.is_set():
            self.schedule_one(timeout=0.5)

    def start(self) -> threading.Thread:
        t = threading.Thread(target=self.run, name="scheduler", daemon=True)
        t.start()
        return t

    def stop(self) -> None:
        self._stop.set()
        self.queue.close()
        if self.bind_ack_tracker is not None:
            self.bind_ack_tracker.stop()
        broadcaster = getattr(self, "event_broadcaster", None)
        if broadcaster is not None:
            # let in-flight binding cycles record their events before the
            # broadcaster drains and exits (bounded: shutdown must not
            # hang on a stuck bind)
            self.wait_for_inflight_binds(timeout=5.0)
        if self._bind_pool is not None:
            self._bind_pool.shutdown(wait=False)
        if broadcaster is not None:
            broadcaster.stop()


def new_scheduler(
    client: Client,
    informer_factory: InformerFactory,
    profiles: Optional[List[KubeSchedulerProfile]] = None,
    out_of_tree_registry: Optional[Registry] = None,
    percentage_of_nodes_to_score: int = 0,
    async_binding: bool = True,
    cache_ttl_seconds: float = 30.0,
    rng=None,
    batch: bool = False,
    max_batch: int = 256,
    solver_config=None,
    solver_mode: str = "greedy",
    mesh=None,
    extenders: Optional[List] = None,
    robustness_config=None,
    containment_config=None,
    bind_ack_config=None,
) -> Scheduler:
    """Build a fully wired scheduler (reference scheduler.go:223 New +
    factory.go create). ``batch=True`` selects the TPU batch-solver loop
    (the out-of-tree ``tpu-jax`` profile of the north star)."""
    registry = new_in_tree_registry()
    registry.merge(out_of_tree_registry)

    if not profiles:
        profiles = [KubeSchedulerProfile()]

    cache = SchedulerCache(ttl_seconds=cache_ttl_seconds)
    snapshot = Snapshot()

    frameworks: Dict[str, Framework] = {}
    built_extenders = []
    for ext in extenders or []:
        if hasattr(ext, "url_prefix"):  # ExtenderConfig -> HTTPExtender
            from kubernetes_tpu.scheduler.extender import HTTPExtender

            built_extenders.append(HTTPExtender(ext))
        else:
            built_extenders.append(ext)

    algorithm = GenericScheduler(
        cache,
        snapshot,
        percentage_of_nodes_to_score=percentage_of_nodes_to_score,
        rng=rng,
        extenders=built_extenders,
    )
    from kubernetes_tpu.scheduler.metrics_recorder import MetricsRecorder
    from kubernetes_tpu.utils.event_recorder import EventBroadcaster

    recorder = MetricsRecorder()
    broadcaster = (
        EventBroadcaster(client.server) if client is not None else None
    )
    for profile_cfg in profiles:
        plugins = default_plugins()
        # prune defaults to registered plugins so the provider list can name
        # plugins that land in later stages
        plugins = _prune_unregistered(plugins, registry)
        plugins = plugins.apply(profile_cfg.plugins)
        fw = Framework(
            registry,
            plugins,
            plugin_config=profile_cfg.plugin_config,
            client=client,
            snapshot_provider=lambda: snapshot,
            informers=informer_factory,
            metrics_recorder=recorder,
            # per-profile recorder, source = schedulerName (profile.go:39)
            recorder=(
                broadcaster.new_recorder(profile_cfg.scheduler_name)
                if broadcaster is not None
                else None
            ),
        )
        frameworks[profile_cfg.scheduler_name] = fw

    first_fw = next(iter(frameworks.values()))
    queue = PriorityQueue(
        first_fw.queue_sort_less_func(),
        sort_key_func=first_fw.queue_sort_key_func(),
    )
    algorithm.nominated_pods_lister = queue

    if batch:
        from kubernetes_tpu.ops.assignment import GreedyConfig
        from kubernetes_tpu.scheduler.batch import BatchScheduler

        sched: Scheduler = BatchScheduler(
            cache,
            queue,
            algorithm,
            frameworks,
            client=client,
            async_binding=async_binding,
            max_batch=max_batch,
            solver_config=solver_config or GreedyConfig(),
            solver_mode=solver_mode,
            mesh=mesh,
            robustness_config=robustness_config,
            containment_config=containment_config,
        )
    else:
        sched = Scheduler(
            cache,
            queue,
            algorithm,
            frameworks,
            client=client,
            async_binding=async_binding,
        )
        if robustness_config is not None:
            # the sequential path has no ladder, but its bind retries
            # must still honor the configured policy (the batch path
            # inherits it from the ladder's config)
            sched.bind_retry_policy = robustness_config.retry
            sched._retry_sleep = robustness_config.sleep
    from kubernetes_tpu.scheduler.eventhandlers import add_all_event_handlers
    from kubernetes_tpu.scheduler.preemption import Preemptor

    sched.preemptor = Preemptor(algorithm, queue, client)
    if batch:
        # the wave ladder mirrors the batch solver's robustness config
        # (watchdog/retry/breaker knobs, injectable sleep) with its OWN
        # breakers: a sick preemption path degrades independently of --
        # and never poisons -- the main solve tiers
        from kubernetes_tpu.robustness.ladder import SolverLadder

        sched.preemptor.ladder = SolverLadder(sched.ladder.config)
    sched.event_broadcaster = broadcaster
    # the bind-ack ledger must exist BEFORE handler registration: the
    # eventhandlers capture it once and feed it the Running-ack frames
    if (
        bind_ack_config is not None
        and getattr(bind_ack_config, "enabled", False)
        and client is not None
    ):
        from kubernetes_tpu.scheduler.bindack import BindAckTracker

        sched.bind_ack_tracker = BindAckTracker(
            client,
            ack_timeout_seconds=bind_ack_config.ack_timeout_seconds,
            sweep_interval_seconds=bind_ack_config.sweep_interval_seconds,
            node_suspect_threshold=bind_ack_config.node_suspect_threshold,
            taint_suspect_nodes=bind_ack_config.taint_suspect_nodes,
        )
        sched.bind_ack_tracker.start()
    add_all_event_handlers(sched, informer_factory)
    # materialize every plugin-consumed informer BEFORE factory start so
    # listers are synced by WaitForCacheSync (reference factory.go shape)
    for accessor in (
        "pdbs", "pod_groups", "services", "replication_controllers",
        "replica_sets", "stateful_sets", "persistent_volumes",
        "persistent_volume_claims", "storage_classes", "csi_nodes",
    ):
        getattr(informer_factory, accessor)()
    return sched


def new_scheduler_from_config(
    client: Client,
    informer_factory: InformerFactory,
    cfg,
    out_of_tree_registry: Optional[Registry] = None,
    rng=None,
) -> Scheduler:
    """Build the scheduler straight from a KubeSchedulerConfiguration
    (config/loader.py), including this build's tpuSolver block: batch
    mode, maxBatch, solverMode, and an n-device jax.sharding.Mesh when
    meshDevices > 0 (VERDICT r2 missing #8: these knobs were
    constructor-only)."""
    from kubernetes_tpu.config.validation import validate_config

    errors = validate_config(cfg)
    if errors:
        raise ValueError(
            "invalid KubeSchedulerConfiguration: " + "; ".join(errors)
        )
    ts = cfg.tpu_solver
    mesh = None
    if ts.enabled and ts.mesh_devices > 0:
        import jax
        from jax.sharding import Mesh

        devices = jax.devices()
        if len(devices) < ts.mesh_devices:
            raise ValueError(
                f"tpuSolver.meshDevices={ts.mesh_devices} but only "
                f"{len(devices)} devices are visible"
            )
        mesh = Mesh(
            np.array(devices[: ts.mesh_devices]), axis_names=("nodes",)
        )
    from kubernetes_tpu.robustness.containment import ContainmentConfig
    from kubernetes_tpu.robustness.faults import (
        injector_from_configuration,
        install_injector,
    )
    from kubernetes_tpu.robustness.ladder import RobustnessConfig

    sched = new_scheduler(
        client,
        informer_factory,
        profiles=cfg.profiles or None,
        out_of_tree_registry=out_of_tree_registry,
        percentage_of_nodes_to_score=cfg.percentage_of_nodes_to_score,
        rng=rng,
        batch=ts.enabled,
        max_batch=ts.max_batch,
        solver_mode=ts.solver_mode,
        mesh=mesh,
        extenders=list(getattr(cfg, "extenders", [])),
        robustness_config=RobustnessConfig.from_configuration(
            cfg.robustness
        ),
        containment_config=ContainmentConfig.from_configuration(
            cfg.containment
        ),
        bind_ack_config=getattr(cfg, "bind_ack", None),
    )
    if ts.enabled:
        sched.batch_window = ts.batch_window_seconds
    apply_streaming_config(
        sched, cfg, informer_factory, batch=ts.enabled,
        max_batch=ts.max_batch,
    )
    injector = injector_from_configuration(cfg.fault_injection)
    if injector is not None:
        install_injector(injector)
    return sched


def apply_streaming_config(
    sched: Scheduler,
    cfg,
    informer_factory: InformerFactory,
    *,
    batch: bool,
    max_batch: int,
) -> None:
    """Wire the ``streaming:`` block onto a built scheduler -- shared
    by ``new_scheduler_from_config`` and ``SchedulerApp`` (which builds
    through ``new_scheduler`` directly): the priority-band threshold
    arms queue jumping on ANY scheduler (the band lives in the queue),
    and the SLO-adaptive controller replaces the static batchWindow/
    maxBatch behavior on the batch path (streaming/autobatch.py)."""
    st = getattr(cfg, "streaming", None)
    if st is None or not st.enabled:
        return
    if st.band_priority_threshold is not None:
        sched.queue.band_threshold = st.band_priority_threshold
    if getattr(st, "band_priority_class", ""):
        # PriorityClass OBJECTS -- not raw integers -- select the
        # band: the named class's value arms the threshold, and a
        # PriorityClass update re-arms it live (the admission
        # classifier stamps each pod's resolved priority at ingest,
        # so the queue compares memo reads against this value)
        _wire_band_priority_class(
            sched, informer_factory, st.band_priority_class,
            fallback=st.band_priority_threshold,
        )
    if batch:
        from kubernetes_tpu.streaming.autobatch import (
            AutoBatchController,
        )

        sched.attach_autobatch(AutoBatchController(
            slo_p99_seconds=st.slo_p99_seconds,
            min_window=st.min_window_seconds,
            max_window=st.max_window_seconds,
            latency_batch=st.latency_batch,
            max_batch=max_batch,
            interval_seconds=st.controller_interval_seconds,
            auto_rungs=getattr(st, "auto_rungs", False),
        ))


def _wire_band_priority_class(
    sched: Scheduler,
    informer_factory: InformerFactory,
    class_name: str,
    fallback: Optional[int] = None,
) -> None:
    """Arm (and live-track) the streaming band threshold from a named
    PriorityClass object: add/update events for that class set
    ``queue.band_threshold`` to its value; deleting it reverts to the
    configured raw ``bandPriorityThreshold`` integer (None when unset:
    band off). Registered before factory start so the initial list
    replay arms the threshold at sync."""
    from kubernetes_tpu.client.informer import ResourceEventHandler

    def _apply(*args) -> None:
        obj = args[-1]
        if obj.metadata.name == class_name:
            sched.queue.band_threshold = int(obj.value)

    def _disarm(obj) -> None:
        if obj.metadata.name == class_name:
            sched.queue.band_threshold = fallback

    informer_factory.priority_classes().add_event_handler(
        ResourceEventHandler(
            on_add=_apply, on_update=_apply, on_delete=_disarm
        )
    )


def _prune_unregistered(plugins: Plugins, registry: Registry) -> Plugins:
    out = Plugins()
    for point in Plugins.EXTENSION_POINTS:
        ps = getattr(plugins, point)
        setattr(
            out,
            point,
            type(ps)(
                enabled=[p for p in ps.enabled if p.name in registry],
                disabled=list(ps.disabled),
            ),
        )
    return out
