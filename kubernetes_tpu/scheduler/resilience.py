"""Control-plane resilience: crash recovery, the assumed-pod TTL sweeper,
and the cache<->apiserver drift checker.

Reference analogues:

- ``recover_on_startup``: the new-leader resume semantics
  (server.go:241) -- nothing is persisted by the scheduler; a fresh
  incarnation relists, ADOPTS pods already bound by its predecessor, and
  requeues pods that died mid-flight (assumed but never bound, which the
  apiserver still shows as pending). This function runs after the
  informers' initial sync and verifies/meters that rebuild.
- ``ControlPlaneReconciler``: the reference's cleanupAssumedPods
  goroutine (cache.go run every 1s) -- dead code here since the seed
  (``cleanup_expired_assumed_pods`` had zero callers) -- plus a drift
  checker in the spirit of the cache comparer (internal/cache/debugger),
  promoted from a debug endpoint to a self-healing sweep: divergence
  between the cache and a fresh apiserver list is healed in place and
  counted in ``scheduler_cache_drift_total``.

Everything is observable: adoption, requeues, expiries, and every healed
divergence land in metrics (utils/metrics.py), because a failover or
restart must be as rehearsed -- and as visible -- as a solver fault.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Dict, List, TYPE_CHECKING

from kubernetes_tpu.api.types import Node, ObjectMeta
from kubernetes_tpu.utils import metrics

if TYPE_CHECKING:
    from kubernetes_tpu.client.client import Client
    from kubernetes_tpu.scheduler.scheduler import Scheduler

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# crash recovery
# ---------------------------------------------------------------------------


@dataclass
class RecoveryReport:
    adopted: int = 0  # bound pods inherited from the previous incarnation
    requeued: int = 0  # pending pods (incl. predecessor's in-flight ones)
    healed: int = 0  # bound pods the informer sync somehow missed


def _attach_volume_counts(sched, pod) -> None:
    """Every direct cache adoption must resolve the pod's attachable-
    volume counts first (BatchScheduler.attach_volume_counts), or the
    re-adopted pod's attaches go uncounted in NodeInfo.volume_in_use and
    the device volume-limit columns over-admit past the node's CSINode
    allocatable. The normal informer path does this in eventhandlers;
    recovery/heal paths bypass it, so mirror it here."""
    attach = getattr(sched, "attach_volume_counts", None)
    if attach is not None:
        attach(pod)


def recover_on_startup(sched: "Scheduler", client: "Client") -> RecoveryReport:
    """Verify + meter the post-restart rebuild against apiserver ground
    truth. The informers' list+watch already rebuilt cache and queue; this
    pass catches anything that slipped (a bound pod missing from the
    cache is re-adopted directly) and publishes the adoption counts a
    restarted control plane is judged by."""
    report = RecoveryReport()
    try:
        pods, _ = client.list_pods()
    except Exception:
        # apiserver unavailable at startup (injected or real): the
        # informers' relist-retry machinery still converges the caches;
        # recovery just goes unmetered for this incarnation
        logger.exception("startup recovery list failed; skipping")
        return report
    # partitioned stack: recovery is scoped to the held partitions --
    # a sibling's pods and nodes are its incarnation's job
    coord = getattr(sched, "partition_coordinator", None)
    for pod in pods:
        if pod.spec.node_name:
            if coord is not None and not coord.owns_node(
                pod.spec.node_name
            ):
                continue
            report.adopted += 1
            if sched.cache.get_pod(pod) is None:
                # informer sync missed it (watch raced the relist): adopt
                try:
                    _attach_volume_counts(sched, pod)
                    sched.cache.add_pod(pod)
                    report.healed += 1
                except Exception:
                    logger.exception("adopting bound pod %s", pod.key())
        elif (
            pod.spec.scheduler_name in sched.profiles
            and pod.metadata.deletion_timestamp is None
            and (coord is None or coord.wants_pod(pod))
        ):
            # pending: either genuinely new or a predecessor's
            # assumed-but-never-bound in-flight pod -- both are pending
            # at the apiserver and must (re)enter the queue. The keyed
            # activeQ makes this idempotent against the informer's add.
            report.requeued += 1
            try:
                sched.queue.add(pod)
            except Exception:
                logger.exception("requeueing pending pod %s", pod.key())
    if report.adopted:
        metrics.pods_adopted_on_restart.inc(report.adopted)
    if report.requeued:
        metrics.pods_requeued_on_restart.inc(report.requeued)
    logger.info(
        "startup recovery: adopted %d bound pod(s) (%d healed into the "
        "cache), requeued %d pending pod(s)",
        report.adopted, report.healed, report.requeued,
    )
    return report


# ---------------------------------------------------------------------------
# the sweeper: assumed-pod TTL expiry + drift checking
# ---------------------------------------------------------------------------


@dataclass
class DriftReport:
    """One drift check's findings (already healed when returned)."""

    pods_readopted: int = 0  # bound in API, missing from cache
    pods_evicted: int = 0  # in cache, gone from / not bound in API
    pods_requeued: int = 0  # evicted pods still pending in API
    nodes_added: int = 0
    nodes_removed: int = 0

    def total(self) -> int:
        return (
            self.pods_readopted + self.pods_evicted
            + self.nodes_added + self.nodes_removed
        )


class ControlPlaneReconciler:
    """Periodic sweeper thread: every ``sweep_interval`` expire assumed
    pods whose binding finished > TTL ago (the confirmation never
    arrived); every ``drift_interval`` diff cache state against a fresh
    apiserver list and heal divergence in place.

    Healing actions reuse the exact informer-driven cache entry points
    (add_pod/remove_pod/add_node/remove_node), so a heal that races the
    real watch event degenerates to a no-op on whichever side lands
    second."""

    def __init__(
        self,
        sched: "Scheduler",
        client: "Client",
        sweep_interval: float = 1.0,
        drift_interval: float = 5.0,
        carry_audit_interval: float = 2.0,
    ) -> None:
        self.sched = sched
        self.client = client
        self.sweep_interval = max(0.01, sweep_interval)
        self.drift_interval = max(self.sweep_interval, drift_interval)
        self.carry_audit_interval = max(
            self.sweep_interval, carry_audit_interval
        )
        self._stop = threading.Event()
        self._thread = None
        self.sweeps = 0
        self.drift_checks = 0
        self.carry_audits = 0

    # -- assumed-pod TTL expiry (the formerly dead cache path) --------------

    def sweep_assumed_once(self) -> List:
        """Run the cache's TTL expiry and route each expired pod by
        apiserver ground truth: still pending -> requeue for another
        attempt; actually bound (the bind landed but its confirmation
        was lost) -> re-adopt; deleted -> nothing to do."""
        expired = self.sched.cache.cleanup_expired_assumed_pods()
        for pod in expired:
            node_removed = pod.__dict__.pop("_node_removed_expired", False)
            metrics.assumed_pods_expired.inc()
            if node_removed:
                logger.warning(
                    "assumed pod %s fast-expired (node %s deleted "
                    "mid-bind)", pod.key(), pod.spec.node_name,
                )
            else:
                logger.warning(
                    "assumed pod %s expired (binding finished, "
                    "confirmation never arrived)", pod.key(),
                )
            try:
                live = self.client.get_pod(
                    pod.metadata.namespace, pod.metadata.name
                )
            except KeyError:
                continue  # deleted while assumed: already forgotten
            except Exception:
                logger.exception("checking expired pod %s", pod.key())
                continue
            try:
                if live.spec.node_name:
                    _attach_volume_counts(self.sched, live)
                    self.sched.cache.add_pod(live)
                    metrics.cache_drift.inc(kind="pod", action="readopt")
                else:
                    self.sched.queue.add(live)
                    if node_removed:
                        metrics.node_removed_requeues.inc()
            except Exception:
                logger.exception("routing expired pod %s", pod.key())
        return expired

    # -- drift checking ------------------------------------------------------

    def check_drift_once(self) -> DriftReport:
        report = DriftReport()
        cache = self.sched.cache
        try:
            pods, _ = self.client.list_pods()
            nodes, _ = self.client.list_nodes()
        except Exception:
            logger.exception("drift check list failed; will retry")
            return report
        # partitioned stack: the cache legitimately excludes foreign
        # partitions, so the drift sweep only compares the owned slice
        # (healing a sibling's nodes in would phantom-double capacity)
        coord = getattr(self.sched, "partition_coordinator", None)
        if coord is not None:
            nodes = [
                n for n in nodes if coord.owns_node_obj(n)
            ]
            pods = [
                p for p in pods
                if (
                    coord.owns_node(p.spec.node_name)
                    if p.spec.node_name
                    else coord.wants_pod(p)
                )
            ]
        cached = cache.pod_states_snapshot()
        api_bound: Dict[str, object] = {
            p.metadata.uid: p for p in pods if p.spec.node_name
        }

        def fresh(pod):
            """Per-pod re-read at heal time. The list above happened
            BEFORE the cache snapshot, so a pod that bound (or was
            deleted) in between looks divergent on stale evidence; a
            heal moves real capacity, so it only acts on a fresh read.
            Returns (ok, live): ok False = unverifiable, skip."""
            try:
                return True, self.client.get_pod(
                    pod.metadata.namespace, pod.metadata.name
                )
            except KeyError:
                return True, None  # genuinely gone
            except Exception:
                logger.exception("drift re-check for %s", pod.key())
                return False, None

        # bound in the API but missing from the cache: the scheduler is
        # blind to real capacity consumption -- re-adopt
        for uid, pod in api_bound.items():
            if uid in cached:
                continue
            ok, live = fresh(pod)
            if not ok or live is None or not live.spec.node_name:
                continue  # deleted/unbound since the list: not drift
            try:
                _attach_volume_counts(self.sched, live)
                cache.add_pod(live)
                report.pods_readopted += 1
                metrics.cache_drift.inc(kind="pod", action="readopt")
            except Exception:
                logger.exception("re-adopting drifted pod %s", pod.key())

        # in the cache but the API disagrees: phantom capacity. Assumed
        # entries are the scheduler's own in-flight overlay -- NEVER
        # healed here (the TTL sweep owns their lifecycle).
        for uid, (pod, assumed) in cached.items():
            if assumed or uid in api_bound:
                continue
            ok, live = fresh(pod)
            if not ok:
                continue
            if (
                live is not None
                and live.metadata.uid == uid
                and live.spec.node_name
            ):
                continue  # bound between the list and the snapshot
            try:
                cache.remove_pod(pod)
                report.pods_evicted += 1
                metrics.cache_drift.inc(kind="pod", action="evict")
            except Exception:
                logger.exception("evicting drifted pod %s", pod.key())
                continue
            if (
                live is not None
                and live.metadata.uid == uid
                and live.spec.scheduler_name in self.sched.profiles
                and live.metadata.deletion_timestamp is None
                and (coord is None or coord.wants_pod(live))
            ):
                # the pod still wants scheduling (cache wrongly believed
                # it placed): give it back to the queue
                try:
                    self.sched.queue.add(live)
                    report.pods_requeued += 1
                    metrics.cache_drift.inc(kind="pod", action="requeue")
                except Exception:
                    logger.exception("requeueing drifted pod %s", pod.key())

        api_nodes = {n.metadata.name: n for n in nodes}
        cached_nodes = set(cache.known_node_names())
        for name, node in api_nodes.items():
            if name not in cached_nodes:
                try:
                    cache.add_node(node)
                    report.nodes_added += 1
                    metrics.cache_drift.inc(kind="node", action="add")
                except Exception:
                    logger.exception("adding drifted node %s", name)
        for name in cached_nodes - set(api_nodes):
            try:
                cache.remove_node(
                    Node(metadata=ObjectMeta(name=name, namespace=""))
                )
                report.nodes_removed += 1
                metrics.cache_drift.inc(kind="node", action="remove")
            except Exception:
                logger.exception("removing drifted node %s", name)
        if report.total():
            logger.warning(
                "drift check healed %d divergence(s): +%d/-%d pods "
                "(%d requeued), +%d/-%d nodes",
                report.total(), report.pods_readopted, report.pods_evicted,
                report.pods_requeued, report.nodes_added,
                report.nodes_removed,
            )
        return report

    # -- carry integrity audit (blast-radius containment, ISSUE 14) ---------

    def audit_carry_once(self) -> str:
        """Run the batch scheduler's device-carry integrity audit
        (BatchScheduler.audit_carry): cheap on-device checksums of the
        resident req/nzr/alloc/valid state against the host shadow,
        full compare + counted-upload heal only on mismatch. A plain
        (non-batch) scheduler has no carry; returns "unsupported"
        then."""
        audit = getattr(self.sched, "audit_carry", None)
        if audit is None:
            return "unsupported"
        return audit()

    # -- the loop ------------------------------------------------------------

    def _run(self) -> None:
        next_drift = self.drift_interval
        next_audit = self.carry_audit_interval
        elapsed = 0.0
        while not self._stop.wait(self.sweep_interval):
            elapsed += self.sweep_interval
            try:
                self.sweep_assumed_once()
                self.sweeps += 1
            except Exception:
                logger.exception("assumed-pod sweep failed")
            if elapsed >= next_audit:
                next_audit = elapsed + self.carry_audit_interval
                try:
                    if self.audit_carry_once() != "unsupported":
                        self.carry_audits += 1
                except Exception:
                    logger.exception("carry integrity audit failed")
            if elapsed >= next_drift:
                next_drift = elapsed + self.drift_interval
                try:
                    self.check_drift_once()
                    self.drift_checks += 1
                except Exception:
                    logger.exception("drift check failed")

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="cp-reconciler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
