"""Hot-path admission classification for the batch dispatcher.

The round-5 regression (VERDICT.md: 24,544 -> 18,490 pods/s) came from
re-deriving the solver-admission decision per pod per dispatch cycle:
``solver_supported`` walked NUMA annotations, spread constraints, and
volume sources, and ``volumes_device_safe`` resolved PVC -> PV through
the listers, all inside ``schedule_batch``'s pop loop. This module
computes the whole classification ONCE -- at informer ingest
(scheduler/eventhandlers.py calls ``BatchScheduler.classify_pod`` when a
pending pod enters the queue) -- and caches the result on the pod object
(``pod.__dict__["_admission"]``), so pop -> dispatch is a memo read.

An ``Admission`` record carries three things:

- the routing decision: ``device_ok`` plus a ``reason`` string for the
  host path ("numa-aligned", "direct-volume-source", "unbound-pvc",
  "extender-interested", ...), and the derived ``klass`` ("device" /
  "constrained" / "host") for observability;
- the pod's resolved attachable-volume counts (``vol_counts``), which
  feed the ``[N, R]`` volume-limit columns (tensors/node_tensor.py) and
  the node in-use accounting (cache/node_info.py);
- per-pod feature bits (hard spread, host ports, required (anti-)
  affinity, scoring terms, gang membership) so ``_dispatch_solve``'s
  batch-level aggregates are ``any()`` over memo bits instead of
  repeated spec walks.

Staleness: the spec-derived bits are keyed by object identity (an
updated pod arrives as a NEW object from the informer, so it simply has
no memo). Volume classification additionally depends on PVC/PV/
StorageClass/CSINode state that mutates WITHOUT replacing the pod
object, so records for PVC-bearing pods stamp the scheduler's
volume-topology generation (bumped by every storage-object event) and
are re-classified at pop time when it moved -- a PVC binding landing
mid-queue re-routes the pod instead of dispatching it under the stale
class. Records are also pinned to a per-scheduler token so a memo from
another scheduler instance (different extenders, different dims
registry) is never trusted.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from kubernetes_tpu.api.types import (
    POD_GROUP_LABEL,
    Pod,
    RESOURCE_CPU,
    RESOURCE_EPHEMERAL_STORAGE,
    RESOURCE_MEMORY,
    RESOURCE_PODS,
)
from kubernetes_tpu.cache.node_info import (
    DEFAULT_MEMORY_REQUEST,
    DEFAULT_MILLI_CPU_REQUEST,
    pod_hot_info,
)
from kubernetes_tpu.plugins.numa import ALIGNED_ANNOTATION
from kubernetes_tpu.tensors.node_tensor import _kib_ceil, stamp_pack_row


def solver_unsupported_reason(pod: Pod) -> str:
    """The pure-spec slice of admission: constraint shapes the device
    solver does not model (see scheduler/batch.py module docstring).
    Returns "" when the spec is solver-supported."""
    spec = pod.spec
    # single-NUMA-aligned extended resources keep the host path: the
    # per-node best-fit group bookkeeping (plugins/numa.py) is stateful
    # per placement in ways the batch replay does not model
    if pod.metadata.annotations.get(ALIGNED_ANNOTATION):
        return "numa-aligned"
    # soft spread with node scoping can't share score groups
    # (ops/topology._eligibility_sig covers only hard spread)
    if any(
        c.when_unsatisfiable != "DoNotSchedule"
        for c in spec.topology_spread_constraints
    ) and (
        spec.node_selector
        or (
            spec.affinity is not None
            and spec.affinity.node_affinity is not None
        )
    ):
        return "soft-spread-node-scoped"
    # direct in-tree sources carry VolumeRestrictions mount-CONFLICT
    # semantics (pairwise identity) the count columns can't express
    for v in spec.volumes:
        if (
            v.gce_pd_name or v.aws_ebs_volume_id
            or v.iscsi_target or v.rbd_image
        ):
            return "direct-volume-source"
    return ""


class Admission:
    """One pod's precomputed admission classification (see module
    docstring). Slotted: one record per pending pod on the hot path."""

    __slots__ = (
        "device_ok", "reason", "vol_counts", "has_pvc", "volume_gen",
        "pinned", "token", "hard_spread", "ports", "affinity_req",
        "required_anti", "scoring_terms", "score_pref", "score_soft",
        "gang",
    )

    def __init__(self) -> None:
        self.device_ok = True
        self.reason = ""
        self.vol_counts: Tuple = ()
        self.has_pvc = False
        self.volume_gen = 0
        self.pinned = False
        self.token: Optional[object] = None
        self.hard_spread = False
        self.ports = False
        self.affinity_req = False
        self.required_anti = False
        self.scoring_terms = False
        self.score_pref = False
        self.score_soft = False
        self.gang = False

    @property
    def klass(self) -> str:
        """Admission class for metrics/docs: "host" (sequential oracle),
        "constrained" (device, with constraint-family tensors), or
        "device" (plain resource solve)."""
        if not self.device_ok:
            return "host"
        if (
            self.hard_spread or self.ports or self.affinity_req
            or self.scoring_terms or self.score_soft
        ):
            return "constrained"
        return "device"

    def as_host_only(self, reason: str) -> "Admission":
        """A pinned host-only copy: used when a device solve rejects a
        countable-volume pod (the additive columns may under-admit a
        shared handle), so the retry runs the exact host oracle instead
        of bouncing device -> NO_NODE forever. Pinned records skip the
        volume-generation staleness check; a real pod update still
        replaces the object (and the memo) wholesale."""
        host = Admission()
        for slot in self.__slots__:
            setattr(host, slot, getattr(self, slot))
        host.device_ok = False
        host.reason = reason
        host.pinned = True
        return host


def classify_pod(
    pod: Pod,
    *,
    extenders,
    listers,
    volume_gen: int,
    token: object,
    priority_resolver=None,
) -> Admission:
    """Build (and memoize on the pod) the full admission record. Safe to
    call from informer threads: lister reads take the informers' own
    locks only, and NOTHING here touches the tensor schema -- volume
    columns are registered by the dispatcher thread at pop time
    (BatchScheduler._admission_of), so the dims registry never grows
    under a concurrently packing NodeTensorCache.update."""
    adm = Admission()
    adm.token = token
    adm.volume_gen = volume_gen
    adm.reason = solver_unsupported_reason(pod)

    spec = pod.spec
    if spec.volumes:
        adm.has_pvc = any(v.pvc_claim_name for v in spec.volumes)
        from kubernetes_tpu.plugins.volumes import classify_pod_volumes

        vol_reason, counts = classify_pod_volumes(pod, listers)
        adm.vol_counts = counts
        # the in-use accounting memo: NodeInfo.add_pod reads it when
        # this pod (or its assume clone, which copies __dict__) lands
        pod.__dict__["_volcount_memo"] = counts
        if not adm.reason and vol_reason:
            adm.reason = vol_reason

    if not adm.reason and extenders:
        if any(e.is_interested(pod) for e in extenders):
            adm.reason = "extender-interested"
    adm.device_ok = not adm.reason

    # feature bits for the dispatch-time batch aggregates
    (_m, _b, _e, _s, _c, _mm, has_aff, host_ports) = pod_hot_info(pod)
    adm.ports = bool(host_ports)
    adm.gang = bool(pod.metadata.labels.get(POD_GROUP_LABEL))
    for c in spec.topology_spread_constraints:
        if c.when_unsatisfiable == "DoNotSchedule":
            adm.hard_spread = True
        else:
            adm.score_soft = True
    if has_aff or spec.affinity is not None:
        from kubernetes_tpu.ops.affinity import (
            _required_affinity,
            _required_anti_affinity,
        )
        from kubernetes_tpu.ops.scoring import (
            _preferred_aff_terms,
            _preferred_anti_terms,
            _required_aff_terms,
        )

        req_aff = bool(_required_affinity(pod))
        adm.required_anti = bool(_required_anti_affinity(pod))
        adm.affinity_req = req_aff or adm.required_anti
        adm.score_pref = bool(
            _preferred_aff_terms(pod) or _preferred_anti_terms(pod)
        )
        adm.scoring_terms = adm.score_pref or bool(_required_aff_terms(pod))

    # effective priority for the streaming band (stamped ONCE at ingest
    # next to the admission memo): pods that carry only a
    # priorityClassName get the PriorityClass object's value resolved
    # here, so the queue's band check stays a memo read -- PriorityClass
    # OBJECTS, not raw integers, select the band
    if priority_resolver is not None:
        try:
            pod.__dict__["_band_priority"] = int(priority_resolver(pod))
        except Exception:  # noqa: BLE001 - band is advisory, never block
            pod.__dict__.pop("_band_priority", None)

    pod.__dict__["_admission"] = adm
    # pack-ready row record (tensors/node_tensor.py): stamped HERE, at
    # ingest, after the volume classification resolved _volcount_memo --
    # pack_pod_batch's per-cycle loop is then a pure memo gather
    stamp_pack_row(pod)
    return adm


# -- the plain-pod fast path (native ingest_stamp + this Python twin) -----
#
# A burst is overwhelmingly PLAIN pods: no volumes, no affinity, no
# spread constraints, no NUMA annotation, no gang label, no host ports,
# and a priority that needs no PriorityClass resolution. For those the
# whole classification is a constant -- so one SHARED read-only
# Admission record serves every plain pod, and the per-pod ingest work
# reduces to building the spec memos (_req_memo/_nzr_memo/_hot_memo/
# _packrow/_band_priority), which native/_hotpath.c ``ingest_stamp``
# does in one C pass. ``stamp_plain_pods`` is the differential twin
# (tests/test_native_ingest.py); non-plain pods are returned by index
# for the full ``classify_pod``. Only valid with NO extenders (an
# extender's is_interested must see every pod).

_FIXED_RESOURCE_NAMES = (
    RESOURCE_CPU, RESOURCE_MEMORY, RESOURCE_EPHEMERAL_STORAGE,
    RESOURCE_PODS,
)


def plain_admission(token: object) -> Admission:
    """The shared Admission record every plain pod points at (read-only
    by contract: ``as_host_only`` copies before mutating)."""
    adm = Admission()
    adm.token = token
    return adm


def ingest_stamp_cfg(plain_adm: Admission) -> Tuple:
    """The constant tuple native ``ingest_stamp`` takes (one build per
    scheduler): the shared record, the gate keys, the fixed resource
    names, and the non-zero defaults."""
    return (
        plain_adm, ALIGNED_ANNOTATION, POD_GROUP_LABEL,
        RESOURCE_CPU, RESOURCE_MEMORY, RESOURCE_EPHEMERAL_STORAGE,
        RESOURCE_PODS, DEFAULT_MILLI_CPU_REQUEST, DEFAULT_MEMORY_REQUEST,
    )


def _is_plain_pod(pod: Pod) -> bool:
    meta = pod.metadata
    spec = pod.spec
    if not isinstance(meta.annotations, dict) or (
        ALIGNED_ANNOTATION in meta.annotations
    ):
        return False
    if not isinstance(meta.labels, dict) or POD_GROUP_LABEL in meta.labels:
        return False
    if spec.volumes or spec.affinity is not None:
        return False
    if spec.topology_spread_constraints:
        return False
    if not isinstance(spec.priority, int):
        return False
    if not spec.priority and spec.priority_class_name:
        return False  # bare priorityClassName needs the lister resolver
    for c in spec.containers:
        for p in c.ports:
            if p.host_port:
                return False
    return True


def _stamp_plain(pod: Pod, plain_adm: Admission) -> None:
    """Build the plain pod's full ingest record (semantics mirrored
    bit-for-bit by native ``ingest_stamp``)."""
    spec = pod.spec
    req: dict = {}
    nzr_cpu = 0
    nzr_mem = 0
    for c in spec.containers:
        requests = c.resources.requests
        for name, qty in requests.items():
            if not isinstance(qty, int):
                raise TypeError("non-int resource quantity")
            req[name] = req.get(name, 0) + qty
        ccpu = requests.get(RESOURCE_CPU, 0)
        cmem = requests.get(RESOURCE_MEMORY, 0)
        nzr_cpu += ccpu if ccpu else DEFAULT_MILLI_CPU_REQUEST
        nzr_mem += cmem if cmem else DEFAULT_MEMORY_REQUEST
    for c in spec.init_containers:
        for name, qty in c.resources.requests.items():
            if not isinstance(qty, int):
                raise TypeError("non-int resource quantity")
            if qty > req.get(name, 0):
                req[name] = qty
    for name, qty in spec.overhead.items():
        if not isinstance(qty, int):
            raise TypeError("non-int resource quantity")
        req[name] = req.get(name, 0) + qty
    scalar = tuple(
        (k, v) for k, v in req.items() if k not in _FIXED_RESOURCE_NAMES
    )
    d = pod.__dict__
    d["_req_memo"] = req
    d["_nzr_memo"] = (nzr_cpu, nzr_mem)
    d["_hot_memo"] = (
        req.get(RESOURCE_CPU, 0), req.get(RESOURCE_MEMORY, 0),
        req.get(RESOURCE_EPHEMERAL_STORAGE, 0), scalar,
        nzr_cpu, nzr_mem, False, (),
    )
    d["_packrow"] = (
        (tuple(req.items()), ()), nzr_cpu, _kib_ceil(nzr_mem),
        spec.priority,
    )
    d["_band_priority"] = spec.priority
    d["_admission"] = plain_adm


def stamp_plain_pods(pods: List[Pod], plain_adm: Admission) -> List[int]:
    """Python twin of native ``ingest_stamp``: stamp every plain pod's
    ingest record, return the indices of pods that need the full
    classifier (non-plain shapes, or anything that errored -- the fast
    path never half-stamps)."""
    rest: List[int] = []
    for i, pod in enumerate(pods):
        try:
            if not _is_plain_pod(pod):
                rest.append(i)
                continue
            _stamp_plain(pod, plain_adm)
        except Exception:  # noqa: BLE001 - route to the full classifier
            rest.append(i)
    return rest
