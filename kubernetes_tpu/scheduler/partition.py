"""Partitioned multi-active scheduling: the lease-backed ownership layer.

PR 2 built fenced single-leader HA: ONE live scheduler stack, one Lease,
`holds_lease()` probed immediately before every commit. This module
generalizes that lease to a **partition map** so N active
`BatchScheduler` stacks share one apiserver, each owning a slice of the
node space:

- the node space is split into ``num_partitions`` consistent-hash
  partitions (``partition_of_name``: crc32 over the node name, or over
  the zone label when ``zone_aligned`` -- a whole zone then fails over
  as a unit);
- every partition is one ``Lease`` object in the apiserver
  (``<prefix>-<k>``), claimed and renewed exactly like
  ``leaderelection.LeaderElector``'s single lease, including the
  clock-skew grace for challengers and the ``lease_renew_fail``
  injection seam;
- pending pods are partitioned too (hash of the pod uid -- or of the
  GANG group key, so a pod group homes as a unit and never splits
  across stacks -- overridable by the spill annotation), so each pod
  has exactly ONE home stack and the
  stacks never race over fresh work -- overlap is the rare exception
  (takeover windows), resolved by typed bind conflicts, not prevented
  by global locks;
- desired assignment is **rendezvous hashing** over the live members
  (each stack also renews a member lease): every coordinator
  independently computes, per partition, the highest-scoring live
  member. Members agree without talking to each other, a dead stack's
  partitions scatter across ALL survivors (the "split the orphaned
  range" property), and a returning stack reclaims exactly its old
  partitions (minimal movement).

Failure modes are rehearsed paths:

- **partition-loss adoption**: a lapsed partition lease (stack crash,
  injected renew failures, partition of the partition-owner) is seized
  by the rendezvous winner among the survivors after the skew grace;
  the adopter then runs a ``recover_on_startup``-style sweep scoped to
  the partition: nodes join its cache (the PR-6 slot machinery absorbs
  them as membership scatters), bound pods are adopted, and the dead
  stack's in-flight assumed-but-never-bound pods -- still pending at
  the apiserver -- are requeued and re-bound exactly once.
  ``partition_takeover_ms`` meters detection -> adoption-complete.
- **commit fencing**: the batch committer probes
  ``may_bind(node)`` -- a FRESH lease read per partition, the
  multi-lease `holds_lease()` -- immediately before every bulk bind;
  pods on unowned partitions are absorbed as typed conflicts (requeue,
  never silent). The apiserver double-checks under its own store lock
  (``PartitionAuthority``) so a binder racing the probe still gets a
  per-slot typed conflict instead of a double placement.
- **spill**: a pod whose feasible nodes all live in a foreign
  partition (NO_NODE on its home stack) is re-stamped
  (``SPILL_TARGET_ANNOTATION``) and forwarded through the apiserver --
  the target stack's informer enqueues it; the pod is never dropped
  and never fails silently. After visiting every partition the normal
  unschedulable backoff applies.
"""

from __future__ import annotations

import logging
import threading
import time
import zlib
from typing import Dict, List, Optional, Set, Tuple

from kubernetes_tpu.api.types import (
    LABEL_ZONE_KEYS,
    Lease,
    ObjectMeta,
    POD_GROUP_LABEL,
    Pod,
)
from kubernetes_tpu.config.types import PartitionConfiguration
from kubernetes_tpu.robustness.faults import FaultPoint, get_injector
from kubernetes_tpu.utils import flightrecorder, metrics

logger = logging.getLogger(__name__)

#: spill re-stamp: overrides the pod's hashed home partition. Written by
#: the failing stack via guaranteed_update; the target stack's informer
#: sees the MODIFIED event and enqueues the pod.
SPILL_TARGET_ANNOTATION = "scheduler.tpu/partition"
#: how many partitions this pod has already failed in; spilling stops
#: (normal unschedulable backoff takes over) once every partition has
#: had a look
SPILL_COUNT_ANNOTATION = "scheduler.tpu/spill-count"
#: comma-joined partition ids this pod has already FAILED in. The
#: feasibility hint makes spill hops non-ring-ordered, so the
#: every-partition-gets-a-look guarantee can no longer ride the hop
#: count alone: candidates are picked unvisited-first against this set
#: (ring revisits only as the last resort within the hop budget)
SPILL_VISITED_ANNOTATION = "scheduler.tpu/spill-visited"


def partition_of_name(name: str, num_partitions: int) -> int:
    """Stable consistent-hash partition for a node (or pod-uid) name.
    crc32 is stable across processes and runs (unlike hash())."""
    if num_partitions <= 1:
        return 0
    return zlib.crc32(name.encode()) % num_partitions


def rendezvous_ranking(partition: int, members: List[str]) -> List[str]:
    """Members ranked by highest-random-weight score for one partition:
    every stack computes the same order independently (no coordination
    round), and a removed member simply drops out of every ranking."""
    return sorted(
        sorted(members),
        key=lambda m: zlib.crc32(f"{m}/{partition}".encode()),
        reverse=True,
    )


def compute_assignment(
    num_partitions: int, members: List[str]
) -> Dict[int, str]:
    """Deterministic balanced partition assignment: rendezvous ranking
    per partition, capped at ceil(P / M) partitions per member so the
    load always spreads across every live stack (pure rendezvous can
    hand one member everything at small P). Identical on every stack
    for the same member set; a dead member's partitions scatter across
    the survivors with the remaining assignments unmoved (the "split
    the orphaned range" property)."""
    members = sorted(set(members))
    if not members or num_partitions < 1:
        return {}
    cap = -(-num_partitions // len(members))  # ceil
    counts = {m: 0 for m in members}
    out: Dict[int, str] = {}
    for k in range(num_partitions):
        for m in rendezvous_ranking(k, members):
            if counts[m] < cap:
                out[k] = m
                counts[m] += 1
                break
    return out


class PartitionAuthority:
    """Server-side bind fence: installed on the APIServer so bulk binds
    carrying a ``binder`` identity are checked against the CURRENT
    partition leases under the store lock -- strictly fresher than any
    committer-side probe. Returns a conflict reason string ("foreign-
    partition") or None (allowed).

    An unheld or expired partition allows the bind: adoption is in
    flight and the committer-side probe plus the per-pod already-bound
    conflict are the remaining guards -- refusing here would wedge
    takeover re-binds behind the lease CAS."""

    def __init__(self, server, config: PartitionConfiguration,
                 clock=time.monotonic) -> None:
        self.server = server
        self.config = config
        self.clock = clock

    def _lease(self, k: int) -> Optional[Lease]:
        store = self.server._stores.get("Lease")
        if not store:
            return None
        return store.get(
            (self.config.resource_namespace,
             f"{self.config.resource_prefix}-{k}")
        )

    def partition_of_node(self, node_name: str) -> int:
        cfg = self.config
        if cfg.zone_aligned:
            node = self.server._stores.get("Node", {}).get(
                ("", node_name)
            ) or self.server._stores.get("Node", {}).get(
                ("default", node_name)
            )
            if node is not None:
                for key in LABEL_ZONE_KEYS:
                    zone = node.metadata.labels.get(key)
                    if zone:
                        return partition_of_name(
                            zone, cfg.num_partitions
                        )
        return partition_of_name(node_name, cfg.num_partitions)

    def check(self, binder: str, node_name: str) -> Optional[str]:
        lease = self._lease(self.partition_of_node(node_name))
        if lease is None or not lease.holder_identity:
            return None
        if lease.holder_identity == binder:
            return None
        if lease.renew_time + lease.lease_duration_seconds <= self.clock():
            return None  # expired: adoption window, probes take over
        return "foreign-partition"


class PartitionCoordinator:
    """One scheduler stack's view of (and claims on) the partition map.

    Runs a renew loop (like ``LeaderElector.run`` but over a member
    lease plus every rendezvous-desired partition lease) and keeps the
    stack's cache/queue scoped to its held partitions:

    - ``owns_node`` / ``wants_pod`` gate the informer event handlers
      (scheduler/eventhandlers.py) and the resilience sweeps;
    - ``may_bind`` is the commit-time fencing probe (fresh lease read);
    - partition acquisition triggers adoption (nodes + bound pods into
      the cache, pending home pods into the queue), partition release
      or loss evicts the partition's state.

    ``fault_injector`` mirrors the LeaderElector seam: a targeted
    injector makes THIS stack's renews fail deterministically (the
    stack-kill chaos primitive) while siblings stay healthy.
    """

    def __init__(
        self,
        client,
        sched,
        config: PartitionConfiguration,
        identity: str,
        clock=time.monotonic,
    ) -> None:
        self.client = client
        self.sched = sched
        self.config = config
        self.identity = identity
        self.clock = clock
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._watch = None
        self._watch_thread: Optional[threading.Thread] = None
        self._lock = threading.RLock()
        #: held partition -> fencing epoch (the lease_transitions value
        #: observed when we acquired it)
        self.held: Dict[int, int] = {}
        #: first time we saw a foreign partition's lease expired
        #: (detection timestamps for partition_takeover_ms)
        self._expiry_seen: Dict[int, float] = {}
        #: last successful renew per held partition: a partition that
        #: has not renewed within the lease duration is treated as LOST
        #: locally (the lease may already be seized) -- the deposed
        #: stack stops wanting its pods instead of fencing forever
        self._last_renewed: Dict[int, float] = {}
        #: zone-aligned mode: node name -> partition, learned from node
        #: objects (the zone label travels with the object, not the name)
        self._node_partition: Dict[str, int] = {}
        self.fault_injector = None
        # -- counters (mirrored into metrics) ----------------------------
        self.takeovers = 0
        self.adoptions_requeued = 0
        self.adoptions_bound = 0
        self.releases = 0
        #: spill feasibility hints that stamped the owner directly
        self.spill_hint_hits = 0
        # per-signature owner-hint cache (see _spill_owner_hint),
        # invalidated when the Node list's resourceVersion moves
        self._spill_hint_cache: Dict[Tuple, Optional[int]] = {}
        self._spill_hint_rv = -1

    # -- partition arithmetic ------------------------------------------------

    @property
    def num_partitions(self) -> int:
        return max(1, self.config.num_partitions)

    def node_partition(self, node_name: str) -> int:
        if self.config.zone_aligned:
            cached = self._node_partition.get(node_name)
            if cached is not None:
                return cached
        return partition_of_name(node_name, self.num_partitions)

    def note_node(self, node) -> int:
        """Record (and return) a node OBJECT's partition; zone-aligned
        mode learns the name -> partition mapping here so later
        name-only lookups (pod.spec.node_name) resolve correctly."""
        k = partition_of_name(
            node.metadata.name, self.num_partitions
        )
        if self.config.zone_aligned:
            for key in LABEL_ZONE_KEYS:
                zone = node.metadata.labels.get(key)
                if zone:
                    k = partition_of_name(zone, self.num_partitions)
                    break
            self._node_partition[node.metadata.name] = k
        return k

    def pod_partition(self, pod: Pod) -> int:
        """The pod's home partition: the spill annotation overrides the
        hash (a re-stamped pod belongs to its spill target). Gang pods
        hash their GROUP key (namespace/pod-group label) instead of the
        per-pod uid, so a gang homes as a unit on one stack -- a
        uid-split gang could never reach quorum on either side and paid
        multi-hop spill convergence to reassemble (ROADMAP item-4e).
        The group hash is deterministic across stacks, and a spilled
        gang member re-homes with the same annotation mechanism as any
        pod (its siblings fail quorum on the same stack and follow to
        the same ring successor)."""
        ann = pod.metadata.annotations.get(SPILL_TARGET_ANNOTATION)
        if ann is not None:
            try:
                k = int(ann)
                if 0 <= k < self.num_partitions:
                    return k
            except ValueError:
                pass
        gang = (pod.metadata.labels or {}).get(POD_GROUP_LABEL)
        if gang:
            return partition_of_name(
                f"{pod.metadata.namespace}/{gang}", self.num_partitions
            )
        return partition_of_name(pod.metadata.uid, self.num_partitions)

    # -- ownership answers (event handlers, resilience, skip checks) --------

    def owns_node(self, node_name: str) -> bool:
        if not node_name:
            return False
        return self.node_partition(node_name) in self.held

    def owns_node_obj(self, node) -> bool:
        return self.note_node(node) in self.held

    def wants_pod(self, pod: Pod) -> bool:
        return self.pod_partition(pod) in self.held

    def held_partitions(self) -> Set[int]:
        with self._lock:
            return set(self.held)

    # -- lease primitives ----------------------------------------------------

    def _lease_name(self, k: int) -> str:
        return f"{self.config.resource_prefix}-{k}"

    def _member_name(self) -> str:
        return f"{self.config.resource_prefix}-member-{self.identity}"

    def _renew_fails_injected(self) -> bool:
        inj = (
            self.fault_injector
            if self.fault_injector is not None
            else get_injector()
        )
        return inj is not None and inj.should_fire(
            FaultPoint.LEASE_RENEW_FAIL
        )

    def _get_or_create(self, name: str) -> Lease:
        server = self.client.server
        ns = self.config.resource_namespace
        try:
            return server.get("Lease", ns, name)
        except KeyError:
            lease = Lease(metadata=ObjectMeta(name=name, namespace=ns))
            try:
                return server.create(lease)
            except ValueError:  # lost the create race
                return server.get("Lease", ns, name)

    def _try_claim(self, name: str, challenger_grace: bool) -> Optional[int]:
        """One CAS round on one lease (tryAcquireOrRenew generalized).
        Returns the lease_transitions epoch on success, None when held
        by a live other."""
        if self._renew_fails_injected():
            metrics.lease_renew_failures.inc()
            return None
        server = self.client.server
        now = self.clock()
        skew = max(0.0, self.config.clock_skew_tolerance_seconds)
        self._get_or_create(name)

        class _Held(Exception):
            pass

        out = {}

        def mutate(obj: Lease) -> None:
            grace = skew if (
                challenger_grace and obj.holder_identity != self.identity
            ) else 0.0
            expired = (
                obj.renew_time + obj.lease_duration_seconds + grace <= now
            )
            if obj.holder_identity not in ("", self.identity) and not expired:
                raise _Held()
            if obj.holder_identity != self.identity:
                obj.lease_transitions += 1
                obj.acquire_time = now
            obj.holder_identity = self.identity
            obj.lease_duration_seconds = self.config.lease_duration_seconds
            obj.renew_time = now
            out["epoch"] = obj.lease_transitions

        try:
            server.guaranteed_update(
                "Lease", self.config.resource_namespace, name, mutate
            )
            return out.get("epoch", 0)
        except _Held:
            return None
        except Exception:
            logger.exception("partition lease update failed: %s", name)
            metrics.lease_renew_failures.inc()
            return None

    def _release_lease(self, name: str) -> None:
        def mutate(obj: Lease) -> None:
            if obj.holder_identity != self.identity:
                return  # already seized: don't clobber
            obj.holder_identity = ""
            obj.renew_time = 0.0

        try:
            self.client.server.guaranteed_update(
                "Lease", self.config.resource_namespace, name, mutate
            )
        except Exception:
            logger.exception("releasing partition lease %s", name)

    def _live_members(self, now: float) -> List[str]:
        """Identities with a live member lease (self always counts while
        running -- our own member renew may race this read)."""
        members = {self.identity}
        prefix = f"{self.config.resource_prefix}-member-"
        try:
            leases, _rv = self.client.server.list("Lease")
        except Exception:
            return sorted(members)
        for lease in leases:
            name = lease.metadata.name
            if (
                not name.startswith(prefix)
                or lease.metadata.namespace
                != self.config.resource_namespace
            ):
                continue
            if not lease.holder_identity:
                continue
            if lease.renew_time + lease.lease_duration_seconds > now:
                members.add(lease.holder_identity)
        return sorted(members)

    # -- commit-time fencing -------------------------------------------------

    def holds_partition(self, k: int) -> bool:
        """Fresh-read fencing probe for one partition (the multi-lease
        ``holds_lease``): any doubt answers False."""
        if k not in self.held:
            return False
        try:
            obj = self.client.server.get(
                "Lease", self.config.resource_namespace,
                self._lease_name(k),
            )
        except Exception:  # noqa: BLE001 - can't prove ownership: fence
            return False
        return (
            obj.holder_identity == self.identity
            and obj.renew_time + obj.lease_duration_seconds > self.clock()
        )

    def may_bind(self, node_name: str) -> bool:
        return self.holds_partition(self.node_partition(node_name))

    def elected_singleton_writer(self) -> bool:
        """Single-writer election for cluster-scoped reconcilers (the
        quota ``sync_all`` absolute used-rewrite): the elected stack is
        the one holding the LOWEST partition currently held by any live
        stack. Every stack evaluates the same lease ground truth, so at
        most one answers True per lease window -- two stacks can only
        disagree across a takeover boundary, and the deposed holder's
        next fresh read flips it False. Doubt (unreadable lease)
        fences; no live holder at all (cold start, single stack racing
        its very first claim round) elects self -- there is nobody to
        race."""
        now = self.clock()
        server = self.client.server
        ns = self.config.resource_namespace
        for k in range(self.num_partitions):
            try:
                obj = server.get("Lease", ns, self._lease_name(k))
            except KeyError:
                continue  # never claimed: not held by anyone
            except Exception:  # noqa: BLE001 - can't prove: fence
                return False
            if not obj.holder_identity:
                continue
            if obj.renew_time + obj.lease_duration_seconds <= now:
                continue  # expired holder is not live
            return obj.holder_identity == self.identity
        return True

    def fence_hosts(self, hosts: List[str]) -> Set[int]:
        """Indexes of hosts this stack may NOT commit to right now; one
        fresh lease probe per unique partition, not per pod."""
        verdict: Dict[int, bool] = {}
        fenced: Set[int] = set()
        for i, host in enumerate(hosts):
            k = self.node_partition(host)
            ok = verdict.get(k)
            if ok is None:
                ok = self.holds_partition(k)
                verdict[k] = ok
            if not ok:
                fenced.add(i)
        return fenced

    # -- spill ---------------------------------------------------------------

    def _spill_owner_hint(self, pod: Pod) -> Optional[int]:
        """Feasibility hint (ROADMAP item-5 residual): which partition
        OWNS the pod's selector-matching nodes. A nodeSelector/nodeName
        pod that NO_NODEs here almost always failed on feasibility, not
        capacity -- ring-ordered spill then walks it through every
        partition until it happens to land on the owner. This matches
        the pod's cached constraint signature (the static-mask-row key,
        ops/host_masks._constraint_signature -- same dedup the mask rows
        use) against the full Node kind and returns the partition owning
        the most matching nodes, so the spill stamps the owner directly:
        one hop max. Pods with no selector/nodeName get no hint (any
        partition is as good as the next -- ring order stands). The
        per-signature answer is cached until the Node list's
        resourceVersion moves."""
        sel = pod.spec.node_selector
        pinned = pod.spec.node_name
        if not sel and not pinned:
            return None
        from kubernetes_tpu.ops.host_masks import _constraint_signature

        sig = _constraint_signature(pod)
        server = self.client.server
        try:
            # invalidate on NODE-kind mutations only: the kind's event
            # log ordinal (base + length) is a monotone count of node
            # adds/updates/deletes, unlike the global resourceVersion,
            # which every pod bind bumps (a cache keyed on that would
            # clear on essentially every call under load)
            node_gen = server._history_base.get(
                "Node", 0
            ) + len(server._history.get("Node", ()))
        except Exception:  # noqa: BLE001 - foreign server shape
            node_gen = -1
        cache = self._spill_hint_cache
        if node_gen < 0 or node_gen != self._spill_hint_rv:
            cache.clear()
            self._spill_hint_rv = node_gen
        elif sig in cache:
            return cache[sig]
        try:
            nodes, _rv = server.list("Node")
        except Exception:  # noqa: BLE001 - hint only: ring order stands
            return None
        counts: Dict[int, int] = {}
        for node in nodes:
            if pinned and node.metadata.name != pinned:
                continue
            labels = node.metadata.labels
            if sel and any(labels.get(k) != v for k, v in sel.items()):
                continue
            k = self.note_node(node)
            counts[k] = counts.get(k, 0) + 1
        hint = max(counts, key=counts.get) if counts else None
        cache[sig] = hint
        return hint

    def try_spill(self, pod: Pod) -> bool:
        """Re-stamp an unplaceable pod to the next partition not held by
        this stack and forward it through the apiserver. Returns True
        when the pod was forwarded (or turned out to be already bound:
        nothing left to do) -- the caller then skips the normal failure
        path. False = spill exhausted or impossible; fail normally."""
        P = self.num_partitions
        if P <= 1:
            return False
        ann = pod.metadata.annotations
        try:
            count = int(ann.get(SPILL_COUNT_ANNOTATION, "0"))
        except ValueError:
            count = 0
        if count >= P - 1:
            return False  # every partition has had a look
        cur = self.pod_partition(pod)
        visited = {cur}
        for tok in ann.get(SPILL_VISITED_ANNOTATION, "").split(","):
            try:
                visited.add(int(tok))
            except ValueError:
                pass
        target = None
        # feasibility hint first: stamp the partition that owns the
        # pod's selector-matching nodes directly (one hop max) instead
        # of walking the ring until the owner happens to come up
        hint = self._spill_owner_hint(pod)
        if (
            hint is not None and hint != cur
            and hint not in self.held and hint not in visited
        ):
            target = hint
            self.spill_hint_hits += 1
            metrics.spill_hint_hits.inc()
        if target is None:
            # UNVISITED-first: a hint hop desynchronizes the ring, so
            # the walk must not burn the hop budget revisiting
            # partitions that already failed while a fresh one remains
            for step in range(1, P):
                k = (cur + step) % P
                if k not in self.held and k not in visited:
                    target = k
                    break
        if target is None:
            # every unvisited partition is held HERE (this stack just
            # NO_NODEd the pod against its whole slice): fall back to
            # the classic ring revisit within the remaining hop budget
            for step in range(1, P):
                k = (cur + step) % P
                if k not in self.held:
                    target = k
                    break
        if target is None:
            return False  # we hold everything: nowhere to forward
        visited.add(target)

        class _AlreadyBound(Exception):
            pass

        def mutate(obj: Pod) -> None:
            if obj.spec.node_name:
                raise _AlreadyBound()
            # the stored object's annotations dict is shared with the
            # old revision (copy-on-write clones metadata shallowly) --
            # replace, never mutate in place
            obj.metadata.annotations = {
                **obj.metadata.annotations,
                SPILL_TARGET_ANNOTATION: str(target),
                SPILL_COUNT_ANNOTATION: str(count + 1),
                SPILL_VISITED_ANNOTATION: ",".join(
                    str(k) for k in sorted(visited)
                ),
            }

        try:
            self.client.server.guaranteed_update(
                "Pod", pod.metadata.namespace, pod.metadata.name, mutate
            )
        except _AlreadyBound:
            return True  # bound while we deliberated: nothing to do
        except KeyError:
            return True  # deleted: nothing to do
        except Exception:
            logger.exception("spilling pod %s", pod.key())
            return False
        metrics.pods_spilled.inc()
        self.sched.pods_spilled += 1
        return True

    # -- adoption / release --------------------------------------------------

    def _adopt_partition(self, k: int) -> None:
        """Bring partition ``k``'s state into this stack: nodes into the
        cache (PR-6 slot claims), bound pods adopted, pending home pods
        (including a dead sibling's assumed-but-never-bound in-flight
        pods, which the apiserver still shows pending) requeued. Every
        entry point is idempotent against the informer's own delivery."""
        sched = self.sched
        try:
            nodes, _ = self.client.list_nodes()
        except Exception:
            logger.exception("adoption list_nodes for partition %d", k)
            nodes = []
        for node in nodes:
            if self.note_node(node) != k:
                continue
            try:
                sched.cache.add_node(node)
            except Exception:
                logger.exception("adopting node %s", node.metadata.name)
        attach = getattr(sched, "attach_volume_counts", None)
        try:
            pods, _ = self.client.list_pods()
        except Exception:
            logger.exception("adoption list_pods for partition %d", k)
            pods = []
        for pod in pods:
            if pod.spec.node_name:
                if self.node_partition(pod.spec.node_name) != k:
                    continue
                if sched.cache.get_pod(pod) is None:
                    try:
                        if attach is not None:
                            attach(pod)
                        sched.cache.add_pod(pod)
                        self.adoptions_bound += 1
                    except Exception:
                        logger.exception("adopting bound pod %s", pod.key())
            elif (
                self.pod_partition(pod) == k
                and pod.spec.scheduler_name in sched.profiles
                and pod.metadata.deletion_timestamp is None
            ):
                classify = getattr(sched, "classify_pod", None)
                try:
                    if classify is not None:
                        classify(pod)
                    sched.queue.add(pod)
                    self.adoptions_requeued += 1
                except Exception:
                    logger.exception("requeueing adopted pod %s", pod.key())

    def _drop_partition(self, k: int) -> None:
        """Evict partition ``k``'s state: its nodes leave the cache
        (their bound pods go with the NodeInfo; stranded assumed pods
        fast-expire through the PR-6 node_removed path and the sweeper
        routes them by apiserver truth)."""
        sched = self.sched
        try:
            names = [
                name for name in sched.cache.known_node_names()
                if self.node_partition(name) == k
            ]
        except Exception:
            logger.exception("listing cache nodes for partition %d", k)
            return
        from kubernetes_tpu.api.types import Node

        for name in names:
            try:
                # remove resident pods first: remove_node keeps a
                # nodeless NodeInfo while pods remain, which would leak
                # phantom accounting for a partition we no longer own
                for pod in list(sched.cache.pods_on_node(name)):
                    sched.cache.remove_pod(pod)
                sched.cache.remove_node(
                    Node(metadata=ObjectMeta(name=name, namespace=""))
                )
            except Exception:
                logger.exception("dropping node %s", name)
        self.releases += 1

    # -- the loop ------------------------------------------------------------

    def step(self) -> None:
        """One coordination round: renew the member lease, compute the
        rendezvous-desired set over the live members, renew/claim
        desired partitions, release undesired ones (graceful handoff),
        and note foreign expiries for takeover metering."""
        now = self.clock()
        self._try_claim(self._member_name(), challenger_grace=False)
        members = self._live_members(now)
        assignment = compute_assignment(self.num_partitions, members)
        desired = {
            k for k, owner in assignment.items()
            if owner == self.identity
        }
        server = self.client.server
        for k in range(self.num_partitions):
            held = k in self.held
            if k in desired:
                was_foreign = False
                if not held:
                    # takeover vs fresh claim: is the lease currently
                    # someone else's (possibly expired)?
                    try:
                        obj = server.get(
                            "Lease", self.config.resource_namespace,
                            self._lease_name(k),
                        )
                        was_foreign = bool(obj.holder_identity) and (
                            obj.holder_identity != self.identity
                        )
                        expired = (
                            obj.renew_time
                            + obj.lease_duration_seconds <= now
                        )
                        if was_foreign and expired:
                            self._expiry_seen.setdefault(
                                k, time.perf_counter()
                            )
                        else:
                            # holder recovered (or it's our own/fresh
                            # lease): a stale detection stamp would
                            # inflate a LATER takeover's latency metric
                            self._expiry_seen.pop(k, None)
                    except KeyError:
                        pass
                    except Exception:
                        pass
                epoch = self._try_claim(
                    self._lease_name(k), challenger_grace=True
                )
                if epoch is None:
                    continue  # still held live by another: wait it out
                self._last_renewed[k] = self.clock()
                if not held:
                    with self._lock:
                        self.held[k] = epoch
                    t_claim = time.perf_counter()
                    self._adopt_partition(k)
                    if was_foreign:
                        # a seized (not fresh/released) partition: meter
                        # the takeover from expiry detection -- or from
                        # the claim, when the watch beat the tick -- to
                        # adoption complete
                        self.takeovers += 1
                        metrics.partition_takeovers.inc()
                        detected = self._expiry_seen.pop(k, None)
                        span = time.perf_counter() - (
                            detected if detected is not None else t_claim
                        )
                        metrics.partition_takeover_ms.observe(span * 1000.0)
                        flightrecorder.mark(
                            "partition_takeover", partition=k,
                            by=self.identity,
                            ms=round(span * 1000.0, 1),
                        )
                        logger.warning(
                            "partition %d adopted by %s in %.0f ms",
                            k, self.identity, span * 1000.0,
                        )
            elif held:
                # rendezvous says another live member owns this now
                # (a member joined): graceful handoff
                with self._lock:
                    self.held.pop(k, None)
                self._last_renewed.pop(k, None)
                self._drop_partition(k)
                self._release_lease(self._lease_name(k))
            else:
                # not desired, not held: any expiry detection for it is
                # no longer ours to meter
                self._expiry_seen.pop(k, None)
        # deposition: a held partition that has not renewed within the
        # lease duration may already be seized (our renews are failing,
        # or the map moved under us). Drop it locally -- commit fencing
        # already refuses it; this stops the stack WANTING its pods so
        # the adopter isn't shadow-raced on every batch. No release:
        # we cannot prove we still own the lease to clear it.
        now2 = self.clock()
        for k in list(self.held):
            renewed = self._last_renewed.get(k)
            if renewed is not None and (
                now2 - renewed > self.config.lease_duration_seconds
            ):
                logger.warning(
                    "partition %d lost by %s (renewals failing); "
                    "dropping locally", k, self.identity,
                )
                with self._lock:
                    self.held.pop(k, None)
                self._last_renewed.pop(k, None)
                self._drop_partition(k)
        metrics.partitions_held.set(float(len(self.held)))

    def _run(self) -> None:
        while not self._stop.is_set():
            if getattr(self.sched, "crashed", False):
                # simulated process death: abandon the leases (no
                # release -- a real crash wouldn't), let them lapse
                return
            try:
                self.step()
            except Exception:
                logger.exception("partition coordination step failed")
            self._wake.wait(self.config.retry_period_seconds)
            self._wake.clear()

    def _watch_map(self) -> None:
        """The map watch: Lease events where the holder CHANGED (a
        release, a seizure) wake the loop immediately instead of
        waiting out the retry period. Renewals (same holder) don't."""
        holders: Dict[str, str] = {}
        prefix = self.config.resource_prefix
        while not self._stop.is_set():
            try:
                evs = self._watch.next_batch(timeout=0.2)
            except Exception:  # noqa: BLE001 - lagged/stopped: reopen
                if self._stop.is_set():
                    return
                try:
                    self._watch = self.client.server.watch(
                        "Lease",
                        since_rv=self.client.server.current_rv(),
                    )
                except Exception:
                    self._stop.wait(0.2)
                continue
            changed = False
            for ev in evs:
                lease = ev.object
                if not lease.metadata.name.startswith(prefix):
                    continue
                prev = holders.get(lease.metadata.name)
                cur = lease.holder_identity
                holders[lease.metadata.name] = cur
                if prev is not None and prev != cur:
                    changed = True
            if changed:
                self._wake.set()

    def start(self) -> None:
        if self._thread is not None:
            return
        # claim synchronously once so callers see an initial ownership
        # set before informers start filtering on it
        try:
            self.step()
        except Exception:
            logger.exception("initial partition claim failed")
        try:
            self._watch = self.client.server.watch(
                "Lease", since_rv=self.client.server.current_rv()
            )
            self._watch_thread = threading.Thread(
                target=self._watch_map,
                name=f"partition-watch-{self.identity}", daemon=True,
            )
            self._watch_thread.start()
        except Exception:
            logger.exception("partition map watch failed to open")
        self._thread = threading.Thread(
            target=self._run, name=f"partition-{self.identity}",
            daemon=True,
        )
        self._thread.start()

    def stop(self, release: bool = True) -> None:
        self._stop.set()
        self._wake.set()
        if self._watch is not None:
            try:
                self._watch.stop()
            except Exception:
                pass
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=2)
            self._watch_thread = None
        if release:
            for k in list(self.held):
                self._release_lease(self._lease_name(k))
            self._release_lease(self._member_name())
            self.held.clear()


def attach_partitioning(sched, client, config: PartitionConfiguration,
                        identity: str) -> PartitionCoordinator:
    """Wire a coordinator into a scheduler stack and install the
    server-side authority (idempotent per server). The coordinator is
    NOT started; the caller starts it before its informers sync so the
    event handlers filter from the first frame."""
    coordinator = PartitionCoordinator(client, sched, config, identity)
    sched.partition_coordinator = coordinator
    server = client.server
    if getattr(server, "_partition_authority", None) is None:
        server.install_partition_authority(
            PartitionAuthority(server, config)
        )
    return coordinator
