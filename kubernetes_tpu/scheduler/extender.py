"""HTTP extenders: legacy webhook extension of filter/prioritize/bind/
preempt.

Reference: /root/reference/pkg/scheduler/core/extender.go (HTTPExtender
:91, Filter :334, Prioritize :404, Bind :446, send :473 JSON-over-HTTP,
IsInterested :503 managed-resources check, ProcessPreemption :243) and the
wire types in staging/src/k8s.io/kube-scheduler/extender/v1/types.go
(ExtenderArgs, ExtenderFilterResult, HostPriorityList,
ExtenderBindingArgs, ExtenderPreemptionArgs/Result).

Run after in-tree filters (generic_scheduler.go:502); scores are added to
the plugin totals weighted by ``weight`` (prioritizeNodes :664).
"""

from __future__ import annotations

import json
import logging
import urllib.request
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import Pod, pod_resource_requests
from kubernetes_tpu.cache.node_info import NodeInfo

logger = logging.getLogger(__name__)

DEFAULT_EXTENDER_TIMEOUT_SECONDS = 5.0


@dataclass
class ExtenderConfig:
    """apis/config/types.go Extender (legacy_types.go in v1.18)."""

    url_prefix: str = ""
    filter_verb: str = ""
    prioritize_verb: str = ""
    bind_verb: str = ""
    preempt_verb: str = ""
    weight: int = 1
    node_cache_capable: bool = False
    ignorable: bool = False
    managed_resources: List[str] = field(default_factory=list)
    http_timeout_seconds: float = DEFAULT_EXTENDER_TIMEOUT_SECONDS


from kubernetes_tpu.api.serialization import (
    affinity_to_wire as _affinity_to_wire,
)


def _quantity_to_wire(name: str, qty: int) -> str:
    # internal base units: cpu milliCPU, memory/ephemeral bytes, extended
    # whole units (api/types.py ResourceList)
    if name == "cpu":
        return f"{qty}m"
    return str(qty)


def _resource_list_to_wire(rl: dict) -> dict:
    return {name: _quantity_to_wire(name, q) for name, q in rl.items()}


def _pod_to_wire(pod: Pod) -> dict:
    """Full Pod serialization for ExtenderArgs. The reference sends the
    whole v1.Pod (extender/v1/types.go ExtenderArgs), so real extenders
    inspect spec fields -- containers/resources, nodeSelector, affinity,
    tolerations -- not just metadata."""
    def container_to_wire(c) -> dict:
        return {
            "name": c.name,
            "image": c.image,
            "resources": {
                "requests": _resource_list_to_wire(c.resources.requests),
                "limits": _resource_list_to_wire(c.resources.limits),
            },
            "ports": [
                {
                    "containerPort": p.container_port,
                    "hostPort": p.host_port,
                    "hostIP": p.host_ip,
                    "protocol": p.protocol,
                }
                for p in c.ports
            ],
        }

    spec: dict = {
        "priority": pod.spec.priority,
        "schedulerName": pod.spec.scheduler_name,
        "containers": [container_to_wire(c) for c in pod.spec.containers],
    }
    if pod.spec.init_containers:
        spec["initContainers"] = [
            container_to_wire(c) for c in pod.spec.init_containers
        ]
    if pod.spec.overhead:
        spec["overhead"] = _resource_list_to_wire(pod.spec.overhead)
    if pod.spec.node_name:
        spec["nodeName"] = pod.spec.node_name
    if pod.spec.priority_class_name:
        spec["priorityClassName"] = pod.spec.priority_class_name
    if pod.spec.node_selector:
        spec["nodeSelector"] = dict(pod.spec.node_selector)
    if pod.spec.tolerations:
        spec["tolerations"] = [
            {
                "key": t.key,
                "operator": t.operator,
                "value": t.value,
                "effect": t.effect,
                **(
                    {"tolerationSeconds": t.toleration_seconds}
                    if t.toleration_seconds is not None
                    else {}
                ),
            }
            for t in pod.spec.tolerations
        ]
    if pod.spec.affinity is not None:
        spec["affinity"] = _affinity_to_wire(pod.spec.affinity)
    return {
        "metadata": {
            "name": pod.metadata.name,
            "namespace": pod.metadata.namespace,
            "uid": pod.metadata.uid,
            "labels": dict(pod.metadata.labels),
        },
        "spec": spec,
        "status": {
            "nominatedNodeName": pod.status.nominated_node_name,
        },
    }


class HTTPExtender:
    def __init__(self, config: ExtenderConfig) -> None:
        self.config = config

    # -- protocol plumbing (extender.go:473 send) ---------------------------

    def _send(self, verb: str, args: dict) -> dict:
        url = self.config.url_prefix.rstrip("/") + "/" + verb
        data = json.dumps(args).encode()
        req = urllib.request.Request(
            url, data=data, headers={"Content-Type": "application/json"}
        )
        with urllib.request.urlopen(
            req, timeout=self.config.http_timeout_seconds
        ) as resp:
            if resp.status != 200:
                raise RuntimeError(f"extender {url} returned {resp.status}")
            return json.loads(resp.read())

    # -- interest (extender.go:503) -----------------------------------------

    def is_interested(self, pod: Pod) -> bool:
        if not self.config.managed_resources:
            return True
        requested = pod_resource_requests(pod)
        return any(r in requested for r in self.config.managed_resources)

    def is_ignorable(self) -> bool:
        return self.config.ignorable

    # -- filter (extender.go:334) -------------------------------------------

    def filter(
        self, pod: Pod, nodes: List[NodeInfo]
    ) -> Tuple[List[NodeInfo], Dict[str, str]]:
        """Returns (feasible, failed{node: reason}). Raises on transport
        error unless ignorable (caller treats ignorable errors as
        pass-through, extender.go:509 comment / generic_scheduler.go:507)."""
        if not self.config.filter_verb:
            return nodes, {}
        # wire format (extender/v1 ExtenderArgs): cache-capable extenders
        # exchange bare node names; others exchange full node objects
        # (extender.go:356-377)
        args = {"pod": _pod_to_wire(pod)}
        if self.config.node_cache_capable:
            args["nodenames"] = [ni.node_name for ni in nodes]
        else:
            args["nodes"] = {
                "items": [
                    {"metadata": {"name": ni.node_name}} for ni in nodes
                ]
            }
        try:
            result = self._send(self.config.filter_verb, args)
        except Exception:
            if self.config.ignorable:
                logger.warning(
                    "ignoring failed ignorable extender %s",
                    self.config.url_prefix,
                )
                return nodes, {}
            raise
        if result.get("error"):
            raise RuntimeError(result["error"])
        failed = dict(result.get("failedNodes") or {})
        if self.config.node_cache_capable:
            kept = result.get("nodeNames")
        else:
            items = (result.get("nodes") or {}).get("items")
            kept = (
                [n["metadata"]["name"] for n in items]
                if items is not None
                else None
            )
        if kept is None:
            kept_set = {ni.node_name for ni in nodes} - set(failed)
        else:
            kept_set = set(kept)
        return [ni for ni in nodes if ni.node_name in kept_set], failed

    # -- prioritize (extender.go:404) ----------------------------------------

    def prioritize(self, pod: Pod, nodes: List[NodeInfo]) -> Dict[str, int]:
        """Returns {node: weighted_score} merged into the plugin totals."""
        if not self.config.prioritize_verb:
            return {}
        args = {"pod": _pod_to_wire(pod)}
        if self.config.node_cache_capable:
            args["nodenames"] = [ni.node_name for ni in nodes]
        else:
            args["nodes"] = {
                "items": [
                    {"metadata": {"name": ni.node_name}} for ni in nodes
                ]
            }
        try:
            result = self._send(self.config.prioritize_verb, args)
        except Exception:
            if self.config.ignorable:
                return {}
            raise
        return {
            hp["host"]: int(hp["score"]) * self.config.weight
            for hp in result or []
        }

    # -- bind (extender.go:446) ----------------------------------------------

    def is_binder(self) -> bool:
        return bool(self.config.bind_verb)

    def bind(self, pod: Pod, host: str) -> None:
        result = self._send(
            self.config.bind_verb,
            {
                "podName": pod.metadata.name,
                "podNamespace": pod.metadata.namespace,
                "podUID": pod.metadata.uid,
                "node": host,
            },
        )
        if result and result.get("error"):
            raise RuntimeError(result["error"])

    # -- preemption (extender.go:243 ProcessPreemption) -----------------------

    def supports_preemption(self) -> bool:
        return bool(self.config.preempt_verb)

    def process_preemption(
        self, pod: Pod, nodes_to_victims: Dict[str, object]
    ) -> Dict[str, object]:
        """Narrows the candidate victim map; values are Victims objects
        (preemption.py). Wire format uses node->metaVictims with pod uids."""
        args = {
            "pod": _pod_to_wire(pod),
            "nodeNameToMetaVictims": {
                node: {
                    "pods": [
                        {"uid": v.metadata.uid} for v in victims.pods
                    ],
                    "numPDBViolations": victims.num_pdb_violations,
                }
                for node, victims in nodes_to_victims.items()
            },
        }
        try:
            result = self._send(self.config.preempt_verb, args)
        except Exception:
            if self.config.ignorable:
                return nodes_to_victims
            raise
        kept = result.get("nodeNameToMetaVictims")
        if kept is None:
            return nodes_to_victims
        out = {}
        for node, meta in kept.items():
            if node not in nodes_to_victims:
                continue
            uids = {p["uid"] for p in meta.get("pods", [])}
            victims = nodes_to_victims[node]
            victims.pods = [v for v in victims.pods if v.metadata.uid in uids]
            out[node] = victims
        return out
