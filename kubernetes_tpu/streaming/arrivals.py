"""Arrival processes: seeded trace generators + the paced ArrivalEngine.

A *trace* is a sorted float64 array of arrival offsets (seconds from the
trace start). Generators are deterministic in their seed -- the same
``(kind, params, seed)`` tuple always produces the same trace, so an
open-loop run is replayable and two policies compared on "the same
trace" really see identical arrival instants (bench.py --mode
open-loop; the trace seed rides in every record).

The ``ArrivalEngine`` replays a trace against a live apiserver on its
own thread: pods whose offset has come due are created in bounded bulk
chunks, and each pod's ``created_ts`` is stamped with the wall clock at
the moment of the create call -- pod-to-bind latency is measured
end-to-end from the arrival process, not per-drain.

Backpressure is explicit: with ``max_queue_depth`` set, the engine
checks the scheduler-side depth gauge (normally
``queue.active_count``) before every chunk and STALLS -- counted in
``backpressure_stalls``/``stall_seconds`` and the
``scheduler_arrival_backpressure_stalls_total`` metric -- until the
queue drains below the resume watermark, instead of growing the activeQ
heap without bound. A stalled engine is the honest open-loop signal
that the offered rate exceeded capacity; the bench treats any stall as
an SLO failure at that rate.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from kubernetes_tpu.utils import flightrecorder, metrics

#: pods created per bulk API call when a burst of offsets is due at once
#: (matches the chunked ingest the closed-loop bench uses)
CREATE_CHUNK = 256


def poisson_trace(
    rate: float, duration: float, seed: int = 0
) -> np.ndarray:
    """Homogeneous Poisson arrivals at ``rate`` pods/s for ``duration``
    seconds: i.i.d. exponential inter-arrival gaps, cumulatively
    summed."""
    if rate <= 0 or duration <= 0:
        return np.empty(0, dtype=np.float64)
    rng = np.random.default_rng(seed)
    out: List[np.ndarray] = []
    t = 0.0
    # draw in slabs until the horizon is covered (vectorized; the tail
    # slab overshoots and is trimmed)
    while t < duration:
        n = max(64, int(rate * (duration - t) * 1.2) + 32)
        gaps = rng.exponential(1.0 / rate, size=n)
        offs = t + np.cumsum(gaps)
        out.append(offs)
        t = float(offs[-1])
    offsets = np.concatenate(out)
    return offsets[offsets < duration]


def bursty_trace(
    base_rate: float,
    burst_rate: float,
    duration: float,
    seed: int = 0,
    base_dwell: float = 8.0,
    burst_dwell: float = 2.0,
) -> np.ndarray:
    """Two-state MMPP (Markov-modulated Poisson process): exponential
    dwell times alternate a ``base_rate`` state with a ``burst_rate``
    state -- the flash-crowd shape a static batch window can't serve
    well at both ends."""
    if duration <= 0:
        return np.empty(0, dtype=np.float64)
    rng = np.random.default_rng(seed)
    out: List[np.ndarray] = []
    t = 0.0
    in_burst = False
    while t < duration:
        rate = burst_rate if in_burst else base_rate
        dwell = rng.exponential(burst_dwell if in_burst else base_dwell)
        end = min(duration, t + dwell)
        if rate > 0:
            seg = t + np.cumsum(
                rng.exponential(
                    1.0 / rate, size=max(16, int(rate * dwell * 1.2) + 16)
                )
            )
            out.append(seg[seg < end])
        t = end
        in_burst = not in_burst
    if not out:
        return np.empty(0, dtype=np.float64)
    return np.concatenate(out)


def diurnal_trace(
    peak_rate: float,
    duration: float,
    seed: int = 0,
    period: float = 60.0,
    trough_fraction: float = 0.2,
) -> np.ndarray:
    """Non-homogeneous Poisson with a raised-cosine rate ramp between
    ``trough_fraction * peak_rate`` and ``peak_rate`` over ``period``
    seconds (the compressed day/night cycle), sampled by thinning
    against the peak rate."""
    if peak_rate <= 0 or duration <= 0:
        return np.empty(0, dtype=np.float64)
    candidates = poisson_trace(peak_rate, duration, seed)
    if candidates.size == 0:
        return candidates
    rng = np.random.default_rng(seed + 1)
    trough = trough_fraction * peak_rate
    # rate(t) peaks mid-period: trough + (peak-trough) * (1-cos)/2
    lam = trough + (peak_rate - trough) * 0.5 * (
        1.0 - np.cos(2.0 * np.pi * candidates / period)
    )
    keep = rng.random(candidates.size) < lam / peak_rate
    return candidates[keep]


def replay_trace(path: str) -> np.ndarray:
    """Load a recorded trace: a JSON list of offsets, or an object with
    an ``offsets`` key (the shape ``save_trace`` writes)."""
    with open(path) as f:
        raw = json.load(f)
    offsets = raw["offsets"] if isinstance(raw, dict) else raw
    return np.sort(np.asarray(offsets, dtype=np.float64))


def save_trace(path: str, offsets: np.ndarray, **meta) -> None:
    """Persist a trace for replay; extra keys ride alongside so a
    recorded production trace can carry its provenance."""
    with open(path, "w") as f:
        json.dump({"offsets": [float(x) for x in offsets], **meta}, f)


def load_trace(
    kind: str,
    rate: float,
    duration: float,
    seed: int = 0,
    *,
    burst_rate: float = 0.0,
    base_dwell: float = 8.0,
    burst_dwell: float = 2.0,
    period: float = 60.0,
    trough_fraction: float = 0.2,
    replay_path: str = "",
) -> np.ndarray:
    """Dispatch on trace ``kind`` -- the single entry point bench.py,
    the perf-matrix runner, and the config wiring share."""
    if kind == "poisson":
        return poisson_trace(rate, duration, seed)
    if kind == "bursty":
        return bursty_trace(
            rate, burst_rate or 4.0 * rate, duration, seed,
            base_dwell=base_dwell, burst_dwell=burst_dwell,
        )
    if kind == "diurnal":
        return diurnal_trace(
            rate, duration, seed,
            period=period, trough_fraction=trough_fraction,
        )
    if kind == "replay":
        if not replay_path:
            raise ValueError("trace kind 'replay' needs replay_path")
        return replay_trace(replay_path)
    raise ValueError(
        f"unknown trace kind {kind!r} "
        f"(poisson|bursty|diurnal|replay)"
    )


def trace_from_config(st, duration: Optional[float] = None) -> np.ndarray:
    """Build a trace from a ``StreamingConfiguration`` (the
    ``streaming:`` block's trace half): kind, rate, seed, and the
    per-kind shape knobs. ``duration`` overrides
    ``st.duration_seconds`` (the perf-matrix runner sizes it to the
    workload's pod count)."""
    return load_trace(
        st.trace,
        st.rate_pods_per_sec,
        st.duration_seconds if duration is None else duration,
        st.seed,
        burst_rate=st.burst_rate_pods_per_sec,
        base_dwell=st.base_dwell_seconds,
        burst_dwell=st.burst_dwell_seconds,
        period=st.period_seconds,
        trough_fraction=st.trough_fraction,
        replay_path=st.replay_path,
    )


class ArrivalEngine:
    """Replay a trace of arrival offsets against the apiserver on a
    paced daemon thread.

    ``pod_factory(i)`` builds the i-th pod (the caller owns naming and
    shape -- priority bands, workload specs). ``depth_fn`` +
    ``max_queue_depth`` form the backpressure gate; ``created_ts``
    maps pod name -> ``time.perf_counter()`` at the create call."""

    def __init__(
        self,
        client,
        offsets: np.ndarray,
        pod_factory: Callable[[int], object],
        *,
        depth_fn: Optional[Callable[[], int]] = None,
        max_queue_depth: int = 0,
        resume_fraction: float = 0.8,
        poll_interval: float = 0.005,
    ) -> None:
        self._client = client
        self._offsets = np.asarray(offsets, dtype=np.float64)
        self._factory = pod_factory
        self._depth_fn = depth_fn
        self._max_depth = int(max_queue_depth)
        self._resume_depth = int(max_queue_depth * resume_fraction)
        self._poll = poll_interval
        self.created_ts: Dict[str, float] = {}
        self.created = 0
        self.backpressure_stalls = 0
        self.stall_seconds = 0.0
        self._stop = threading.Event()
        self.done = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="arrival-engine", daemon=True
        )

    def start(self) -> "ArrivalEngine":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    def join(self, timeout: Optional[float] = None) -> bool:
        return self.done.wait(timeout)

    # -- internals -----------------------------------------------------------

    def _gate(self) -> None:
        """Backpressure: block while the scheduler-side queue depth sits
        at or above the bound; resume below the low watermark so the
        gate doesn't chatter at the boundary."""
        if not self._max_depth or self._depth_fn is None:
            return
        if self._depth_fn() < self._max_depth:
            return
        self.backpressure_stalls += 1
        metrics.backpressure_stalls.inc()
        t0 = time.perf_counter()
        while not self._stop.is_set():
            if self._depth_fn() <= self._resume_depth:
                break
            self._stop.wait(self._poll)
        stalled = time.perf_counter() - t0
        self.stall_seconds += stalled
        metrics.backpressure_stall_seconds.inc(stalled)
        flightrecorder.mark(
            "arrival_stall", seconds=round(stalled, 4),
            stalls=self.backpressure_stalls,
        )
        # the --trace timeline gets the stall as a span on the
        # arrival-engine track (a stalled engine means the offered rate
        # did not actually enter the system -- that must be visible
        # next to the solve spans it starves)
        flightrecorder.trace_span(
            "backpressure_stall", t0, stalled, track="arrival-engine",
        )

    def _run(self) -> None:
        offsets = self._offsets
        n = offsets.size
        base = time.perf_counter()
        i = 0
        try:
            while i < n and not self._stop.is_set():
                now = time.perf_counter() - base
                due = offsets[i]
                if now < due:
                    self._stop.wait(min(due - now, 0.05))
                    continue
                self._gate()
                if self._stop.is_set():
                    return
                # everything due by the post-gate clock goes out in
                # bounded bulk chunks (a stall releases as one burst --
                # exactly what the backlog it waited out looks like)
                now = time.perf_counter() - base
                j = i
                while (
                    j < n and offsets[j] <= now and j - i < CREATE_CHUNK
                ):
                    j += 1
                pods = [self._factory(k) for k in range(i, j)]
                ts = time.perf_counter()
                for p in pods:
                    self.created_ts[p.metadata.name] = ts
                self._client.create_pods_bulk(pods)
                self.created += len(pods)
                i = j
        finally:
            self.done.set()
