"""SLO-adaptive batch controller: the feedback loop that replaces the
static ``batch_window``/``max_batch`` knobs.

The dispatcher's batching knobs trade latency for throughput: a short
window dispatches small, cheap-to-wait-for batches (every solve still
pays the fixed padded-dispatch cost), a long window fills batches
toward ``max_batch`` and amortizes that fixed cost. No static setting
serves an arrival *process* at both ends -- shallow-queue periods want
the short window, backlog wants the long one. The
``AutoBatchController`` closes the loop from the signals the dispatch
path already produces:

- queue depth (``queue.active_count``) and the pop counter
  (``queue.scheduling_cycle``) give a drain-rate estimate, so
  ``depth / rate`` estimates the backlog sojourn a pod joining now
  will see;
- the always-on per-thread stage timers (PR 4) split ``pop_wait``
  (dispatcher blocked on arrivals) from drain work, so a transiently
  deep queue on an otherwise-idle dispatcher doesn't trigger a grow.

Control law (deliberately simple, deterministic, and hysteretic):

- **throughput mode** when the estimated sojourn exceeds
  ``grow_fraction * slo``: double the window toward ``max_window``
  (clamped to ``slo/2`` -- the window itself must never spend the
  latency budget) and step the dispatch cap ONE RUNG up the solve-pad
  ladder (default ladder = the two poles, so this is "to max_batch";
  with ``auto_rungs`` the ladder is sized from the measured per-pad
  solve cost at warmup and calibrate() prunes rungs that don't pay).
- **latency mode** when the estimated sojourn is under
  ``shrink_fraction * slo`` AND the queue is shallower than one
  latency-mode batch: halve the window toward ``min_window`` and step
  the cap one rung down toward ``latency_batch`` (which also shrinks
  the padded solve shape -- small batches stop paying the full-pad
  solve cost).
- **hold** inside the hysteresis band -- on a steady trace the
  controller converges and stops moving (the tier-1 oscillation guard
  pins this).

Overload is special-cased (ROADMAP item-2 residual b): at sustained
overload the RAW pressure signal whipsaws -- a deep queue pins the
sojourn estimate, the resulting max-batch drain empties the window's
view of the queue, the estimate collapses, the controller shrinks, the
backlog re-forms, it grows again (~10 window moves inside a failing
rung measured on this box). Two mechanisms calm it:

- the decision signal is an **EWMA** of the pressure ratio
  (``pressure_ewma_alpha``), so one big drain can't fake a recovery;
- crossing the grow threshold ``latch_after_steps`` consecutive times
  **latches throughput mode**: shrinks are blocked until the smoothed
  pressure stays under the shrink threshold for
  ``unlatch_after_steps`` consecutive decisions. A latched controller
  parked at the throughput pole makes at most the initial grow moves
  on a sustained overload series (unit-pinned at <= 2).


``step()`` is a pure function of its arguments plus controller state:
a fixed input sequence always produces the same window/cap trajectory
(deterministic-trace convergence tests). ``maybe_step()`` is the
time-gated wrapper the dispatch loop calls once per
``interval_seconds``.
"""

from __future__ import annotations

import time
from typing import Optional

from kubernetes_tpu.utils import flightrecorder, metrics

#: batch sizes quantize to this (mirrors scheduler/batch.py POD_BUCKET
#: without importing the scheduler -- the controller must stay
#: dependency-light so the queue/bench layers can use it standalone)
BATCH_BUCKET = 64


class AutoBatchController:
    def __init__(
        self,
        *,
        slo_p99_seconds: float = 1.0,
        min_window: float = 0.0,
        max_window: Optional[float] = None,
        latency_batch: int = 512,
        max_batch: int = 4096,
        interval_seconds: float = 0.25,
        grow_fraction: float = 0.5,
        shrink_fraction: float = 0.15,
        grow_floor_window: float = 0.02,
        idle_grow_guard: float = 0.5,
        pressure_ewma_alpha: float = 0.4,
        latch_after_steps: int = 2,
        unlatch_after_steps: int = 4,
        rungs: Optional[list] = None,
        auto_rungs: bool = False,
        now=time.monotonic,
    ) -> None:
        """``rungs``: explicit solve-pad ladder (batch caps, ascending;
        endpoints ``latency_batch``/``max_batch`` are always included).
        ``auto_rungs``: seed a geometric candidate ladder between the
        two poles instead of the hardcoded two rungs; the scheduler's
        ``warmup()`` measures every candidate's per-pad solve cost and
        ``calibrate`` prunes rungs that don't pay -- every surviving
        rung is pre-compiled, so a rung switch never pays JIT mid-run
        (ROADMAP item-2a residual)."""
        if slo_p99_seconds <= 0:
            raise ValueError("slo_p99_seconds must be positive")
        self.slo = slo_p99_seconds
        self.min_window = max(0.0, min_window)
        # the window is spent INSIDE the latency budget; cap it at half
        # the SLO so batching alone can never burn the whole budget
        cap = 0.5 * slo_p99_seconds
        self.max_window = min(
            cap, max_window if max_window is not None else 0.25
        )
        self.max_window = max(self.max_window, self.min_window)
        self.max_batch = max(BATCH_BUCKET, int(max_batch))
        lb = min(int(latency_batch), self.max_batch)
        self.latency_batch = max(
            BATCH_BUCKET,
            BATCH_BUCKET * (lb // BATCH_BUCKET),
        )
        # -- the solve-pad rung ladder ------------------------------------
        self.auto_rungs = bool(auto_rungs)
        if rungs is None and self.auto_rungs:
            rungs = self.candidate_rungs(self.latency_batch, self.max_batch)
        if rungs is None:
            rungs = [self.latency_batch, self.max_batch]
        self.rungs = self._normalize_rungs(rungs)
        self.interval = interval_seconds
        self.grow_fraction = grow_fraction
        self.shrink_fraction = shrink_fraction
        self.grow_floor_window = max(grow_floor_window, 1e-4)
        self.idle_grow_guard = idle_grow_guard
        self._now = now

        # controller outputs (read by the dispatcher every batch)
        self.window = self.min_window
        self.batch_cap = self.latency_batch

        # trajectory / oscillation visibility
        self.steps = 0
        self.window_changes = 0
        self.cap_changes = 0
        self.grows = 0
        self.shrinks = 0

        self._last_t: Optional[float] = None
        self._last_cycle = 0
        self._last_pop_wait = 0.0
        self._last_step_t: Optional[float] = None

        # -- overload latch state (EWMA-smoothed pressure) ----------------
        self.pressure_ewma_alpha = min(1.0, max(0.0, pressure_ewma_alpha))
        self.latch_after_steps = max(1, int(latch_after_steps))
        self.unlatch_after_steps = max(1, int(unlatch_after_steps))
        self.pressure_ewma = 0.0
        self.latched = False
        self.latches = 0  # times the latch engaged (visibility)
        self._over_streak = 0
        self._calm_streak = 0

    # -- the solve-pad rung ladder -------------------------------------------

    @staticmethod
    def candidate_rungs(latency_batch: int, max_batch: int) -> list:
        """Geometric candidate ladder between the two poles (doubling):
        the starting point calibration prunes from."""
        out = []
        r = max(BATCH_BUCKET, int(latency_batch))
        while r < max_batch:
            out.append(r)
            r *= 2
        out.append(int(max_batch))
        return out

    def _normalize_rungs(self, rungs) -> list:
        """Bucket-quantized, clamped, deduplicated ascending ladder that
        always contains both poles (a cap the dispatcher never pads to
        would fork an unwarmed jit signature)."""
        norm = {self.latency_batch, self.max_batch}
        for r in rungs:
            r = int(r)
            r = max(BATCH_BUCKET, BATCH_BUCKET * (r // BATCH_BUCKET))
            # only strictly-interior rungs: quantizing a value at/past a
            # pole must not mint a near-duplicate of that pole
            if self.latency_batch < r < self.max_batch:
                norm.add(r)
        return sorted(norm)

    def calibrate(self, pad_costs: dict, keep_fraction: float = 0.8):
        """Prune the candidate ladder from MEASURED per-pad solve cost
        (``BatchScheduler.warmup`` times one steady solve per compiled
        pad): a middle rung survives only when its solve costs at most
        ``keep_fraction`` of the next kept rung above -- a rung that
        isn't meaningfully cheaper buys no latency and only adds
        controller churn. The poles always survive; an unmeasured
        middle rung drops (it was never compiled, so switching to it
        would pay JIT mid-run -- the exact thing the ladder exists to
        prevent). No-op unless ``auto_rungs``. Returns the ladder."""
        if not self.auto_rungs or len(self.rungs) <= 2:
            return self.rungs
        kept = [self.rungs[-1]]
        for r in reversed(self.rungs[:-1]):
            if r == self.rungs[0]:
                kept.append(r)
                continue
            cost = pad_costs.get(r)
            above = pad_costs.get(kept[-1])
            if cost is None or above is None or above <= 0:
                continue
            if cost <= keep_fraction * above:
                kept.append(r)
        self.rungs = sorted(set(kept))
        if self.batch_cap not in self.rungs:
            fitting = [r for r in self.rungs if r >= self.batch_cap]
            self.batch_cap = fitting[0] if fitting else self.rungs[-1]
        return self.rungs

    def _cap_up(self) -> int:
        for r in self.rungs:
            if r > self.batch_cap:
                return r
        return self.rungs[-1]

    def _cap_down(self) -> int:
        for r in reversed(self.rungs):
            if r < self.batch_cap:
                return r
        return self.rungs[0]

    # -- the control law ----------------------------------------------------

    def step(
        self,
        depth: int,
        popped_cycle: int,
        t: float,
        pop_wait_seconds: Optional[float] = None,
    ) -> str:
        """One controller decision from (queue depth, cumulative pop
        counter, clock, cumulative pop_wait stage seconds). Returns the
        direction taken: "grow" | "shrink" | "hold". Pure in its inputs
        + controller state -- no clock or RNG reads."""
        self.steps += 1
        if self._last_t is None:
            self._last_t = t
            self._last_cycle = popped_cycle
            if pop_wait_seconds is not None:
                self._last_pop_wait = pop_wait_seconds
            return "hold"
        dt = t - self._last_t
        if dt <= 0:
            return "hold"
        rate = max(0.0, (popped_cycle - self._last_cycle) / dt)
        idle_frac = 0.0
        if pop_wait_seconds is not None:
            idle_frac = max(
                0.0, min(1.0, (pop_wait_seconds - self._last_pop_wait) / dt)
            )
            self._last_pop_wait = pop_wait_seconds
        self._last_t = t
        self._last_cycle = popped_cycle

        if rate > 0:
            wait_est = depth / rate
        else:
            # nothing drained this interval: a backlog with no drain is
            # saturation (estimate pins to the SLO, forcing a grow); an
            # empty queue with no drain is plain idle
            wait_est = self.slo if depth > 0 else 0.0
        raw_pressure = wait_est / self.slo
        # the DECISION signal is the smoothed pressure: one max-batch
        # drain that momentarily empties the queue can no longer fake a
        # recovery mid-overload (the pole-hunting residual)
        a = self.pressure_ewma_alpha
        self.pressure_ewma = a * raw_pressure + (1.0 - a) * self.pressure_ewma
        pressure = self.pressure_ewma

        # latch bookkeeping: consecutive over-threshold decisions engage
        # it; consecutive calm decisions release it
        if pressure > self.grow_fraction and (
            idle_frac < self.idle_grow_guard
        ):
            # the idle-dispatcher guard applies to the latch too: depth
            # piling up while the dispatcher is blocked on arrivals is
            # not overload, and must neither grow nor latch
            self._over_streak += 1
            self._calm_streak = 0
            if (
                not self.latched
                and self._over_streak >= self.latch_after_steps
            ):
                self.latched = True
                self.latches += 1
                metrics.autobatch_latched.set(1.0)
                # sustained overload: walking the window up one
                # doubling per interval just prolongs the failing rung.
                # Jump straight to the throughput pole (top rung) and
                # hold there.
                return self._apply(
                    "grow", (self.max_window, self.rungs[-1])
                )
        elif pressure < self.shrink_fraction:
            self._calm_streak += 1
            self._over_streak = 0
            if self.latched and self._calm_streak >= self.unlatch_after_steps:
                self.latched = False
                metrics.autobatch_latched.set(0.0)
        else:
            self._over_streak = 0
            self._calm_streak = 0

        if pressure > self.grow_fraction and idle_frac < self.idle_grow_guard:
            return self._apply("grow", self._grown())
        if (
            pressure < self.shrink_fraction
            and depth <= self.latency_batch
            and not self.latched
        ):
            return self._apply("shrink", self._shrunk())
        return "hold"

    def _grown(self):
        window = min(
            self.max_window, max(self.grow_floor_window, self.window * 2.0)
        )
        return window, self._cap_up()

    def _shrunk(self):
        if self.window <= self.grow_floor_window:
            window = self.min_window
        else:
            window = max(self.min_window, self.window / 2.0)
        return window, self._cap_down()

    def _apply(self, direction: str, target) -> str:
        window, cap = target
        changed = False
        if window != self.window:
            self.window = window
            self.window_changes += 1
            changed = True
        if cap != self.batch_cap:
            self.batch_cap = cap
            self.cap_changes += 1
            changed = True
        if not changed:
            # already pinned at the pole: not a decision, not a change
            return "hold"
        if direction == "grow":
            self.grows += 1
        else:
            self.shrinks += 1
        metrics.autobatch_decisions.inc(direction=direction)
        metrics.autobatch_window.set(self.window)
        metrics.autobatch_batch_cap.set(float(self.batch_cap))
        flightrecorder.mark(
            "autobatch", direction=direction,
            window_ms=round(self.window * 1000.0, 3),
            cap=self.batch_cap,
        )
        # --trace timelines show controller moves as instant events on
        # their own track, between the stage spans they retune
        flightrecorder.trace_instant(
            f"autobatch_{direction}",
            args={"window_ms": round(self.window * 1000.0, 3),
                  "cap": self.batch_cap},
            track="autobatch",
        )
        return direction

    # -- dispatcher-facing wrapper -------------------------------------------

    def maybe_step(self, sched) -> Optional[str]:
        """Time-gated poll from the dispatch loop: at most one decision
        per ``interval_seconds``, reading the live queue + stage-timer
        signals and pushing the outputs onto the scheduler
        (``batch_window``, ``dispatch_batch_cap``, ``solve_pad``)."""
        t = self._now()
        if (
            self._last_step_t is not None
            and t - self._last_step_t < self.interval
        ):
            return None
        self._last_step_t = t
        direction = self.step(
            sched.queue.active_count(),
            sched.queue.scheduling_cycle,
            t,
            sched.stage_seconds.get("pop_wait", 0.0),
        )
        sched.batch_window = self.window
        sched.dispatch_batch_cap = self.batch_cap
        sched.solve_pad = self.batch_cap
        return direction
