"""Open-loop streaming subsystem (ROADMAP item 2): arrival processes,
SLO-adaptive batching, and priority-band backpressure.

Every closed-loop burst number answers "how fast can the drain go"; the
production question is "how much sustained arrival traffic fits under a
fixed p99 pod-to-bind budget". This package supplies the three parts
that turn the burst bench into that measurement:

- ``arrivals``:  seeded trace generators (Poisson, bursty/MMPP, diurnal
  ramp, replay-from-JSON) and a paced ``ArrivalEngine`` that feeds pods
  into the apiserver continuously, recording per-pod ``created_ts`` so
  pod-to-bind latency is end-to-end, with explicit backpressure (a
  bounded activeQ depth stalls the engine instead of growing the heap
  without bound).
- ``autobatch``: the ``AutoBatchController`` feedback loop that replaces
  the static ``batch_window``/``max_batch`` knobs -- latency mode when
  the queue is shallow, throughput mode when backlog builds, anchored to
  a configured p99 pod-to-bind SLO.
- priority-band queue jumping lives in
  ``kubernetes_tpu/queue/scheduling_queue.py`` (``band_threshold``):
  high-priority pods never wait out a batch window behind a bulk drain.
"""

from kubernetes_tpu.streaming.arrivals import (  # noqa: F401
    ArrivalEngine,
    bursty_trace,
    diurnal_trace,
    load_trace,
    poisson_trace,
    replay_trace,
    trace_from_config,
)
from kubernetes_tpu.streaming.autobatch import (  # noqa: F401
    AutoBatchController,
)
