"""Lightweight wall-clock timeline for the burst hot path.

Enabled with KTPU_TIMELINE=1: hot-path stages call ``mark(name)`` /
``span(name)`` and the bench dumps a per-stage summary at exit. Zero
overhead when disabled (marks compile to a no-op lambda).

This is the in-window view the cProfile dump can't give: cumulative
profiles mix setup (5k node creation, warmup compiles) with the measured
window, and thread wait-time attribution drowns the real CPU costs.
"""

from __future__ import annotations

import os
import threading
import time
from collections import defaultdict, deque
from typing import Dict, List, Tuple

ENABLED = os.environ.get("KTPU_TIMELINE") == "1"

#: bounded: a long-lived process with KTPU_TIMELINE=1 must not grow
#: memory monotonically; the bench window is far smaller than this
_events: "deque" = deque(maxlen=500_000)  # (t, name, dur)
_lock = threading.Lock()


if ENABLED:

    def mark(name: str, dur: float = 0.0) -> None:
        with _lock:
            _events.append((time.perf_counter(), name, dur))

else:

    def mark(name: str, dur: float = 0.0) -> None:  # type: ignore[misc]
        pass


class span:
    """Context manager recording the duration of one stage."""

    __slots__ = ("name", "t0")

    def __init__(self, name: str) -> None:
        self.name = name

    def __enter__(self) -> "span":
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        if ENABLED:
            mark(self.name, time.perf_counter() - self.t0)


def reset() -> None:
    with _lock:
        _events.clear()


def summary() -> Dict[str, Tuple[int, float]]:
    """name -> (count, total_seconds) for spans; marks have dur 0."""
    out: Dict[str, List[float]] = defaultdict(lambda: [0, 0.0])
    with _lock:
        for _, name, dur in _events:
            rec = out[name]
            rec[0] += 1
            rec[1] += dur
    return {k: (int(v[0]), v[1]) for k, v in out.items()}


def dump(t_origin: float = 0.0) -> str:
    lines = []
    with _lock:
        for t, name, dur in sorted(_events):
            lines.append(
                f"{(t - t_origin) * 1000:9.1f}ms  {name:32s} "
                f"{dur * 1000:8.2f}ms"
            )
    return "\n".join(lines)
