"""Cyclic-GC tuning for the scheduling hot path.

A synced control plane holds a large, long-lived object graph (nodes,
cached pods, informer stores). Scheduling bursts allocate heavily, and
CPython's generational collector rescans that whole graph every few
hundred net allocations: measured ~1.2s of GC pause across ~1500
collections during one 10k-pod burst (roughly 2x wall clock). Freezing
the steady-state graph into the permanent generation and stretching the
thresholds removes those rescans -- the standard long-lived-graph
mitigation for CPython services.
"""

from __future__ import annotations

import gc
import time as _time


def freeze_steady_state_graph(
    gen0: int = 100_000, gen1: int = 50, gen2: int = 50
) -> None:
    """Call once the long-lived state is built (after informer sync /
    before the measured burst)."""
    gc.collect()
    gc.freeze()
    gc.set_threshold(gen0, gen1, gen2)


class GCBatchGuard:
    """Collect-at-idle policy for the batch dispatcher.

    Even with the steady-state graph frozen and thresholds stretched, a
    10k-pod burst allocates enough (clones, watch events, queue entries,
    solver bookkeeping) to trigger several young-generation collections
    INSIDE the measured window; each scans the whole unfrozen young set
    (measured ~7us/pod of the commit path -- 4x the actual object work).
    The scheduler knows its own idle points (queue drained, nothing in
    flight), so cyclic collection is disabled while batches are being
    scheduled and runs once at the active->idle transition. Plain
    refcounting still reclaims the (acyclic) burst garbage immediately;
    the deferred pass only exists to catch stray cycles (tracebacks,
    closures)."""

    #: under SUSTAINED load (the queue never drains) a bounded young-
    #: generation collect runs at most this often, so stray cycles from a
    #: long active phase cannot grow RSS without bound
    ACTIVE_COLLECT_INTERVAL_S = 10.0
    #: every Nth in-flight collect runs the FULL collector: gen-1-only
    #: passes promote surviving cycles to gen 2, which would otherwise
    #: wait for an idle transition that sustained load never reaches
    FULL_COLLECT_EVERY = 6

    def __init__(self) -> None:
        self._active = False
        self._last_collect = 0.0
        self._active_collects = 0

    def active(self) -> None:
        if not self._active:
            gc.disable()
            self._active = True
            self._last_collect = _time.monotonic()
            self._active_collects = 0
            return
        now = _time.monotonic()
        if now - self._last_collect >= self.ACTIVE_COLLECT_INTERVAL_S:
            # explicit collect works while the collector is disabled;
            # gen-1 keeps the pause bounded (young objects only), with a
            # periodic full pass to drain gen-2 promotions
            self._active_collects += 1
            if self._active_collects % self.FULL_COLLECT_EVERY == 0:
                gc.collect()
            else:
                gc.collect(1)
            self._last_collect = now

    def idle(self) -> None:
        if self._active:
            gc.enable()
            gc.collect()
            self._active = False

    def close(self) -> None:
        self.idle()
