"""Cyclic-GC tuning for the scheduling hot path.

A synced control plane holds a large, long-lived object graph (nodes,
cached pods, informer stores). Scheduling bursts allocate heavily, and
CPython's generational collector rescans that whole graph every few
hundred net allocations: measured ~1.2s of GC pause across ~1500
collections during one 10k-pod burst (roughly 2x wall clock). Freezing
the steady-state graph into the permanent generation and stretching the
thresholds removes those rescans -- the standard long-lived-graph
mitigation for CPython services.
"""

from __future__ import annotations

import gc


def freeze_steady_state_graph(
    gen0: int = 100_000, gen1: int = 50, gen2: int = 50
) -> None:
    """Call once the long-lived state is built (after informer sync /
    before the measured burst)."""
    gc.collect()
    gc.freeze()
    gc.set_threshold(gen0, gen1, gen2)
