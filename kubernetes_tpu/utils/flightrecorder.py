"""Always-on flight recorder: the last K batch spans + control-plane
marks, dumpable as JSON when something goes wrong.

Twelve PRs of robustness machinery (ladders, breakers, waves,
partitions) degrade observably only as scattered counters; this module
is the single record that reconstructs *what happened to batch N*:

- ``BatchSpan``: one monotonically-numbered record per dispatch --
  batch size, pad shape, solver tier actually run, carry decision
  (reuse / delta scatter / full upload), per-stage wall clock, commit
  outcome, conflicts absorbed, per-pod linkage (uid -> batch id,
  queue-wait, attempt count).
- marks: breaker transitions, ladder fallbacks, fault points fired,
  fencing aborts, partition takeovers, preemption waves, mid-run jit
  recompiles, arrival-engine stalls, autobatch decisions.

The ring is bounded (``deque(maxlen=...)``) and lock-cheap: one short
lock hold per span begin / mark; span field updates are owned by the
single thread driving that batch (dispatcher, then committer -- the
pipeline hands the batch off, never shares it). ``KTPU_FLIGHTRECORDER=0``
compiles the spine out (begin_batch returns the no-op NullSpan, mark
returns immediately) -- the arm the overhead microbench compares
against.

Dump triggers: ``/debug/flightrecorder`` (scheduler/app.py), SIGUSR1,
and ``dump_on_degraded`` wherever a component raises the
degraded-health gauge. Chaos e2es assert against ``RECORDER.dump()``
instead of grepping logs.

The module doubles as the Chrome-trace event sink: ``start_trace()``
arms a buffer (bench.py --trace) and every span stage / instant mark
also lands there as a Chrome-trace event; ``export_chrome_trace``
writes JSON that loads in ui.perfetto.dev. Zero cost when not armed
(one None check).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

#: compile-out switch: the spine costs ~1us per op when on; off, the
#: begin/mark calls return immediately (the microbench's OFF arm)
ENABLED = os.environ.get("KTPU_FLIGHTRECORDER", "1") != "0"
SPAN_CAPACITY = int(os.environ.get("KTPU_FLIGHTRECORDER_SPANS", "512"))
MARK_CAPACITY = int(os.environ.get("KTPU_FLIGHTRECORDER_MARKS", "4096"))
#: where degraded-health / SIGUSR1 dumps land
DUMP_DIR = os.environ.get("KTPU_FLIGHTRECORDER_DIR", ".")


class BatchSpan:
    """One dispatch's record. Mutated only by the thread currently
    driving the batch (dispatcher -> committer hand-off; the async bulk
    bind bumps ``conflicts`` last). Lives in the ring from begin, so a
    dump mid-flight shows the batch in its current state."""

    __slots__ = (
        "batch_id", "t_start", "t_end", "size", "padded", "tier",
        "carry", "delta_rows", "stages", "placed", "no_node",
        "gang_masked", "spilled", "volume_retries", "conflicts",
        "routed", "pods", "thread", "extra",
    )

    def __init__(self, batch_id: int, size: int, pods) -> None:
        self.batch_id = batch_id
        self.t_start = time.perf_counter()
        self.t_end: Optional[float] = None
        self.size = size
        self.padded: Optional[int] = None
        self.tier: Optional[str] = None
        self.carry: Optional[str] = None
        self.delta_rows = 0
        self.stages: Dict[str, float] = {}
        self.placed = 0
        self.no_node = 0
        self.gang_masked = 0
        self.spilled = 0
        self.volume_retries = 0
        self.conflicts = 0
        self.routed: Optional[str] = None  # non-device disposition
        #: (pod uid, queue-wait seconds, attempt count) per pod
        self.pods: List[Tuple[str, float, int]] = pods
        self.thread = threading.current_thread().name
        self.extra: Optional[dict] = None

    def stage(self, name: str, seconds: float,
              t0: Optional[float] = None) -> None:
        """Accumulate one stage's wall clock; when the Chrome-trace
        buffer is armed the stage also lands there as a duration event
        on the calling thread's track (t0 = perf_counter at start)."""
        self.stages[name] = self.stages.get(name, 0.0) + seconds
        if _trace is not None and t0 is not None:
            trace_span(name, t0, seconds,
                       args={"batch": self.batch_id})

    def note(self, **fields) -> None:
        for k, v in fields.items():
            if k in BatchSpan.__slots__:
                setattr(self, k, v)
            else:
                if self.extra is None:
                    self.extra = {}
                self.extra[k] = v

    def bump(self, field: str, n: int = 1) -> None:
        setattr(self, field, getattr(self, field) + n)

    def finish(self, tier: Optional[str] = None,
               routed: Optional[str] = None) -> None:
        if tier is not None:
            self.tier = tier
        if routed is not None:
            self.routed = routed
        self.t_end = time.perf_counter()

    def __bool__(self) -> bool:
        return True

    def to_dict(self) -> dict:
        # copy the mutable members first: a dump can run concurrently
        # with the owning thread still stamping stages (mid-flight
        # batch on the debug endpoint / SIGUSR1 path) -- iterating the
        # live dicts would raise "changed size during iteration"
        stages = dict(self.stages)
        pods = list(self.pods)
        extra = dict(self.extra) if self.extra else None
        d = {
            "batch_id": self.batch_id,
            "t_start": self.t_start,
            "t_end": self.t_end,
            "duration_ms": (
                round((self.t_end - self.t_start) * 1000.0, 3)
                if self.t_end is not None else None
            ),
            "size": self.size,
            "padded": self.padded,
            "tier": self.tier,
            "carry": self.carry,
            "delta_rows": self.delta_rows,
            "stages_ms": {
                k: round(v * 1000.0, 3) for k, v in stages.items()
            },
            "placed": self.placed,
            "no_node": self.no_node,
            "gang_masked": self.gang_masked,
            "spilled": self.spilled,
            "volume_retries": self.volume_retries,
            "conflicts": self.conflicts,
            "routed": self.routed,
            "thread": self.thread,
            "pods": [
                {"uid": uid, "queue_wait_ms": round(w * 1000.0, 3),
                 "attempts": att}
                for uid, w, att in pods
            ],
        }
        if extra:
            d["extra"] = extra
        return d


class _NullSpan:
    """The compiled-out span: every spine call is a no-op attribute
    access. Falsy so callers can branch on it cheaply."""

    __slots__ = ()

    def stage(self, name, seconds, t0=None):
        pass

    def note(self, **fields):
        pass

    def bump(self, field, n=1):
        pass

    def finish(self, tier=None, routed=None):
        pass

    def __bool__(self) -> bool:
        return False


NULL_SPAN = _NullSpan()


class FlightRecorder:
    """The bounded ring of spans + marks. One process-global instance
    (``RECORDER``); chaos harnesses may construct private ones."""

    def __init__(self, span_capacity: int = SPAN_CAPACITY,
                 mark_capacity: int = MARK_CAPACITY) -> None:
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=span_capacity)
        self._marks: deque = deque(maxlen=mark_capacity)
        self._next_id = 0

    def begin_batch(self, size: int, pods=()) -> BatchSpan:
        """Allocate the next batch id and enter the span into the ring
        immediately (a mid-flight dump must show in-flight batches)."""
        with self._lock:
            self._next_id += 1
            span = BatchSpan(self._next_id, size, list(pods))
            self._spans.append(span)
        return span

    def mark(self, kind: str, /, **fields) -> None:
        """One timestamped control-plane event (breaker transition,
        fallback, fault fired, fencing abort, takeover, recompile...).
        ``kind`` is positional-only so a field may also be named
        ``kind``; the event kind wins in the dump."""
        self._marks.append((time.perf_counter(), kind, fields))

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()
            self._marks.clear()
            self._next_id = 0

    # -- dumps ---------------------------------------------------------

    def dump(self) -> dict:
        """Snapshot the rings as plain data (JSON-serializable)."""
        with self._lock:
            spans = list(self._spans)
            marks = list(self._marks)
        return {
            "dumped_at": time.time(),
            "perf_counter": time.perf_counter(),
            "next_batch_id": self._next_id,
            "spans": [s.to_dict() for s in spans],
            "marks": [
                # event kind last: it wins over a field named "kind"
                {**fields, "t": t, "kind": kind}
                for t, kind, fields in marks
            ],
        }

    def dump_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.dump(), indent=indent, default=str)

    def dump_to_file(self, reason: str) -> str:
        """Write the dump next to the process (KTPU_FLIGHTRECORDER_DIR)
        and return the path; failures log, never raise (the recorder
        must not take down the path that tripped it)."""
        path = os.path.join(
            DUMP_DIR,
            f"flightrecorder-{int(time.time())}-{reason}.json",
        )
        try:
            with open(path, "w") as f:
                f.write(self.dump_json(indent=1))
            logger.warning("flight recorder dumped to %s (%s)", path, reason)
        except Exception:  # noqa: BLE001 - never take down the caller
            logger.exception("flight recorder dump to %s failed", path)
        return path


RECORDER = FlightRecorder()


def begin_batch(size: int, pods=()) -> BatchSpan:
    if not ENABLED:
        return NULL_SPAN  # type: ignore[return-value]
    return RECORDER.begin_batch(size, pods)


def mark(kind: str, /, **fields) -> None:
    if not ENABLED:
        return
    RECORDER.mark(kind, **fields)


def dump_on_degraded(reason: str) -> Optional[str]:
    """Called wherever a component sets the degraded-health gauge: the
    moment something goes degraded is exactly when the last-K record is
    worth keeping."""
    if not ENABLED:
        return None
    RECORDER.mark("degraded", reason=reason)
    return RECORDER.dump_to_file(reason)


# -- Chrome-trace event buffer (bench.py --trace) ------------------------

_trace: Optional[list] = None
_trace_lock = threading.Lock()
_trace_tids: Dict[str, int] = {}


def start_trace() -> None:
    """Arm the Chrome-trace buffer: from here every span stage, arrival
    stall, and autobatch decision lands as a trace event."""
    global _trace
    with _trace_lock:
        _trace = []
        _trace_tids.clear()


def trace_active() -> bool:
    return _trace is not None


def _tid_for(name: str) -> int:
    """Stable small-int tid per track name, with a Perfetto thread_name
    metadata event emitted on first sight."""
    tid = _trace_tids.get(name)
    if tid is None:
        tid = len(_trace_tids) + 1
        _trace_tids[name] = tid
        _trace.append({  # type: ignore[union-attr]
            "ph": "M", "name": "thread_name", "pid": 1, "tid": tid,
            "args": {"name": name},
        })
    return tid


def trace_span(name: str, t0: float, dur: float,
               track: Optional[str] = None, args: Optional[dict] = None
               ) -> None:
    """One complete ('X') duration event; t0/dur in perf_counter
    seconds, converted to the trace's microsecond clock."""
    buf = _trace
    if buf is None:
        return
    with _trace_lock:
        if _trace is None:
            return
        ev = {
            "ph": "X", "name": name, "pid": 1,
            "tid": _tid_for(track or threading.current_thread().name),
            "ts": t0 * 1e6, "dur": max(dur, 0.0) * 1e6,
        }
        if args:
            ev["args"] = args
        _trace.append(ev)


def trace_instant(name: str, args: Optional[dict] = None,
                  track: Optional[str] = None) -> None:
    buf = _trace
    if buf is None:
        return
    with _trace_lock:
        if _trace is None:
            return
        ev = {
            "ph": "i", "name": name, "pid": 1, "s": "t",
            "tid": _tid_for(track or threading.current_thread().name),
            "ts": time.perf_counter() * 1e6,
        }
        if args:
            ev["args"] = args
        _trace.append(ev)


def stop_trace() -> List[dict]:
    """Disarm and return the collected events."""
    global _trace
    with _trace_lock:
        events, _trace = (_trace or []), None
        _trace_tids.clear()
    return events


def export_chrome_trace(path: str) -> int:
    """Write the armed buffer as Chrome-trace JSON (the object form,
    which Perfetto and chrome://tracing both load) and disarm. Returns
    the event count."""
    events = stop_trace()
    with open(path, "w") as f:
        json.dump(
            {"traceEvents": events, "displayTimeUnit": "ms"}, f
        )
    return len(events)
