"""Shared utilities: metrics, tracing, clock helpers."""
