"""Prometheus-style metrics with the reference's metric names.

Reference: /root/reference/pkg/scheduler/metrics/metrics.go (metric set
:54-:230) and staging/src/k8s.io/component-base/metrics (registry +
text exposition). The names below are kept identical so dashboards and
the perf harness scrape unchanged.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from typing import Callable, Dict, List, Optional, Sequence, Tuple

_DEF_BUCKETS = (
    0.001, 0.002, 0.005, 0.01, 0.02, 0.05, 0.1, 0.2, 0.5, 1, 2, 5, 10,
)


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted(labels.items()))


def _escape_label_value(value) -> str:
    """Prometheus text exposition escaping for label VALUES: backslash,
    double-quote, and line-feed must be escaped or the emitted series is
    unparseable (a fault-point name or node name containing a quote used
    to corrupt the whole scrape)."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt_labels(key: Tuple[Tuple[str, str], ...], extra: str = "") -> str:
    parts = [f'{k}="{_escape_label_value(v)}"' for k, v in key]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class Counter:
    def __init__(self, name: str, help: str, label_names: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._values: Dict[Tuple, float] = {}
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = () if not labels else _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        return self._values.get(_label_key(labels), 0.0)

    def collect(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            for key, v in sorted(self._values.items()):
                out.append(f"{self.name}{_fmt_labels(key)} {v}")
        return out


class Gauge:
    def __init__(
        self,
        name: str,
        help: str,
        label_names: Sequence[str] = (),
        fn: Optional[Callable[[], float]] = None,
    ):
        if fn is not None and label_names:
            # a bare ``fn`` cannot answer for a labeled family --
            # collect() would emit an unlabeled sample under a labeled
            # HELP/TYPE header (a malformed series). Per-label callbacks
            # go through register_callback instead.
            raise ValueError(
                f"gauge {name!r}: a constructor callback cannot carry "
                f"label_names; use register_callback(fn, **labels)"
            )
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self.fn = fn  # callback gauge (unlabeled)
        self._callbacks: Dict[Tuple, Callable[[], float]] = {}
        self._values: Dict[Tuple, float] = {}
        self._lock = threading.Lock()

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[_label_key(labels)] = value

    def register_callback(
        self, fn: Callable[[], float], **labels: str
    ) -> None:
        """Per-label-set callback: collect() calls ``fn`` at scrape
        time for exactly this series (the labeled analogue of the
        constructor ``fn``)."""
        with self._lock:
            self._callbacks[_label_key(labels)] = fn

    def value(self, **labels: str) -> float:
        if self.fn is not None:
            return self.fn()
        cb = self._callbacks.get(_label_key(labels))
        if cb is not None:
            return cb()
        return self._values.get(_label_key(labels), 0.0)

    def collect(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        if self.fn is not None:
            out.append(f"{self.name} {self.fn()}")
            return out
        with self._lock:
            callbacks = list(self._callbacks.items())
            values = [
                (key, v) for key, v in sorted(self._values.items())
                if key not in self._callbacks
            ]
        for key, cb in sorted(callbacks):
            out.append(f"{self.name}{_fmt_labels(key)} {cb()}")
        for key, v in values:
            out.append(f"{self.name}{_fmt_labels(key)} {v}")
        return out


class Histogram:
    def __init__(
        self,
        name: str,
        help: str,
        label_names: Sequence[str] = (),
        buckets: Sequence[float] = _DEF_BUCKETS,
    ):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}
        self._totals: Dict[Tuple, int] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, **labels: str) -> None:
        # counts are stored per-bucket (first bucket the value falls in);
        # the Prometheus cumulative form is materialized in collect() --
        # one bisect instead of a Python loop over every bucket
        key = () if not labels else _label_key(labels)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * (len(self.buckets) + 1))
            counts[bisect_left(self.buckets, value)] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def observe_many(self, values: Sequence[float], **labels: str) -> None:
        """Bulk observe under one lock (the batch-commit hot path)."""
        if not values:
            return
        key = () if not labels else _label_key(labels)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * (len(self.buckets) + 1))
            total = 0.0
            for v in values:
                counts[bisect_left(self.buckets, v)] += 1
                total += v
            self._sums[key] = self._sums.get(key, 0.0) + total
            self._totals[key] = self._totals.get(key, 0) + len(values)

    def count(self, **labels: str) -> int:
        return self._totals.get(_label_key(labels), 0)

    def sum(self, **labels: str) -> float:
        return self._sums.get(_label_key(labels), 0.0)

    def collect(self) -> List[str]:
        out = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} histogram",
        ]
        with self._lock:
            for key in sorted(self._totals):
                cumulative = 0
                for i, b in enumerate(self.buckets):
                    cumulative += self._counts[key][i]
                    # the le label is hoisted into a variable: a backslash
                    # inside an f-string expression is 3.12-only syntax,
                    # and this module must import on 3.10
                    le_label = 'le="%s"' % b
                    out.append(
                        f"{self.name}_bucket"
                        f"{_fmt_labels(key, le_label)} "
                        f"{cumulative}"
                    )
                le_inf = 'le="+Inf"'
                out.append(
                    f"{self.name}_bucket{_fmt_labels(key, le_inf)} "
                    f"{self._totals[key]}"
                )
                out.append(f"{self.name}_sum{_fmt_labels(key)} {self._sums[key]}")
                out.append(f"{self.name}_count{_fmt_labels(key)} {self._totals[key]}")
        return out


class Registry:
    def __init__(self) -> None:
        self._metrics: List = []
        self._lock = threading.Lock()

    def register(self, metric):
        with self._lock:
            self._metrics.append(metric)
        return metric

    def expose(self) -> str:
        """Prometheus text exposition format."""
        lines: List[str] = []
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            lines.extend(m.collect())
        return "\n".join(lines) + "\n"


# -- the scheduler metric set (metrics.go names, verbatim) -------------------

registry = Registry()

schedule_attempts = registry.register(Counter(
    "scheduler_schedule_attempts_total",
    "Number of attempts to schedule pods, by result.",
    ("result",),
))
e2e_scheduling_duration = registry.register(Histogram(
    "scheduler_e2e_scheduling_duration_seconds",
    "E2e scheduling latency (scheduling algorithm + binding).",
))
scheduling_algorithm_duration = registry.register(Histogram(
    "scheduler_scheduling_algorithm_duration_seconds",
    "Scheduling algorithm latency.",
))
binding_duration = registry.register(Histogram(
    "scheduler_binding_duration_seconds",
    "Binding latency.",
))
preemption_victims = registry.register(Histogram(
    "scheduler_pod_preemption_victims",
    "Number of selected preemption victims.",
    buckets=(1, 2, 4, 8, 16, 32, 64),
))
preemption_attempts = registry.register(Counter(
    "scheduler_total_preemption_attempts",
    "Total preemption attempts in the cluster.",
))
pending_pods = registry.register(Gauge(
    "scheduler_pending_pods",
    "Number of pending pods by queue.",
    ("queue",),
))
pod_scheduling_duration = registry.register(Histogram(
    "scheduler_pod_scheduling_duration_seconds",
    "E2e latency for a pod being scheduled, from first attempt.",
))
pod_scheduling_attempts = registry.register(Histogram(
    "scheduler_pod_scheduling_attempts",
    "Number of attempts to successfully schedule a pod.",
    buckets=(1, 2, 4, 8, 16),
))
framework_extension_point_duration = registry.register(Histogram(
    "scheduler_framework_extension_point_duration_seconds",
    "Latency for running all plugins of a specific extension point.",
    ("extension_point", "status"),
))
plugin_execution_duration = registry.register(Histogram(
    "scheduler_plugin_execution_duration_seconds",
    "Duration for running a plugin at a specific extension point.",
    ("plugin", "extension_point", "status"),
))
queue_incoming_pods = registry.register(Counter(
    "scheduler_queue_incoming_pods_total",
    "Number of pods added to scheduling queues by event and queue type.",
    ("queue", "event"),
))
permit_wait_duration = registry.register(Histogram(
    "scheduler_permit_wait_duration_seconds",
    "Duration of waiting on permit.",
))
cache_size = registry.register(Gauge(
    "scheduler_scheduler_cache_size",
    "Number of nodes, pods, and assumed pods in the scheduler cache.",
    ("type",),
))
# TPU-path additions (new names, not replacements)
batch_solve_duration = registry.register(Histogram(
    "scheduler_tpu_batch_solve_duration_seconds",
    "Device solve latency per batch (pack + solve + readback).",
))
batch_size = registry.register(Histogram(
    "scheduler_tpu_batch_size",
    "Pods per device-solved batch.",
    buckets=(1, 8, 32, 64, 128, 256, 512, 1024),
))
# robustness subsystem (kubernetes_tpu/robustness/): fault injection,
# solver degradation ladder, circuit breakers -- degradation must be
# observable, not silent
faults_injected = registry.register(Counter(
    "scheduler_faults_injected_total",
    "Faults fired by the injection harness, by injection point.",
    ("point",),
))
breaker_transitions = registry.register(Counter(
    "scheduler_circuit_breaker_transitions_total",
    "Circuit breaker state transitions, by solver tier and edge.",
    ("tier", "from_state", "to_state"),
))
solver_fallbacks = registry.register(Counter(
    "scheduler_solver_fallback_total",
    "Batches stepped down the solver degradation ladder, by the tier "
    "that handled them and the reason the higher tier was skipped.",
    ("tier", "reason"),
))
solve_retries = registry.register(Counter(
    "scheduler_solve_retries_total",
    "Device-solve retries before stepping down the ladder, by tier.",
    ("tier",),
))
bind_retries = registry.register(Counter(
    "scheduler_bind_retries_total",
    "Bind/commit attempts retried after a transient API failure.",
))
watch_relists = registry.register(Counter(
    "scheduler_watch_relist_total",
    "Informer relists forced by a broken watch stream, by kind.",
    ("kind",),
))
# control-plane resilience (PR 2): crash recovery, fenced HA failover,
# cache<->apiserver reconciliation -- failover and restart must be as
# observable as a solver fault
fencing_aborts = registry.register(Counter(
    "scheduler_fencing_aborts_total",
    "Commits aborted because the lease was no longer held at commit "
    "time (the deposed-leader double-bind guard).",
))
lease_renew_failures = registry.register(Counter(
    "scheduler_lease_renew_failures_total",
    "Failed lease acquire/renew rounds (API error or injected).",
))
assumed_pods_expired = registry.register(Counter(
    "scheduler_assumed_pods_expired_total",
    "Assumed pods expired by the TTL sweeper (binding finished but the "
    "watch confirmation never arrived).",
))
# cluster-lifecycle wave (PR 6): drains, reclamation storms, and churn
# must be as observable as any other rehearsed failure path
evictions_blocked_by_pdb = registry.register(Counter(
    "scheduler_evictions_blocked_by_pdb_total",
    "Voluntary disruptions (drain or taint eviction) denied by the "
    "shared PodDisruptionBudget gate (DisruptionController."
    "can_disrupt).",
))
# batched preemption waves (PR 11): device-chosen victims, budget-gated
# evictions, nomination lifecycle -- every wave outcome is counted by
# what ACTUALLY happened (victims book only after the eviction
# transaction lands; a wave aborted by a breaker, a fence, or a denied
# budget books nothing)
preemption_waves = registry.register(Counter(
    "scheduler_preemption_waves_total",
    "Batched device preemption waves run (one per flushed failed-pod "
    "group per profile).",
))
victims_selected = registry.register(Counter(
    "scheduler_preemption_victims_selected_total",
    "Victims actually evicted by preemption, by the solver tier that "
    "chose them (pallas / xla / host). Booked only after the eviction "
    "transaction succeeds -- an aborted wave un-books nothing because "
    "nothing was booked.",
    ("tier",),
))
nominations_set = registry.register(Counter(
    "scheduler_preemption_nominations_set_total",
    "nominatedNodeName reservations installed in the scheduling queue "
    "(update_nominated_pod_for_node with a concrete node).",
))
nominations_cleared = registry.register(Counter(
    "scheduler_preemption_nominations_cleared_total",
    "Nominations removed from the queue map: the nominee bound, was "
    "superseded, failed terminally, or its nominated node was deleted.",
))
preemption_budget_denials = registry.register(Counter(
    "scheduler_preemption_budget_denials_total",
    "Preemptors whose victim set was denied by the shared "
    "DisruptionController.can_disrupt PDB gate (grants taken for the "
    "attempt are refunded; the preemptor requeues without a "
    "nomination).",
))
node_removed_requeues = registry.register(Counter(
    "scheduler_node_removed_requeues_total",
    "In-flight assumed pods whose node was deleted mid-bind, expired "
    "immediately and routed by apiserver truth instead of waiting out "
    "the assume TTL.",
))
cache_drift = registry.register(Counter(
    "scheduler_cache_drift_total",
    "Cache<->apiserver divergences detected and healed by the drift "
    "checker, by object kind and healing action.",
    ("kind", "action"),
))
pods_adopted_on_restart = registry.register(Counter(
    "scheduler_pods_adopted_on_restart_total",
    "Pods found already bound by a previous incarnation and adopted "
    "into the cache at startup.",
))
pods_requeued_on_restart = registry.register(Counter(
    "scheduler_pods_requeued_on_restart_total",
    "Pending pods (including a previous incarnation's in-flight "
    "assume-but-never-bound pods) requeued at startup.",
))
watch_gone = registry.register(Counter(
    "scheduler_watch_gone_total",
    "Watch opens rejected with the 410 Gone analogue (replay window "
    "truncated past since_rv), by kind.",
    ("kind",),
))
ingest_native_fallbacks = registry.register(Counter(
    "scheduler_ingest_native_fallbacks_total",
    "Ingest-plane calls that ran the pure-Python twin while the native "
    "path was WANTED (KTPU_NATIVE_INGEST on) but unavailable (build/"
    "import failure), by site. KTPU_NATIVE_INGEST=0 runs the twins as "
    "the configured path and books nothing here.",
    ("site",),
))
commit_join_timeouts = registry.register(Counter(
    "scheduler_commit_thread_join_timeouts_total",
    "Committer threads that failed to join at shutdown.",
))
degraded_health = registry.register(Gauge(
    "scheduler_degraded_health",
    "1 when a component is operating degraded, by reason.",
    ("reason",),
))
# open-loop streaming subsystem (kubernetes_tpu/streaming/): the
# SLO-adaptive batch controller, priority bands, and arrival-engine
# backpressure must be observable -- a controller that thrashes or an
# engine that stalls is a capacity signal, not an implementation detail
autobatch_decisions = registry.register(Counter(
    "scheduler_autobatch_decisions_total",
    "SLO-adaptive batch controller decisions that changed the window "
    "or dispatch cap, by direction (grow = throughput mode, shrink = "
    "latency mode).",
    ("direction",),
))
autobatch_window = registry.register(Gauge(
    "scheduler_autobatch_window_seconds",
    "Current adaptive batch window.",
))
autobatch_batch_cap = registry.register(Gauge(
    "scheduler_autobatch_batch_cap",
    "Current adaptive dispatch cap (pods per pop_batch drain; also "
    "floors the padded solve shape).",
))
autobatch_latched = registry.register(Gauge(
    "scheduler_autobatch_overload_latched",
    "1 while the controller's overload latch holds throughput mode "
    "(EWMA pressure crossed the grow threshold repeatedly; shrinks "
    "blocked until it calms).",
))
queue_band_wait = registry.register(Histogram(
    "scheduler_queue_band_wait_seconds",
    "ActiveQ wait (enqueue to drain) by priority band; only recorded "
    "when band_threshold is set.",
    ("band",),
))
backpressure_stalls = registry.register(Counter(
    "scheduler_arrival_backpressure_stalls_total",
    "Times the open-loop arrival engine stalled because the activeQ "
    "depth hit its bound (offered rate exceeded capacity).",
))
backpressure_stall_seconds = registry.register(Counter(
    "scheduler_arrival_backpressure_stall_seconds_total",
    "Cumulative wall clock the arrival engine spent stalled on the "
    "activeQ depth gate.",
))
# multi-active partitioned scheduling (scheduler/partition.py): N live
# stacks over one apiserver -- conflicts, spills, and takeovers are the
# rehearsed coordination paths and every one must be accounted (the
# conflict ledger: absorbed == requeued + satisfied, no silent loss)
bind_conflicts_absorbed = registry.register(Counter(
    "scheduler_bind_conflicts_absorbed_total",
    "Typed bind conflicts (already-bound / uid-mismatch / foreign-"
    "partition / partition-fence) absorbed by the committer through "
    "the requeue path instead of surfacing as scheduler errors, by "
    "conflict kind.",
    ("kind",),
))
pods_spilled = registry.register(Counter(
    "scheduler_pods_spilled_total",
    "Pods re-stamped to a sibling partition and forwarded through the "
    "apiserver because their feasible nodes live in a foreign "
    "partition.",
))
partition_takeovers = registry.register(Counter(
    "scheduler_partition_takeovers_total",
    "Foreign partitions seized after their holder's lease lapsed "
    "(stack crash, injected renew failures).",
))
partition_takeover_ms = registry.register(Histogram(
    "scheduler_partition_takeover_ms",
    "Lapsed-partition takeover latency: expiry detection to adoption "
    "complete (nodes in cache, orphaned pods requeued), milliseconds.",
    buckets=(5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000),
))
partitions_held = registry.register(Gauge(
    "scheduler_partitions_held",
    "Partitions currently held by this stack's coordinator.",
))
# tracing plane (ISSUE 13): the device-state counters that previously
# lived only as bench/solver labels become real series -- a live
# cluster sees what the bench sees -- plus the jit-cache watchdog and
# the streaming pod-to-bind quantile gauges. Booking follows the PR-5
# rule: link-traffic counters record what actually rode the link, so
# state_uploads/delta_rows book only after a device solve LANDED (the
# internal attributes un-book on ladder exhaustion / host-tier routing,
# and a Prometheus counter cannot).
state_uploads = registry.register(Counter(
    "scheduler_tpu_state_uploads_total",
    "Full [N, R] node-state uploads that reached the device (cold "
    "dispatches, layout changes, escalated churn, counted divergence "
    "resyncs).",
))
delta_rows_uploaded = registry.register(Counter(
    "scheduler_tpu_delta_rows_uploaded_total",
    "Changed node rows shipped as (indices, rows) scatters onto the "
    "device-resident carry instead of full [N, R] uploads.",
))
carry_divergences = registry.register(Counter(
    "scheduler_tpu_carry_divergences_total",
    "Generation-handshake mismatches: host node state not explained by "
    "our own mirrored placements (node churn, bind failures) -- "
    "resolved by a row scatter-fix or a counted full upload, never "
    "silently.",
))
tensor_full_repacks = registry.register(Counter(
    "scheduler_tpu_tensor_full_repacks_total",
    "NodeTensorCache full repacks (schema growth or slot-headroom "
    "exhaustion; steady membership churn scatters in place instead).",
))
tensor_rows_added = registry.register(Counter(
    "scheduler_tpu_tensor_rows_added_total",
    "Node rows claimed in place by incremental node adds (free or "
    "headroom slots; no layout move).",
))
tensor_rows_retired = registry.register(Counter(
    "scheduler_tpu_tensor_rows_retired_total",
    "Node rows freed in place by incremental node removals.",
))
spill_hint_hits = registry.register(Counter(
    "scheduler_spill_hint_hits_total",
    "Cross-partition spills routed straight to the owner partition by "
    "the feasibility hint (one hop) instead of walking the ring.",
))
jit_compiles = registry.register(Counter(
    "scheduler_tpu_jit_compiles_total",
    "Jitted-solver cache growth observed by the runtime jit-cache "
    "watchdog, by solver signature family. Growth after warmup sealed "
    "the cache is a MID-RUN recompile: it also fires a flight-recorder "
    "mark, because an unplanned multi-second compile inside a measured "
    "window is exactly what the warmup contract exists to prevent.",
    ("signature",),
))
# blast-radius containment (ISSUE 14): poison-pod bisection, the
# quarantine ledger, the carry integrity audit, and device-loss rebuild
# -- per-pod containment must be as observable as the tier fallback it
# replaces (a quarantined pod is VISIBLE, never silently dropped)
bisections = registry.register(Counter(
    "scheduler_tpu_bisections_total",
    "Ladder-exhausted batches taken down the poison-bisection path "
    "instead of failing whole to the sequential floor.",
))
bisect_subsolves = registry.register(Counter(
    "scheduler_tpu_bisect_subsolves_total",
    "Sub-batch solves dispatched by the bisection search (O(log B) per "
    "isolated pod; each reuses an already-warm pad rung).",
))
bisect_aborts = registry.register(Counter(
    "scheduler_tpu_bisect_aborts_total",
    "Bisection runs aborted to the sequential path because EVERY "
    "sub-solve failed (systemic device failure, not a poison "
    "signature).",
))
exhausted_crashloops = registry.register(Counter(
    "scheduler_ladder_exhausted_crashloops_total",
    "Identical batches that exhausted the solver ladder twice in a "
    "row: the retry is a crash loop, so containment (bisection / "
    "quarantine) takes over instead of a third full-batch retry.",
))
quarantine_pods = registry.register(Counter(
    "scheduler_quarantine_pods_total",
    "Pod isolation events booked by the quarantine ledger, by "
    "disposition (held = escalating out-of-queue backoff; parked = "
    "retry budget exhausted, PodQuarantined condition written) and "
    "isolation reason.",
    ("disposition", "reason"),
))
quarantine_parked = registry.register(Gauge(
    "scheduler_quarantine_parked",
    "Pods currently parked in the quarantine queue (terminal until an "
    "operator or a real spec update intervenes).",
))
quarantine_releases = registry.register(Counter(
    "scheduler_quarantine_releases_total",
    "Held pods released back to the activeQ after their quarantine "
    "hold expired (bounded retries before parking).",
))
quota_admissions = registry.register(Counter(
    "scheduler_quota_admissions_total",
    "ResourceQuota decisions at the scheduling gate: granted charges "
    "the namespace ledger (guaranteed_update check-and-increment); "
    "denied parks the pod typed-QuotaExceeded until a quota or usage "
    "event frees headroom.",
    ("result",),
))
quota_refunds = registry.register(Counter(
    "scheduler_quota_refunds_total",
    "Quota charges given back (exactly once per pod incarnation), by "
    "reason: requeue (scheduling/bind failure), spill (re-homed to a "
    "sibling partition), quarantine, delete.",
    ("reason",),
))
quota_parked = registry.register(Gauge(
    "scheduler_quota_parked",
    "Pods currently parked typed-QuotaExceeded (released by quota/"
    "usage events only, never polled).",
))
quota_releases = registry.register(Counter(
    "scheduler_quota_releases_total",
    "Quota-parked pods released back to the activeQ after a quota "
    "raise or a usage drop opened headroom for them.",
))
tenant_dominant_share = registry.register(Gauge(
    "scheduler_tenant_dominant_share",
    "DRF dominant share (max over cpu/memory of tenant-used / "
    "cluster-capacity) across tenants with usage, by stat: max = the "
    "most-served tenant; spread = max - min (the fairness gap the "
    "solve-order bias closes).",
    ("stat",),
))
carry_audit_sweeps = registry.register(Counter(
    "scheduler_tpu_carry_audit_sweeps_total",
    "Carry integrity audits run (cheap on-device checksum of the "
    "resident req/nzr/alloc/valid state against the host shadow), by "
    "disposition (clean / mismatch / busy / idle / raced).",
    ("disposition",),
))
carry_audit_mismatches = registry.register(Counter(
    "scheduler_tpu_carry_audit_mismatches_total",
    "Device-resident carry arrays whose audit checksum diverged from "
    "the host shadow (silent corruption caught before it mis-places "
    "pods), by array.",
    ("array",),
))
carry_audit_heals = registry.register(Counter(
    "scheduler_tpu_carry_audit_heals_total",
    "Corrupted device-resident state self-healed through the counted "
    "re-upload path after an audit mismatch.",
))
device_lost_events = registry.register(Counter(
    "scheduler_tpu_device_lost_total",
    "Device-loss events: all resident state dropped, in-flight batches "
    "recovered through the requeue machinery, state rebuilt from the "
    "host cache via the cold-upload path.",
))
device_rebuild_ms = registry.register(Histogram(
    "scheduler_tpu_device_rebuild_ms",
    "Device-loss rebuild latency: loss detection to the first jitted "
    "solve landing on the re-uploaded state, milliseconds.",
    buckets=(5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000),
))
pod_to_bind_quantile = registry.register(Gauge(
    "scheduler_pod_to_bind_quantile_seconds",
    "Live streaming estimate of the pod-to-bind latency quantile "
    "(P-squared sketch over every bound pod's first-attempt-to-bind "
    "wall clock), by quantile.",
    ("q",),
))

# hollow-node plane (ISSUE 17): the bind loop is closed -- a bind is
# only done when the node agent acks it into pod status -- so the ack
# path, the heartbeat plane, and the zombie-recovery arc each get their
# own families (README "Closing the bind loop" reads these)
hollow_acks = registry.register(Counter(
    "scheduler_hollow_acks_total",
    "Bindings acked into pod status (phase=Running) by the hollow-node "
    "fleet -- the kubelet syncLoop edge that closes the bind loop.",
))
hollow_heartbeats = registry.register(Counter(
    "scheduler_hollow_heartbeats_total",
    "Lease renewals written by the hollow-node fleet.",
))
bind_acks_observed = registry.register(Counter(
    "scheduler_bind_acks_total",
    "Bind acks observed by the scheduler's bind-ack tracker (the "
    "pod-Running transition arriving over the watch), by how: acked = "
    "the node confirmed in time; acked-late = the ack raced the "
    "rebind sweep and won at the store.",
    ("how",),
))
bind_ack_latency = registry.register(Histogram(
    "scheduler_bind_ack_latency_seconds",
    "Bind-to-ack latency: bulk bind commit to the pod-Running ack "
    "arriving over the watch.",
))
bind_ack_timeouts = registry.register(Counter(
    "scheduler_bind_ack_timeouts_total",
    "Bound pods whose ack never arrived within the ack timeout (the "
    "zombie-kubelet signal; each feeds the rebind path exactly once "
    "per pod incarnation).",
))
rebinds = registry.register(Counter(
    "scheduler_rebinds_total",
    "Bound-but-never-acked pods unbound back to the queue by the "
    "rebind-after-timeout sweep (uid-fenced: at most one per pod "
    "incarnation).",
))
bind_ack_pending = registry.register(Gauge(
    "scheduler_bind_ack_pending",
    "Bound pods currently awaiting their node's ack.",
))
suspect_nodes_tainted = registry.register(Counter(
    "scheduler_bind_ack_suspect_nodes_tainted_total",
    "Nodes tainted unschedulable by the bind-ack tracker after "
    "repeated ack timeouts (cleared when the node acks again).",
))
node_heartbeat_lapses = registry.register(Counter(
    "scheduler_node_heartbeat_lapses_total",
    "Nodes marked unreachable by the nodelifecycle monitor after their "
    "lease lapsed past the grace period.",
))
taint_evictions = registry.register(Counter(
    "scheduler_taint_evictions_total",
    "Pods evicted off unreachable nodes by the nodelifecycle monitor "
    "(every one granted through the shared can_disrupt PDB gate).",
))

# pipelined speculative dispatch (ISSUE 18): batch N+1 solves against
# the committer's shadow expectation while batch N is still committing;
# a commit-divergence rewinds only the divergent batch. The carry
# compression families book the int16 resident-carry A/B
# (KTPU_CARRY_COMPRESS=0 pins the int32 behavior)
speculative_launches = registry.register(Counter(
    "scheduler_speculative_launches_total",
    "Solves dispatched speculatively against the shadow-expected carry "
    "while at least one earlier batch was still in flight.",
))
speculative_rewinds = registry.register(Counter(
    "scheduler_speculative_rewinds_total",
    "Speculative-chain rewinds, by reason: row_patch = the expected "
    "deltas diverged (bind conflict, quota refund, conflict-requeue) "
    "and the carry was repaired in place with a row scatter; "
    "mirror_wait = the dispatcher paused for in-flight mirrors before "
    "renegotiating; drain = the chain fell back to a full pipeline "
    "drain + redispatch.",
    ("reason",),
))
carry_compressed = registry.register(Gauge(
    "scheduler_tpu_carry_compressed",
    "1 while the device-resident req/nzr carry is held int16 (the "
    "range-gated lossless compression engaged), else 0.",
))
carry_compress_bytes_saved = registry.register(Counter(
    "scheduler_tpu_carry_compress_bytes_saved_total",
    "Host-to-device link bytes saved by shipping req/nzr state and "
    "row deltas packed int16 instead of int32.",
))
carry_compress_disengages = registry.register(Counter(
    "scheduler_tpu_carry_compress_disengages_total",
    "Compressed-carry disengagements, by reason: range = a column sum "
    "approached the int16 ceiling; mode = the dispatch needed an "
    "uncompressed variant (constrained ladder, mesh, host tier).",
    ("reason",),
))

from kubernetes_tpu.utils.quantiles import QuantileSet as _QuantileSet

#: the live pod-to-bind sketch the gauges read at scrape time; the
#: AutoBatchController can consume the same estimate
pod_to_bind_sketch = _QuantileSet((0.5, 0.99))
pod_to_bind_quantile.register_callback(
    lambda: pod_to_bind_sketch.value(0.5), q="0.5"
)
pod_to_bind_quantile.register_callback(
    lambda: pod_to_bind_sketch.value(0.99), q="0.99"
)


def observe_pod_to_bind(seconds) -> None:
    """Feed the live quantile sketch (accepts a scalar or a sequence);
    called from both bind paths next to pod_scheduling_duration."""
    if isinstance(seconds, (int, float)):
        pod_to_bind_sketch.observe(seconds)
    else:
        pod_to_bind_sketch.observe_many(seconds)


class SinceTimer:
    """Tiny helper: observe elapsed seconds into a histogram."""

    def __init__(self, hist: Histogram, **labels: str) -> None:
        self.hist = hist
        self.labels = labels
        self.start = time.perf_counter()

    def observe(self, **extra: str) -> float:
        elapsed = time.perf_counter() - self.start
        self.hist.observe(elapsed, **{**self.labels, **extra})
        return elapsed
