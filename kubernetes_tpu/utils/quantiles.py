"""Streaming quantile estimation: the P-squared (P²) algorithm.

Jain & Chlamtac, "The P² algorithm for dynamic calculation of quantiles
and histograms without storing observations" (CACM 1985): five markers
per tracked quantile, O(1) per observation, no sample buffer. This is
the live pod-to-bind p50/p99 the metrics endpoint exposes as gauges --
the same estimate the AutoBatchController can consume, without the
bench's sort-everything post-processing.

Accuracy is a function of the stream, not the implementation: for the
unimodal latency distributions the scheduler produces, the estimate
lands within a few percent of the exact percentile (unit-pinned against
numpy in tests/test_flightrecorder.py).
"""

from __future__ import annotations

import threading
from bisect import insort
from typing import Dict, Optional, Sequence


class P2Quantile:
    """One P² estimator for a single quantile ``q`` in (0, 1).

    Not thread-safe on its own; ``QuantileSet`` adds the lock the
    concurrent bind paths need.
    """

    __slots__ = ("q", "_n", "_init", "_heights", "_pos", "_desired", "_incr")

    def __init__(self, q: float) -> None:
        if not 0.0 < q < 1.0:
            raise ValueError(f"quantile must be in (0, 1), got {q}")
        self.q = q
        self._n = 0
        self._init: list = []  # first five observations, kept sorted
        self._heights: list = []  # marker heights q_i
        self._pos: list = []  # marker positions n_i (1-based)
        self._desired: list = []  # desired positions n'_i
        self._incr = (0.0, q / 2.0, q, (1.0 + q) / 2.0, 1.0)

    def observe(self, x: float) -> None:
        self._n += 1
        if self._n <= 5:
            insort(self._init, x)
            if self._n == 5:
                self._heights = list(self._init)
                self._pos = [1.0, 2.0, 3.0, 4.0, 5.0]
                q = self.q
                self._desired = [
                    1.0, 1.0 + 2.0 * q, 1.0 + 4.0 * q, 3.0 + 2.0 * q, 5.0,
                ]
            return
        h = self._heights
        pos = self._pos
        # locate the cell; extreme observations move the end markers
        if x < h[0]:
            h[0] = x
            k = 0
        elif x >= h[4]:
            h[4] = x
            k = 3
        else:
            k = 0
            while k < 3 and not (h[k] <= x < h[k + 1]):
                k += 1
        for i in range(k + 1, 5):
            pos[i] += 1.0
        for i in range(5):
            self._desired[i] += self._incr[i]
        # adjust the three interior markers toward their desired spots
        for i in (1, 2, 3):
            d = self._desired[i] - pos[i]
            if (d >= 1.0 and pos[i + 1] - pos[i] > 1.0) or (
                d <= -1.0 and pos[i - 1] - pos[i] < -1.0
            ):
                step = 1.0 if d >= 1.0 else -1.0
                cand = self._parabolic(i, step)
                if h[i - 1] < cand < h[i + 1]:
                    h[i] = cand
                else:
                    h[i] = self._linear(i, step)
                pos[i] += step

    def _parabolic(self, i: int, d: float) -> float:
        h, n = self._heights, self._pos
        return h[i] + d / (n[i + 1] - n[i - 1]) * (
            (n[i] - n[i - 1] + d) * (h[i + 1] - h[i]) / (n[i + 1] - n[i])
            + (n[i + 1] - n[i] - d) * (h[i] - h[i - 1]) / (n[i] - n[i - 1])
        )

    def _linear(self, i: int, d: float) -> float:
        h, n = self._heights, self._pos
        j = i + int(d)
        return h[i] + d * (h[j] - h[i]) / (n[j] - n[i])

    @property
    def count(self) -> int:
        return self._n

    def value(self) -> float:
        """Current estimate (0.0 before the first observation; the
        exact sample quantile while fewer than five have arrived)."""
        if self._n == 0:
            return 0.0
        if self._n < 5:
            idx = min(len(self._init) - 1, int(self.q * len(self._init)))
            return self._init[idx]
        return self._heights[2]


class QuantileSet:
    """A locked bundle of P² estimators over one stream (e.g. p50 +
    p99 pod-to-bind), observable from concurrent bind threads."""

    def __init__(self, quantiles: Sequence[float] = (0.5, 0.99)) -> None:
        self._lock = threading.Lock()
        self._est: Dict[float, P2Quantile] = {
            q: P2Quantile(q) for q in quantiles
        }

    def observe(self, x: float) -> None:
        with self._lock:
            for est in self._est.values():
                est.observe(x)

    def observe_many(self, values: Sequence[float]) -> None:
        if not values:
            return
        with self._lock:
            for est in self._est.values():
                for x in values:
                    est.observe(x)

    def value(self, q: float) -> float:
        with self._lock:
            est = self._est.get(q)
            return est.value() if est is not None else 0.0

    @property
    def count(self) -> int:
        with self._lock:
            for est in self._est.values():
                return est.count
            return 0

    def reset(self) -> None:
        """Drop accumulated state (bench trials that want a fresh
        window; production never calls this)."""
        with self._lock:
            self._est = {q: P2Quantile(q) for q in self._est}

    def quantiles(self) -> Sequence[float]:
        return tuple(self._est)


def exact_quantile(values: Sequence[float], q: float) -> Optional[float]:
    """Reference implementation for tests/benches: the same index rule
    bench.py uses for its p99 (sorted, floor(n*q) clamped)."""
    if not values:
        return None
    vs = sorted(values)
    return vs[min(len(vs) - 1, int(len(vs) * q))]
