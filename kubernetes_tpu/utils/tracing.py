"""utiltrace-style step tracing.

Reference: vendored k8s.io/utils/trace/trace.go:55 -- an in-process span
log; Schedule wraps each cycle and logs any trace exceeding a threshold
with per-step timings (generic_scheduler.go:151-152). The apiserver wraps
REST handlers the same way (endpoints/handlers/get.go:52).

Device-side profiling is jax.profiler's job; this covers the host path.
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional, Tuple

logger = logging.getLogger("trace")


class Trace:
    def __init__(self, name: str, **fields) -> None:
        self.name = name
        self.fields = fields
        self.start = time.perf_counter()
        self.steps: List[Tuple[float, str]] = []

    def step(self, msg: str) -> None:
        self.steps.append((time.perf_counter(), msg))

    def total_seconds(self) -> float:
        return time.perf_counter() - self.start

    def log_if_long(self, threshold_seconds: float = 0.1) -> None:
        """trace.go LogIfLong: emit the step table when over threshold."""
        total = self.total_seconds()
        if total < threshold_seconds:
            return
        fields = ",".join(f"{k}={v}" for k, v in self.fields.items())
        lines = [f'Trace "{self.name}" ({fields}): total {total*1000:.1f}ms']
        prev = self.start
        for ts, msg in self.steps:
            lines.append(f"  step {((ts - prev) * 1000):.1f}ms: {msg}")
            prev = ts
        logger.info("\n".join(lines))
