"""API event recorder: Scheduled / FailedScheduling / Preempted Event
objects (VERDICT r2 missing #6).

Reference: the profile-scoped events recorder
(pkg/scheduler/profile/profile.go:39 Recorder, emitted at
scheduler.go:378 "FailedScheduling" and :544 "Scheduled") over
client-go's tools/events EventBroadcaster. Like the reference
broadcaster, emission is ASYNCHRONOUS (the scheduling hot path only
enqueues) and events aggregate: a repeat of the same
(object, reason, message) key bumps ``count`` on the stored Event
instead of writing a new object (events_cache aggregation).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Dict, Optional, Tuple

from kubernetes_tpu.api.types import Event, ObjectMeta, ObjectReference

logger = logging.getLogger(__name__)


class EventBroadcaster:
    """One per scheduler process; profiles get per-source recorders."""

    def __init__(self, server) -> None:
        self._server = server
        self._q: "deque" = deque()
        self._cond = threading.Condition()
        self._stop = False
        self._seq = 0
        # (involved uid, reason, message) -> stored event key
        self._aggregate: Dict[Tuple, Tuple[str, str]] = {}
        self._thread = threading.Thread(
            target=self._run, name="event-broadcaster", daemon=True
        )
        self._thread.start()

    def new_recorder(self, source: str) -> "EventRecorder":
        return EventRecorder(self, source)

    def _enqueue(self, item) -> None:
        with self._cond:
            self._q.append(item)
            self._cond.notify()

    def _enqueue_many(self, items) -> None:
        with self._cond:
            self._q.extend(items)
            self._cond.notify()

    #: coalescing delay before draining: eager per-commit drains
    #: interleave the broadcaster with the burst's lock-holding commit
    #: threads (GIL convoying); waiting collects a much larger frame and
    #: emits it in a handful of store transactions instead of hundreds
    COALESCE_SECONDS = 0.2

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._q and not self._stop:
                    self._cond.wait(0.5)
                if not self._q and self._stop:
                    return
            if not self._stop:
                time.sleep(self.COALESCE_SECONDS)
            with self._cond:
                items = list(self._q)
                self._q.clear()
            if not items:
                continue
            try:
                self._emit_batch(items)
            except Exception:
                logger.exception("emitting events")

    def _emit_batch(self, items) -> None:
        """One store transaction per drained frame: a 10k-pod burst emits
        10k Scheduled events, and per-event creates would contend the
        store lock with the bulk binds on the hot path (measured ~25%
        bench regression). New events ride ONE create_bulk; aggregation
        bumps ride per-object updates (rare). ObjectReference/Event
        construction happens HERE, off the scheduling threads, and event
        metadata skips uid generation (events are never referenced by
        uid)."""
        fresh = []
        now = time.time()
        for item in items:
            source, obj, event_type, reason, message = item
            meta = obj.metadata
            if message is None and reason == "Scheduled":
                # deferred formatting: the commit hot path enqueues the
                # bare (pod, host) and the message f-string renders HERE,
                # off the scheduling threads (host rides spec.node_name)
                message = (
                    f"Successfully assigned {meta.namespace}/{meta.name} "
                    f"to {obj.spec.node_name}"
                )
            key = (meta.uid, reason, message)
            stored = self._aggregate.get(key)
            if stored is not None:
                ns, name = stored
                try:
                    self._server.guaranteed_update(
                        "Event", ns, name,
                        lambda e: setattr(e, "count", e.count + 1),
                    )
                    continue
                except KeyError:
                    pass  # evicted from the store: write a fresh one
            self._seq += 1
            name = f"{meta.name}.{self._seq:x}"
            fresh.append(
                Event(
                    metadata=ObjectMeta(
                        name=name, namespace=meta.namespace, uid=""
                    ),
                    involved_object=ObjectReference(
                        kind=getattr(obj, "kind", ""),
                        namespace=meta.namespace,
                        name=meta.name,
                        uid=meta.uid,
                    ),
                    reason=reason,
                    message=message,
                    type=event_type,
                    source=source,
                    count=1,
                    first_timestamp=now,
                )
            )
            self._aggregate[key] = (meta.namespace, name)
        if fresh:
            self._server.create_bulk(fresh)
        if len(self._aggregate) > 10_000:
            self._aggregate.clear()  # bounded memory, like cache eviction

    def flush(self, timeout: float = 5.0) -> None:
        """Block until the queue drains (tests / shutdown)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._cond:
                if not self._q:
                    return
            time.sleep(0.01)

    def stop(self) -> None:
        self.flush()
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=2)


class EventRecorder:
    """profile.go:39: the per-profile recorder (source = schedulerName).
    eventf only enqueues (object reference + strings); everything else
    happens on the broadcaster thread."""

    def __init__(self, broadcaster: EventBroadcaster, source: str) -> None:
        self._broadcaster = broadcaster
        self.source = source

    def eventf(
        self, obj: Any, event_type: str, reason: str, message: str
    ) -> None:
        self._broadcaster._enqueue(
            (self.source, obj, event_type, reason, message)
        )

    def eventf_many(self, items) -> None:
        """Bulk enqueue under one lock: items = [(obj, type, reason,
        message)] (the batch commit's per-burst Scheduled events).
        ``message=None`` with reason "Scheduled" defers formatting to the
        broadcaster thread."""
        src = self.source
        self._broadcaster._enqueue_many(
            [(src, obj, t, r, m) for obj, t, r, m in items]
        )

    def scheduled_many(self, bound_pods) -> None:
        """Zero-format enqueue for the burst commit: one tuple per bound
        pod, message rendered on the broadcaster thread."""
        src = self.source
        self._broadcaster._enqueue_many(
            [(src, pod, "Normal", "Scheduled", None) for pod in bound_pods]
        )


class NullRecorder:
    """Recorder stand-in when no client/server is wired (unit tests)."""

    source = ""

    def eventf(self, obj, event_type, reason, message) -> None:
        return None
