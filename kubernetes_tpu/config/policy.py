"""Legacy v1 Policy translation: predicate/priority names -> plugins.

Reference: /root/reference/pkg/scheduler/factory.go:239
(createFromConfig) + framework/plugins/legacy_registry.go -- the
pre-ComponentConfig Policy file/ConfigMap format ({"kind": "Policy",
"predicates": [...], "priorities": [...]}) mapped onto the plugin
framework, so operators migrating from a Policy keep their algorithm.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import yaml

from kubernetes_tpu.config.types import (
    KubeSchedulerProfile,
    Plugin,
    PluginSet,
    Plugins,
)

# legacy_registry.go predicate name -> (filter plugin, also prefilter?)
PREDICATE_TO_PLUGIN: Dict[str, Tuple[str, bool]] = {
    "PodFitsResources": ("NodeResourcesFit", True),
    "PodFitsHostPorts": ("NodePorts", True),
    "HostName": ("NodeName", False),
    "MatchNodeSelector": ("NodeAffinity", False),
    "NoDiskConflict": ("VolumeRestrictions", False),
    "NoVolumeZoneConflict": ("VolumeZone", False),
    "PodToleratesNodeTaints": ("TaintToleration", False),
    "CheckNodeUnschedulable": ("NodeUnschedulable", False),
    "MaxEBSVolumeCount": ("EBSLimits", False),
    "MaxGCEPDVolumeCount": ("GCEPDLimits", False),
    "MaxAzureDiskVolumeCount": ("AzureDiskLimits", False),
    "MaxCSIVolumeCountPred": ("NodeVolumeLimitsCSI", False),
    "CheckVolumeBinding": ("VolumeBinding", False),
    "MatchInterPodAffinity": ("InterPodAffinity", True),
    "EvenPodsSpreadPred": ("PodTopologySpread", True),
    "TestServiceAffinity": ("ServiceAffinity", True),
    "CheckNodeLabelPresence": ("NodeLabel", False),
}

# legacy priority name -> score plugin (+ needs prescore?)
PRIORITY_TO_PLUGIN: Dict[str, Tuple[str, bool]] = {
    "LeastRequestedPriority": ("NodeResourcesLeastAllocated", False),
    "MostRequestedPriority": ("NodeResourcesMostAllocated", False),
    "BalancedResourceAllocation": ("NodeResourcesBalancedAllocation", False),
    "SelectorSpreadPriority": ("DefaultPodTopologySpread", True),
    "InterPodAffinityPriority": ("InterPodAffinity", True),
    "NodeAffinityPriority": ("NodeAffinity", False),
    "TaintTolerationPriority": ("TaintToleration", True),
    "ImageLocalityPriority": ("ImageLocality", False),
    "NodePreferAvoidPodsPriority": ("NodePreferAvoidPods", False),
    "RequestedToCapacityRatioPriority": ("RequestedToCapacityRatio", False),
    "EvenPodsSpreadPriority": ("PodTopologySpread", True),
    "ResourceLimitsPriority": ("NodeResourceLimits", True),
    "ServiceSpreadingPriority": ("DefaultPodTopologySpread", True),
}


def plugins_from_policy(raw: Dict[str, Any]) -> Plugins:
    """Translate one Policy dict into a Plugins wiring. Unknown names
    raise ValueError (the reference fails scheduler startup the same
    way)."""
    filter_names: List[str] = []
    pre_filter: List[str] = []
    pre_score: List[str] = []
    scores: List[Tuple[str, int]] = []

    def add_unique(lst: List[str], name: str) -> None:
        if name not in lst:
            lst.append(name)

    for pred in raw.get("predicates", []):
        name = pred["name"]
        mapped = PREDICATE_TO_PLUGIN.get(name)
        if mapped is None:
            raise ValueError(f"unknown Policy predicate {name!r}")
        plugin, wants_prefilter = mapped
        add_unique(filter_names, plugin)
        if wants_prefilter:
            add_unique(pre_filter, plugin)
    for prio in raw.get("priorities", []):
        name = prio["name"]
        mapped = PRIORITY_TO_PLUGIN.get(name)
        if mapped is None:
            raise ValueError(f"unknown Policy priority {name!r}")
        plugin, wants_prescore = mapped
        weight = int(prio.get("weight", 1))
        if all(plugin != p for p, _w in scores):
            scores.append((plugin, weight))
        if wants_prescore:
            add_unique(pre_score, plugin)

    return Plugins(
        queue_sort=PluginSet(enabled=[Plugin("PrioritySort")]),
        pre_filter=PluginSet(enabled=[Plugin(n) for n in pre_filter]),
        filter=PluginSet(enabled=[Plugin(n) for n in filter_names]),
        pre_score=PluginSet(enabled=[Plugin(n) for n in pre_score]),
        score=PluginSet(
            enabled=[Plugin(n, weight=w) for n, w in scores]
        ),
        bind=PluginSet(enabled=[Plugin("DefaultBinder")]),
    )


def profile_from_policy(
    raw: Dict[str, Any], scheduler_name: str = "default-scheduler"
) -> KubeSchedulerProfile:
    """One profile carrying the translated Policy wiring. The profile's
    plugins REPLACE the defaults wholesale (Policy semantics: the listed
    predicates/priorities are the whole algorithm, factory.go:239)."""
    plugins = plugins_from_policy(raw)
    # mark every extension point explicit: disable defaults with "*"
    for point in Plugins.EXTENSION_POINTS:
        ps: PluginSet = getattr(plugins, point)
        ps.disabled = [Plugin("*")]
    return KubeSchedulerProfile(
        scheduler_name=scheduler_name, plugins=plugins
    )


def load_policy(path: str) -> KubeSchedulerProfile:
    with open(path) as f:
        raw = yaml.safe_load(f) or {}
    if raw.get("kind") not in (None, "Policy"):
        raise ValueError(f"not a Policy document: kind={raw.get('kind')!r}")
    return profile_from_policy(raw)
