from kubernetes_tpu.config.types import (
    KubeSchedulerConfiguration,
    KubeSchedulerProfile,
    Plugin as PluginRef,
    PluginSet,
    Plugins,
)

__all__ = [
    "KubeSchedulerConfiguration",
    "KubeSchedulerProfile",
    "PluginRef",
    "PluginSet",
    "Plugins",
]
