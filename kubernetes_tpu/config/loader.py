"""KubeSchedulerConfiguration loading: YAML/JSON -> typed config.

Reference: the layered config system (SURVEY.md section 5): versioned
ComponentConfig decoded with defaulting (apis/config/v1alpha2), feature
gates (component-base/featuregate), per-plugin args. Field names accept
the reference's camelCase wire form.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

import yaml

from kubernetes_tpu.config.types import (
    BindAckConfiguration,
    ContainmentConfiguration,
    FaultInjectionConfiguration,
    FaultPointConfiguration,
    KubeSchedulerConfiguration,
    KubeSchedulerProfile,
    LeaderElectionConfiguration,
    PartitionConfiguration,
    Plugin,
    PluginSet,
    Plugins,
    ResilienceConfiguration,
    RobustnessConfiguration,
    StreamingConfiguration,
    TenancyConfiguration,
    TPUSolverConfiguration,
)
from kubernetes_tpu.scheduler.extender import ExtenderConfig

_POINT_KEYS = {
    "queueSort": "queue_sort",
    "preFilter": "pre_filter",
    "filter": "filter",
    "preScore": "pre_score",
    "score": "score",
    "reserve": "reserve",
    "permit": "permit",
    "preBind": "pre_bind",
    "bind": "bind",
    "postBind": "post_bind",
    "unreserve": "unreserve",
}


def _plugin_set(raw: Dict[str, Any]) -> PluginSet:
    def plugin(p: Dict[str, Any]) -> Plugin:
        return Plugin(name=p["name"], weight=int(p.get("weight", 1)))

    return PluginSet(
        enabled=[plugin(p) for p in raw.get("enabled", [])],
        disabled=[plugin(p) for p in raw.get("disabled", [])],
    )


def _plugins(raw: Optional[Dict[str, Any]]) -> Optional[Plugins]:
    if raw is None:
        return None
    out = Plugins()
    for wire_key, attr in _POINT_KEYS.items():
        if wire_key in raw:
            setattr(out, attr, _plugin_set(raw[wire_key]))
    return out


def _profile(raw: Dict[str, Any]) -> KubeSchedulerProfile:
    plugin_config = {
        pc["name"]: pc.get("args", {}) for pc in raw.get("pluginConfig", [])
    }
    return KubeSchedulerProfile(
        scheduler_name=raw.get("schedulerName", "default-scheduler"),
        plugins=_plugins(raw.get("plugins")),
        plugin_config=plugin_config,
    )


_DURATION_UNITS = {
    "ns": 1e-9, "us": 1e-6, "µs": 1e-6, "ms": 1e-3,
    "s": 1.0, "m": 60.0, "h": 3600.0,
}


def _duration_seconds(raw: Any) -> float:
    """Accept numeric seconds or Go-style duration strings ("30s",
    "1m30s", "500ms") -- the reference wire format expresses HTTPTimeout
    and the leader-election knobs as metav1.Duration."""
    if isinstance(raw, (int, float)):
        return float(raw)
    s = str(raw).strip()
    try:
        return float(s)
    except ValueError:
        pass
    total = 0.0
    m = re.fullmatch(r"(?:\d+(?:\.\d+)?(?:ns|us|µs|ms|s|m|h))+", s)
    if not m:
        raise ValueError(f"invalid duration {raw!r}")
    for num, unit in re.findall(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h)", s):
        total += float(num) * _DURATION_UNITS[unit]
    return total


def _extender(raw: Dict[str, Any]) -> ExtenderConfig:
    return ExtenderConfig(
        url_prefix=raw.get("urlPrefix", ""),
        filter_verb=raw.get("filterVerb", ""),
        prioritize_verb=raw.get("prioritizeVerb", ""),
        bind_verb=raw.get("bindVerb", ""),
        preempt_verb=raw.get("preemptVerb", ""),
        weight=int(raw.get("weight", 1)),
        node_cache_capable=bool(raw.get("nodeCacheCapable", False)),
        ignorable=bool(raw.get("ignorable", False)),
        managed_resources=[
            r["name"] for r in raw.get("managedResources", [])
        ],
        http_timeout_seconds=_duration_seconds(raw.get("httpTimeout", 5.0)),
    )


def streaming_from_dict(st_raw: Dict[str, Any]) -> StreamingConfiguration:
    """Parse a ``streaming:`` block (camelCase wire form). Shared by
    the top-level config loader and the perf-matrix runner's
    workload-scoped blocks, so both speak the same schema."""
    return StreamingConfiguration(
        enabled=bool(st_raw.get("enabled", False)),
        slo_p99_seconds=_duration_seconds(st_raw.get("sloP99", 1.0)),
        min_window_seconds=_duration_seconds(st_raw.get("minWindow", 0.0)),
        max_window_seconds=_duration_seconds(st_raw.get("maxWindow", 0.25)),
        latency_batch=int(st_raw.get("latencyBatch", 512)),
        auto_rungs=bool(st_raw.get("autoRungs", False)),
        controller_interval_seconds=_duration_seconds(
            st_raw.get("controllerInterval", 0.25)
        ),
        band_priority_threshold=(
            int(st_raw["bandPriorityThreshold"])
            if "bandPriorityThreshold" in st_raw
            else None
        ),
        band_priority_class=st_raw.get("bandPriorityClass", ""),
        max_queue_depth=int(st_raw.get("maxQueueDepth", 20000)),
        trace=st_raw.get("trace", "poisson"),
        rate_pods_per_sec=float(st_raw.get("rate", 1000.0)),
        duration_seconds=_duration_seconds(st_raw.get("duration", 30.0)),
        seed=int(st_raw.get("seed", 0)),
        burst_rate_pods_per_sec=float(st_raw.get("burstRate", 0.0)),
        base_dwell_seconds=_duration_seconds(st_raw.get("baseDwell", 8.0)),
        burst_dwell_seconds=_duration_seconds(
            st_raw.get("burstDwell", 2.0)
        ),
        period_seconds=_duration_seconds(st_raw.get("period", 60.0)),
        trough_fraction=float(st_raw.get("troughFraction", 0.2)),
        replay_path=st_raw.get("replayPath", ""),
    )


def load_config_from_dict(raw: Dict[str, Any]) -> KubeSchedulerConfiguration:
    le_raw = raw.get("leaderElection", {})
    cfg = KubeSchedulerConfiguration(
        profiles=[_profile(p) for p in raw.get("profiles", [])],
        percentage_of_nodes_to_score=int(
            raw.get("percentageOfNodesToScore", 0)
        ),
        pod_initial_backoff_seconds=float(
            raw.get("podInitialBackoffSeconds", 1.0)
        ),
        pod_max_backoff_seconds=float(raw.get("podMaxBackoffSeconds", 10.0)),
        leader_election=LeaderElectionConfiguration(
            leader_elect=bool(le_raw.get("leaderElect", False)),
            lease_duration_seconds=_duration_seconds(le_raw.get("leaseDuration", 15.0)),
            renew_deadline_seconds=_duration_seconds(le_raw.get("renewDeadline", 10.0)),
            retry_period_seconds=_duration_seconds(le_raw.get("retryPeriod", 2.0)),
            resource_name=le_raw.get("resourceName", "kube-scheduler"),
            resource_namespace=le_raw.get("resourceNamespace", "kube-system"),
            renew_jitter_fraction=float(le_raw.get("renewJitter", 0.1)),
            clock_skew_tolerance_seconds=_duration_seconds(
                le_raw.get("clockSkewTolerance", 0.0)
            ),
        ),
        health_bind_address=raw.get("healthzBindAddress", ""),
        metrics_bind_address=raw.get("metricsBindAddress", ""),
        feature_gates=dict(raw.get("featureGates", {})),
    )
    solver_raw = raw.get("tpuSolver", {})
    cfg.tpu_solver = TPUSolverConfiguration(
        enabled=bool(solver_raw.get("enabled", True)),
        max_batch=int(solver_raw.get("maxBatch", 256)),
        solver_mode=solver_raw.get("solverMode", "greedy"),
        batch_window_seconds=_duration_seconds(
            solver_raw.get("batchWindow", 0.01)
        ),
        mesh_devices=int(solver_raw.get("meshDevices", 0)),
    )
    cfg.extenders = [_extender(e) for e in raw.get("extenders", [])]
    rb_raw = raw.get("robustness", {})
    cfg.robustness = RobustnessConfiguration(
        enabled=bool(rb_raw.get("enabled", True)),
        solve_timeout_seconds=_duration_seconds(
            rb_raw.get("solveTimeout", 60.0)
        ),
        failure_threshold=int(rb_raw.get("failureThreshold", 3)),
        cooloff_seconds=_duration_seconds(rb_raw.get("cooloff", 5.0)),
        probe_batches=int(rb_raw.get("probeBatches", 1)),
        retry_max_attempts=int(rb_raw.get("retryMaxAttempts", 2)),
        retry_backoff_seconds=_duration_seconds(
            rb_raw.get("retryBackoff", 0.05)
        ),
        retry_max_backoff_seconds=_duration_seconds(
            rb_raw.get("retryMaxBackoff", 1.0)
        ),
    )
    ct_raw = raw.get("containment", {})
    cfg.containment = ContainmentConfiguration(
        enabled=bool(ct_raw.get("enabled", True)),
        max_strikes=int(ct_raw.get("maxStrikes", 3)),
        base_hold_seconds=_duration_seconds(
            ct_raw.get("baseHold", 0.25)
        ),
        max_hold_seconds=_duration_seconds(ct_raw.get("maxHold", 5.0)),
        bisect_abort_after=int(ct_raw.get("bisectAbortAfter", 4)),
    )
    rs_raw = raw.get("resilience", {})
    cfg.resilience = ResilienceConfiguration(
        sweeper_enabled=bool(rs_raw.get("sweeperEnabled", True)),
        sweep_interval_seconds=_duration_seconds(
            rs_raw.get("sweepInterval", 1.0)
        ),
        drift_check_interval_seconds=_duration_seconds(
            rs_raw.get("driftCheckInterval", 5.0)
        ),
        commit_fencing=bool(rs_raw.get("commitFencing", True)),
    )
    cfg.streaming = streaming_from_dict(raw.get("streaming", {}))
    pt_raw = raw.get("partition", {})
    cfg.partition = PartitionConfiguration(
        enabled=bool(pt_raw.get("enabled", False)),
        num_partitions=int(pt_raw.get("numPartitions", 2)),
        lease_duration_seconds=_duration_seconds(
            pt_raw.get("leaseDuration", 1.0)
        ),
        retry_period_seconds=_duration_seconds(
            pt_raw.get("retryPeriod", 0.1)
        ),
        clock_skew_tolerance_seconds=_duration_seconds(
            pt_raw.get("clockSkewTolerance", 0.0)
        ),
        zone_aligned=bool(pt_raw.get("zoneAligned", False)),
        resource_namespace=pt_raw.get("resourceNamespace", "kube-system"),
        resource_prefix=pt_raw.get("resourcePrefix", "ksp-partition"),
    )
    tn_raw = raw.get("tenancy", {})
    cfg.tenancy = TenancyConfiguration(
        enabled=bool(tn_raw.get("enabled", False)),
        quota_enforcement=bool(tn_raw.get("quotaEnforcement", True)),
        drf_bias=bool(tn_raw.get("drfBias", True)),
    )
    ba_raw = raw.get("bindAck", {})
    cfg.bind_ack = BindAckConfiguration(
        enabled=bool(ba_raw.get("enabled", False)),
        ack_timeout_seconds=_duration_seconds(
            ba_raw.get("ackTimeout", 5.0)
        ),
        sweep_interval_seconds=_duration_seconds(
            ba_raw.get("sweepInterval", 0.5)
        ),
        node_suspect_threshold=int(ba_raw.get("nodeSuspectThreshold", 1)),
        taint_suspect_nodes=bool(ba_raw.get("taintSuspectNodes", True)),
    )
    fi_raw = raw.get("faultInjection", {})
    cfg.fault_injection = FaultInjectionConfiguration(
        enabled=bool(fi_raw.get("enabled", False)),
        profile=fi_raw.get("profile", ""),
        seed=int(fi_raw.get("seed", 0)),
        points={
            name: FaultPointConfiguration(
                rate=float(p.get("rate", 0.0)),
                max_fires=(
                    int(p["maxFires"]) if "maxFires" in p else None
                ),
                hang_seconds=_duration_seconds(p.get("hangSeconds", 0.0)),
            )
            for name, p in fi_raw.get("points", {}).items()
        },
    )
    return cfg


def load_config(path: str, validate: bool = True) -> KubeSchedulerConfiguration:
    with open(path) as f:
        raw = yaml.safe_load(f) or {}
    cfg = load_config_from_dict(raw)
    if validate:
        from kubernetes_tpu.config.validation import validate_config

        errors = validate_config(cfg)
        if errors:
            raise ValueError(
                "invalid KubeSchedulerConfiguration: " + "; ".join(errors)
            )
    return cfg


class FeatureGate:
    """component-base/featuregate/feature_gate.go: thread-safe known-gate
    map with defaults + overrides."""

    def __init__(self, defaults: Optional[Dict[str, bool]] = None) -> None:
        self._known: Dict[str, bool] = dict(defaults or {})

    def add(self, name: str, default: bool) -> None:
        self._known.setdefault(name, default)

    def set_from_map(self, overrides: Dict[str, bool]) -> None:
        for name, value in overrides.items():
            if name not in self._known:
                raise ValueError(f"unknown feature gate {name!r}")
            self._known[name] = value

    def enabled(self, name: str) -> bool:
        if name not in self._known:
            raise ValueError(f"unknown feature gate {name!r}")
        return self._known[name]


# the gates the scheduler path consults (pkg/features/kube_features.go)
DEFAULT_FEATURE_GATES = {
    "EvenPodsSpread": True,
    "ResourceLimitsPriorityFunction": False,
    "NonPreemptingPriority": True,
    "BalanceAttachedNodeVolumes": False,
    "TPUBatchSolver": True,  # this build's fast path
}
