"""Scheduler component configuration.

Reference: /root/reference/pkg/scheduler/apis/config/types.go
(KubeSchedulerConfiguration :46, KubeSchedulerProfile :111, Plugins :178,
Plugin/PluginSet :230-247) and the v1alpha2 wire format in
staging/src/k8s.io/kube-scheduler/config/v1alpha2/types.go:94.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE = 0  # 0 => adaptive (types.go:250)
MIN_FEASIBLE_NODES_TO_FIND = 100  # generic_scheduler.go:57
MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND = 5  # generic_scheduler.go:62

DEFAULT_POD_INITIAL_BACKOFF_SECONDS = 1.0  # types.go:95
DEFAULT_POD_MAX_BACKOFF_SECONDS = 10.0  # types.go:101


@dataclass
class Plugin:
    """An enabled plugin reference with an optional weight (Score only)."""

    name: str
    weight: int = 1


@dataclass
class PluginSet:
    enabled: List[Plugin] = field(default_factory=list)
    disabled: List[Plugin] = field(default_factory=list)  # name "*" disables all


@dataclass
class Plugins:
    """Per-extension-point enable/disable lists (types.go:178)."""

    queue_sort: PluginSet = field(default_factory=PluginSet)
    pre_filter: PluginSet = field(default_factory=PluginSet)
    filter: PluginSet = field(default_factory=PluginSet)
    pre_score: PluginSet = field(default_factory=PluginSet)
    score: PluginSet = field(default_factory=PluginSet)
    reserve: PluginSet = field(default_factory=PluginSet)
    permit: PluginSet = field(default_factory=PluginSet)
    pre_bind: PluginSet = field(default_factory=PluginSet)
    bind: PluginSet = field(default_factory=PluginSet)
    post_bind: PluginSet = field(default_factory=PluginSet)
    unreserve: PluginSet = field(default_factory=PluginSet)

    EXTENSION_POINTS = (
        "queue_sort",
        "pre_filter",
        "filter",
        "pre_score",
        "score",
        "reserve",
        "permit",
        "pre_bind",
        "bind",
        "post_bind",
        "unreserve",
    )

    def apply(self, custom: Optional["Plugins"]) -> "Plugins":
        """Merge a profile's overrides onto defaults: for each extension
        point, custom enabled plugins are appended after defaults that were
        not disabled (reference apis/config/v1alpha2 mergePlugins)."""
        if custom is None:
            return self
        out = Plugins()
        for point in self.EXTENSION_POINTS:
            default_set: PluginSet = getattr(self, point)
            custom_set: PluginSet = getattr(custom, point)
            disabled = {p.name for p in custom_set.disabled}
            if "*" in disabled:
                enabled = []
            else:
                enabled = [p for p in default_set.enabled if p.name not in disabled]
            enabled = enabled + list(custom_set.enabled)
            setattr(out, point, PluginSet(enabled=enabled))
        return out


@dataclass
class KubeSchedulerProfile:
    """types.go:111."""

    scheduler_name: str = "default-scheduler"
    plugins: Optional[Plugins] = None
    plugin_config: Dict[str, Any] = field(default_factory=dict)  # plugin -> args


@dataclass
class LeaderElectionConfiguration:
    leader_elect: bool = False
    lease_duration_seconds: float = 15.0
    renew_deadline_seconds: float = 10.0
    retry_period_seconds: float = 2.0
    resource_name: str = "kube-scheduler"
    resource_namespace: str = "kube-system"
    # PR-2 HA hardening (scheduler/leaderelection.py): retry periods are
    # stretched by up to this fraction so candidates don't thunder in
    # lockstep, and a challenger grants an expired holder this much
    # extra grace before seizing (clock-skew tolerance)
    renew_jitter_fraction: float = 0.1
    clock_skew_tolerance_seconds: float = 0.0


@dataclass
class ResilienceConfiguration:
    """Control-plane resilience knobs (scheduler/resilience.py): the
    assumed-pod TTL sweeper, the cache<->apiserver drift checker, and
    commit-time lease fencing."""

    #: gates the WHOLE reconciler thread: assumed-pod TTL expiry AND the
    #: drift checker (they share one sweep loop); False disables both
    sweeper_enabled: bool = True
    sweep_interval_seconds: float = 1.0  # reference cleanupAssumedPods cadence
    drift_check_interval_seconds: float = 5.0
    commit_fencing: bool = True


@dataclass
class PartitionConfiguration:
    """Multi-active partitioned scheduling (scheduler/partition.py): N
    live scheduler stacks over one apiserver, each owning a consistent-
    hash slice of the node space via per-partition Leases. Enabling
    this replaces single-leader election for the stack (the stack runs
    ACTIVE immediately, scoped to its held partitions)."""

    enabled: bool = False
    #: node-space slices; stacks split them by rendezvous hashing over
    #: the live members, so it need not equal the stack count
    num_partitions: int = 2
    lease_duration_seconds: float = 1.0
    retry_period_seconds: float = 0.1
    clock_skew_tolerance_seconds: float = 0.0
    #: partition by the node's zone label (LABEL_ZONE_KEYS) instead of
    #: its name, so a zone fails over as one unit
    zone_aligned: bool = False
    resource_namespace: str = "kube-system"
    resource_prefix: str = "ksp-partition"


@dataclass
class TPUSolverConfiguration:
    """The TPU batch-solver knobs (this build's extension of the wire
    config -- VERDICT r2 missing #8: solver_mode/mesh were
    constructor-only). ``mesh_devices`` > 0 builds an n-device
    jax.sharding.Mesh over the "nodes" axis at scheduler construction."""

    enabled: bool = True
    max_batch: int = 256
    solver_mode: str = "greedy"  # "greedy" | "sinkhorn"
    batch_window_seconds: float = 0.01
    mesh_devices: int = 0  # 0 = single device (no mesh)


@dataclass
class StreamingConfiguration:
    """Open-loop streaming knobs (kubernetes_tpu/streaming/): the
    SLO-adaptive batch controller, priority-band queue jumping, and the
    arrival-engine backpressure bound. ``enabled`` turns on the
    controller (it replaces the static batchWindow/maxBatch behavior
    with feedback between its latency and throughput poles); the trace
    fields describe the arrival process the bench/runner replay."""

    enabled: bool = False
    # -- the SLO + controller -------------------------------------------
    slo_p99_seconds: float = 1.0
    min_window_seconds: float = 0.0
    #: upper window bound; clamped to slo/2 by the controller
    max_window_seconds: float = 0.25
    #: latency-mode dispatch cap (also the latency solve pad rung)
    latency_batch: int = 512
    #: size the solve-pad rung ladder from the measured per-pad solve
    #: cost at warmup (geometric candidates latencyBatch..maxBatch,
    #: pruned by AutoBatchController.calibrate) instead of the
    #: hardcoded two rungs; every surviving rung is pre-compiled
    auto_rungs: bool = False
    controller_interval_seconds: float = 0.25
    # -- priority bands --------------------------------------------------
    #: pods with spec.priority >= this form the high band; None = off
    band_priority_threshold: Optional[int] = None
    #: name of a PriorityClass object whose ``value`` selects the band
    #: threshold (resolved live from the apiserver; overrides the raw
    #: integer when both are set, and tracks PriorityClass updates)
    band_priority_class: str = ""
    # -- backpressure ----------------------------------------------------
    #: activeQ depth that stalls the arrival engine; 0 = unbounded
    max_queue_depth: int = 20000
    # -- arrival trace (bench/runner replay) -----------------------------
    trace: str = "poisson"  # poisson | bursty | diurnal | replay
    rate_pods_per_sec: float = 1000.0
    duration_seconds: float = 30.0
    seed: int = 0
    burst_rate_pods_per_sec: float = 0.0  # bursty high state (0 = 4x)
    base_dwell_seconds: float = 8.0
    burst_dwell_seconds: float = 2.0
    period_seconds: float = 60.0  # diurnal cycle length
    trough_fraction: float = 0.2  # diurnal trough / peak ratio
    replay_path: str = ""


@dataclass
class RobustnessConfiguration:
    """Degradation-ladder knobs (robustness/ladder.py): per-tier circuit
    breakers, device-solve watchdog, solve/bind retry policy."""

    enabled: bool = True
    solve_timeout_seconds: float = 60.0  # device-solve wall-clock deadline
    failure_threshold: int = 3  # consecutive failures before open
    cooloff_seconds: float = 5.0  # open -> half-open delay
    probe_batches: int = 1  # half-open probes before close
    retry_max_attempts: int = 2
    retry_backoff_seconds: float = 0.05
    retry_max_backoff_seconds: float = 1.0


@dataclass
class ContainmentConfiguration:
    """Blast-radius containment knobs (robustness/containment.py):
    poison bisection of ladder-exhausted batches + the quarantine
    ledger's strike budget and hold schedule."""

    enabled: bool = True
    max_strikes: int = 3  # isolations before parking (PodQuarantined)
    base_hold_seconds: float = 0.25  # first hold; doubles per strike
    max_hold_seconds: float = 5.0
    bisect_abort_after: int = 4  # zero-success isolations -> systemic abort


@dataclass
class TenancyConfiguration:
    """Multi-tenant fairness plane (scheduler/tenancy.py +
    controllers/quota.py): the ResourceQuota hard-cap admission gate
    (exhausted namespaces park typed-QuotaExceeded, woken by quota/
    usage events) and the DRF dominant-share solve-order bias (within a
    priority level, the tenant with the lowest dominant share places
    first -- all solver tiers, zero kernel changes). Off by default:
    single-tenant deployments pay one is-None check per popped pod."""

    enabled: bool = False
    #: enforce ResourceQuota objects at the scheduling gate
    quota_enforcement: bool = True
    #: arm the dominant-share tracker + fair solve order
    drf_bias: bool = True


@dataclass
class BindAckConfiguration:
    """Bind-ack tracking (scheduler/bindack.py): a bind is pending until
    the node agent acks it into pod status (phase=Running); a pod whose
    ack never arrives within ``ack_timeout_seconds`` is unbound back to
    the queue and rebinds elsewhere -- exactly once per incarnation.
    Off by default: bind-and-forget deployments pay one is-None check
    per commit. The ack timeout should sit well under the nodelifecycle
    grace period: a zombie kubelet heartbeats forever, so the ack path
    must fire first."""

    enabled: bool = False
    ack_timeout_seconds: float = 5.0
    sweep_interval_seconds: float = 0.5
    #: ack timeouts on one node before it is tainted NoSchedule (the
    #: rebind must land elsewhere); the taint lifts on the next ack
    node_suspect_threshold: int = 1
    taint_suspect_nodes: bool = True


@dataclass
class FaultPointConfiguration:
    """One injection point's firing policy (robustness/faults.py)."""

    rate: float = 0.0
    max_fires: Optional[int] = None
    hang_seconds: float = 0.0


@dataclass
class FaultInjectionConfiguration:
    """Fault-injection harness config. Off by default: production pays a
    single is-None check per seam. ``profile`` names a builtin profile
    (robustness/faults.py builtin_profiles); ``points`` overrides or
    extends its per-point rates."""

    enabled: bool = False
    profile: str = ""
    seed: int = 0
    points: Dict[str, FaultPointConfiguration] = field(default_factory=dict)


@dataclass
class KubeSchedulerConfiguration:
    """types.go:46."""

    profiles: List[KubeSchedulerProfile] = field(default_factory=list)
    percentage_of_nodes_to_score: int = DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE
    pod_initial_backoff_seconds: float = DEFAULT_POD_INITIAL_BACKOFF_SECONDS
    pod_max_backoff_seconds: float = DEFAULT_POD_MAX_BACKOFF_SECONDS
    leader_election: LeaderElectionConfiguration = field(
        default_factory=LeaderElectionConfiguration
    )
    health_bind_address: str = ""
    metrics_bind_address: str = ""
    feature_gates: Dict[str, bool] = field(default_factory=dict)
    tpu_solver: TPUSolverConfiguration = field(
        default_factory=TPUSolverConfiguration
    )
    robustness: RobustnessConfiguration = field(
        default_factory=RobustnessConfiguration
    )
    containment: ContainmentConfiguration = field(
        default_factory=ContainmentConfiguration
    )
    resilience: ResilienceConfiguration = field(
        default_factory=ResilienceConfiguration
    )
    fault_injection: FaultInjectionConfiguration = field(
        default_factory=FaultInjectionConfiguration
    )
    streaming: StreamingConfiguration = field(
        default_factory=StreamingConfiguration
    )
    partition: PartitionConfiguration = field(
        default_factory=PartitionConfiguration
    )
    tenancy: TenancyConfiguration = field(
        default_factory=TenancyConfiguration
    )
    bind_ack: BindAckConfiguration = field(
        default_factory=BindAckConfiguration
    )
