"""KubeSchedulerConfiguration validation.

Reference: /root/reference/pkg/scheduler/apis/config/validation/
validation.go (ValidateKubeSchedulerConfiguration) -- the same checks,
plus this build's tpuSolver block. Returns a list of error strings
(empty = valid); load_config raises on any.
"""

from __future__ import annotations

from typing import List

from kubernetes_tpu.config.types import (
    KubeSchedulerConfiguration,
    Plugins,
)

MAX_WEIGHT = 64 * 1024  # framework/v1alpha1: MaxTotalScore guardrail


def validate_config(cfg: KubeSchedulerConfiguration) -> List[str]:
    errors: List[str] = []
    if not 0 <= cfg.percentage_of_nodes_to_score <= 100:
        errors.append(
            "percentageOfNodesToScore must be in [0, 100], got "
            f"{cfg.percentage_of_nodes_to_score}"
        )
    if cfg.pod_initial_backoff_seconds <= 0:
        errors.append("podInitialBackoffSeconds must be positive")
    if cfg.pod_max_backoff_seconds < cfg.pod_initial_backoff_seconds:
        errors.append(
            "podMaxBackoffSeconds must be >= podInitialBackoffSeconds"
        )

    le = cfg.leader_election
    if le.leader_elect:
        if le.lease_duration_seconds <= 0:
            errors.append("leaderElection.leaseDuration must be positive")
        if le.renew_deadline_seconds <= 0:
            errors.append("leaderElection.renewDeadline must be positive")
        if le.retry_period_seconds <= 0:
            errors.append("leaderElection.retryPeriod must be positive")
        if le.renew_deadline_seconds > le.lease_duration_seconds:
            errors.append(
                "leaderElection.renewDeadline must be <= leaseDuration"
            )
        if not le.resource_name:
            errors.append("leaderElection.resourceName is required")
        if not 0.0 <= le.renew_jitter_fraction <= 1.0:
            errors.append(
                "leaderElection.renewJitter must be in [0, 1]"
            )
        if le.clock_skew_tolerance_seconds < 0:
            errors.append(
                "leaderElection.clockSkewTolerance must be >= 0"
            )

    # profiles: unique scheduler names; all share one queue sort
    # (profile.go:120 validation)
    names = [p.scheduler_name for p in cfg.profiles]
    if len(set(names)) != len(names):
        errors.append("profile schedulerNames must be unique")
    queue_sorts = set()
    for prof in cfg.profiles:
        if not prof.scheduler_name:
            errors.append("profile schedulerName must not be empty")
        if prof.plugins is not None:
            qs = tuple(
                p.name for p in prof.plugins.queue_sort.enabled
            )
            if qs:
                queue_sorts.add(qs)
            errors.extend(_validate_plugins(prof.scheduler_name, prof.plugins))
    if len(queue_sorts) > 1:
        errors.append("all profiles must use the same queueSort plugins")

    for i, ext in enumerate(getattr(cfg, "extenders", [])):
        if not ext.url_prefix:
            errors.append(f"extenders[{i}].urlPrefix is required")
        if ext.weight <= 0 and ext.prioritize_verb:
            errors.append(f"extenders[{i}].weight must be positive")
        if ext.http_timeout_seconds <= 0:
            errors.append(f"extenders[{i}].httpTimeout must be positive")
    binders = sum(
        1 for ext in getattr(cfg, "extenders", []) if ext.bind_verb
    )
    if binders > 1:
        errors.append("only one extender may implement bind")

    ts = cfg.tpu_solver
    if ts.solver_mode not in ("greedy", "sinkhorn"):
        errors.append(
            f"tpuSolver.solverMode must be greedy|sinkhorn, got "
            f"{ts.solver_mode!r}"
        )
    if ts.max_batch <= 0:
        errors.append("tpuSolver.maxBatch must be positive")
    if ts.batch_window_seconds < 0:
        errors.append("tpuSolver.batchWindow must be >= 0")
    if ts.mesh_devices < 0:
        errors.append("tpuSolver.meshDevices must be >= 0")

    rb = getattr(cfg, "robustness", None)
    if rb is not None:
        if rb.solve_timeout_seconds < 0:
            errors.append("robustness.solveTimeout must be >= 0")
        if rb.failure_threshold < 1:
            errors.append("robustness.failureThreshold must be >= 1")
        if rb.cooloff_seconds < 0:
            errors.append("robustness.cooloff must be >= 0")
        if rb.probe_batches < 1:
            errors.append("robustness.probeBatches must be >= 1")
        if rb.retry_max_attempts < 1:
            errors.append("robustness.retryMaxAttempts must be >= 1")
        if rb.retry_backoff_seconds < 0:
            errors.append("robustness.retryBackoff must be >= 0")

    ct = getattr(cfg, "containment", None)
    if ct is not None:
        if ct.max_strikes < 1:
            errors.append("containment.maxStrikes must be >= 1")
        if ct.base_hold_seconds < 0:
            errors.append("containment.baseHold must be >= 0")
        if ct.max_hold_seconds < ct.base_hold_seconds:
            errors.append(
                "containment.maxHold must be >= containment.baseHold"
            )
        if ct.bisect_abort_after < 1:
            errors.append("containment.bisectAbortAfter must be >= 1")

    tn = getattr(cfg, "tenancy", None)
    if tn is not None and tn.enabled:
        if not tn.quota_enforcement and not tn.drf_bias:
            errors.append(
                "tenancy.enabled with both quotaEnforcement and "
                "drfBias off arms nothing; disable tenancy instead"
            )

    ba = getattr(cfg, "bind_ack", None)
    if ba is not None and ba.enabled:
        if ba.ack_timeout_seconds <= 0:
            errors.append("bindAck.ackTimeout must be positive")
        if ba.sweep_interval_seconds <= 0:
            errors.append("bindAck.sweepInterval must be positive")
        if ba.node_suspect_threshold < 1:
            errors.append("bindAck.nodeSuspectThreshold must be >= 1")

    rs = getattr(cfg, "resilience", None)
    if rs is not None:
        if rs.sweep_interval_seconds <= 0:
            errors.append("resilience.sweepInterval must be positive")
        if rs.drift_check_interval_seconds <= 0:
            errors.append("resilience.driftCheckInterval must be positive")

    st = getattr(cfg, "streaming", None)
    if st is not None:
        if st.trace not in ("poisson", "bursty", "diurnal", "replay"):
            errors.append(
                f"streaming.trace must be poisson|bursty|diurnal|replay, "
                f"got {st.trace!r}"
            )
        if st.trace == "replay" and not st.replay_path:
            errors.append("streaming.replayPath is required for replay")
        if st.slo_p99_seconds <= 0:
            errors.append("streaming.sloP99 must be positive")
        if st.min_window_seconds < 0:
            errors.append("streaming.minWindow must be >= 0")
        if st.max_window_seconds < st.min_window_seconds:
            errors.append("streaming.maxWindow must be >= minWindow")
        if st.latency_batch <= 0:
            errors.append("streaming.latencyBatch must be positive")
        if st.controller_interval_seconds <= 0:
            errors.append("streaming.controllerInterval must be positive")
        if st.rate_pods_per_sec <= 0:
            errors.append("streaming.rate must be positive")
        if st.max_queue_depth < 0:
            errors.append("streaming.maxQueueDepth must be >= 0")
        if not 0.0 <= st.trough_fraction <= 1.0:
            errors.append("streaming.troughFraction must be in [0, 1]")

    pt = getattr(cfg, "partition", None)
    if pt is not None and pt.enabled:
        if pt.num_partitions < 1:
            errors.append("partition.numPartitions must be >= 1")
        if pt.lease_duration_seconds <= 0:
            errors.append("partition.leaseDuration must be positive")
        if pt.retry_period_seconds <= 0:
            errors.append("partition.retryPeriod must be positive")
        if pt.retry_period_seconds >= pt.lease_duration_seconds:
            errors.append(
                "partition.retryPeriod must be < leaseDuration (a "
                "holder must be able to renew before it expires)"
            )
        if pt.clock_skew_tolerance_seconds < 0:
            errors.append("partition.clockSkewTolerance must be >= 0")
        if not pt.resource_prefix:
            errors.append("partition.resourcePrefix is required")
        le = cfg.leader_election
        if le.leader_elect:
            errors.append(
                "partition.enabled and leaderElection.leaderElect are "
                "mutually exclusive (partitioned stacks are all active)"
            )

    fi = getattr(cfg, "fault_injection", None)
    if fi is not None and fi.enabled:
        from kubernetes_tpu.robustness.faults import (
            FaultPoint,
            builtin_profiles,
        )

        if fi.profile and fi.profile not in builtin_profiles():
            errors.append(
                f"faultInjection.profile {fi.profile!r} is not a known "
                f"profile ({', '.join(sorted(builtin_profiles()))})"
            )
        for name, p in fi.points.items():
            if name not in FaultPoint.ALL:
                errors.append(
                    f"faultInjection.points.{name} is not an injection "
                    f"point ({', '.join(FaultPoint.ALL)})"
                )
            if not 0.0 <= p.rate <= 1.0:
                errors.append(
                    f"faultInjection.points.{name}.rate must be in [0, 1]"
                )
            if p.hang_seconds < 0:
                errors.append(
                    f"faultInjection.points.{name}.hangSeconds must be "
                    f">= 0"
                )
    return errors


def _validate_plugins(profile: str, plugins: Plugins) -> List[str]:
    errors: List[str] = []
    for point in Plugins.EXTENSION_POINTS:
        ps = getattr(plugins, point)
        for p in ps.enabled:
            if not p.name:
                errors.append(f"profile {profile}: {point} plugin without name")
            if point == "score" and not 1 <= p.weight <= MAX_WEIGHT:
                errors.append(
                    f"profile {profile}: score plugin {p.name} weight "
                    f"{p.weight} outside [1, {MAX_WEIGHT}]"
                )
    return errors
