"""Host-side string -> int encodings for device-pure-numeric state.

The reference matches labels/selectors as strings inside its per-node hot
loops (e.g. interpodaffinity/filtering.go:256 over all nodes x all pods).
On TPU strings don't exist: every label key/value and topology value is
interned to a dense int id on the host, and the device only ever sees int
tensors (SURVEY.md section 7, "hardest parts (c)").
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np


class StringInterner:
    """Stable string -> dense-int interning. Id 0 is reserved for
    "absent" so zero-initialized tensors mean "no value"."""

    ABSENT = 0

    def __init__(self) -> None:
        self._ids: Dict[str, int] = {}
        self._strings: List[str] = ["\x00absent"]

    def intern(self, s: str) -> int:
        i = self._ids.get(s)
        if i is None:
            i = len(self._strings)
            self._ids[s] = i
            self._strings.append(s)
        return i

    def lookup(self, s: str) -> int:
        """Like intern but returns ABSENT for unknown strings."""
        return self._ids.get(s, self.ABSENT)

    def string(self, i: int) -> str:
        return self._strings[i]

    def __len__(self) -> int:
        return len(self._strings)


class TopologyEncoder:
    """Per-topology-key interning of node label values.

    Produces, for a set of registered topology keys (e.g. ``zone``,
    ``kubernetes.io/hostname``), a ``[N, K]`` int32 matrix of interned
    label values (ABSENT=0 when the node lacks the key). Keys are
    registered lazily as pod constraints reference them; adding a key
    invalidates packed columns, so the cache tracks a key-set version.
    """

    def __init__(self) -> None:
        self.keys: List[str] = []
        self._key_index: Dict[str, int] = {}
        self._value_interners: List[StringInterner] = []
        self.version = 0

    def register_key(self, key: str) -> int:
        idx = self._key_index.get(key)
        if idx is None:
            idx = len(self.keys)
            self._key_index[key] = idx
            self.keys.append(key)
            self._value_interners.append(StringInterner())
            self.version += 1
        return idx

    def key_index(self, key: str) -> Optional[int]:
        return self._key_index.get(key)

    def encode_value(self, key_idx: int, value: str) -> int:
        return self._value_interners[key_idx].intern(value)

    def num_values(self, key_idx: int) -> int:
        return len(self._value_interners[key_idx])

    def encode_node_labels(self, labels: Dict[str, str]) -> np.ndarray:
        """[K] int32 row of interned topology values for one node."""
        row = np.zeros(len(self.keys), dtype=np.int32)
        for i, key in enumerate(self.keys):
            v = labels.get(key)
            if v is not None:
                row[i] = self._value_interners[i].intern(v)
        return row
