"""NodeTensor: the ``[N, R]`` packed cluster state + incremental updates.

This lifts the reference's NodeInfo aggregates
(/root/reference/pkg/scheduler/nodeinfo/node_info.go:47: allocatable,
requestedResource, nonzeroRequest) into dense int32 device-ready arrays,
and mirrors the generation-based incremental snapshot update
(internal/cache/cache.go:203 UpdateSnapshot: only changed nodes are
copied) as an incremental row repack.

Units (chosen so int32 masks are EXACT, matching the reference's integer
quantity comparisons; see Fit semantics fit.go:181-252):
  col 0: cpu          milliCPU
  col 1: memory       KiB (allocatable floored, requests ceiled --
                      conservative: never admits a pod the byte-exact
                      check would reject)
  col 2: ephemeral    KiB (same rounding)
  col 3: pods         pod count / allowed pod number
  col 4+: extended/scalar resources, whole units, in ``ResourceDims`` order

Capacity is padded to the next multiple of 128 (TPU lane width) so the
solver JITs once per bucket, not per node-count (SURVEY.md section 7
"hardest parts (b)": pad to buckets, mask).
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from kubernetes_tpu.api.types import (
    Pod,
    RESOURCE_CPU,
    RESOURCE_EPHEMERAL_STORAGE,
    RESOURCE_MEMORY,
    RESOURCE_PODS,
    ResourceList,
    pod_resource_requests,
)
from kubernetes_tpu.cache.node_info import (
    NodeInfo,
    Resource,
    non_zero_requests,
    pod_hot_info,
)
from kubernetes_tpu.cache.snapshot import Snapshot
from kubernetes_tpu.tensors.encoding import TopologyEncoder

NODE_BUCKET = 128  # row padding granularity (TPU lane width)

CPU, MEM, EPH, PODS = 0, 1, 2, 3
NUM_FIXED_DIMS = 4

VALUE_FLOOR = 128


def value_capacity(n_cap: int, floor: int = VALUE_FLOOR) -> int:
    """Interned topology-value slots per key for the device count
    tensors (affinity/spread/score families): label values come from
    node labels, so hostname-keyed terms (the canonical
    spread-replicas-across-nodes workload) need as many slots as nodes.
    The cap adapts to the padded node capacity -- n_cap is already
    bucketed, so the derived shapes are re-JIT-stable per cluster."""
    return max(floor, n_cap)


def _kib_floor(b: int) -> int:
    return b // 1024


def _kib_ceil(b: int) -> int:
    return -((-b) // 1024)


class ResourceDims:
    """Resource name -> tensor column. Fixed dims 0-3; scalar/extended
    resources get columns as they first appear. Growing the dim set bumps
    ``version`` which invalidates packed tensors.

    Attachable-volume count limits (``attachable-volumes-*``, see
    cache/node_info.py) register through ``volume_column``: they share
    the scalar column space -- the fit scan already treats any scalar
    column with a zero request as "not requested" -- but are tracked
    separately so the node packer knows to fill their allocatable from
    CSINode limits / in-tree defaults and their requested from the
    node's in-use counts rather than from the Resource aggregates.

    Registration is thread-safe: the admission classifier registers
    volume columns from informer threads while the dispatcher packs."""

    def __init__(self) -> None:
        self._scalar_cols: Dict[str, int] = {}
        self._volume_names: set = set()
        self._volume_cols_cache: Optional[Dict[str, int]] = None
        self._reg_lock = threading.Lock()
        self.version = 0

    @property
    def num_dims(self) -> int:
        return NUM_FIXED_DIMS + len(self._scalar_cols)

    def scalar_names(self) -> List[str]:
        return sorted(self._scalar_cols, key=self._scalar_cols.__getitem__)

    def column(self, resource: str) -> int:
        if resource == RESOURCE_CPU:
            return CPU
        if resource == RESOURCE_MEMORY:
            return MEM
        if resource == RESOURCE_EPHEMERAL_STORAGE:
            return EPH
        if resource == RESOURCE_PODS:
            return PODS
        col = self._scalar_cols.get(resource)
        if col is None:
            with self._reg_lock:
                col = self._scalar_cols.get(resource)
                if col is None:
                    col = NUM_FIXED_DIMS + len(self._scalar_cols)
                    self._scalar_cols[resource] = col
                    self.version += 1
        return col

    def volume_column(self, resource: str) -> int:
        """Register ``resource`` as an attachable-volume count column."""
        col = self.column(resource)
        if resource not in self._volume_names:
            with self._reg_lock:
                self._volume_names.add(resource)
                self._volume_cols_cache = None
        return col

    def existing_column(self, resource: str) -> Optional[int]:
        """Column for ``resource`` without growing the schema."""
        return self._scalar_cols.get(resource)

    def volume_columns(self) -> Dict[str, int]:
        """name -> column for every registered volume-count resource
        (cached; invalidated on registration). Built under the
        registration lock so a concurrent volume_column() can never
        mutate the name set mid-iteration; the returned dict is
        replaced atomically and safe to read lock-free."""
        cache = self._volume_cols_cache
        if cache is None:
            with self._reg_lock:
                cache = {
                    name: self._scalar_cols[name]
                    for name in self._volume_names
                }
                self._volume_cols_cache = cache
        return cache

    def encode_resource(self, r: Resource, *, ceil_bytes: bool) -> np.ndarray:
        kib = _kib_ceil if ceil_bytes else _kib_floor
        row = np.zeros(self.num_dims, dtype=np.int32)
        row[CPU] = r.milli_cpu
        row[MEM] = kib(r.memory)
        row[EPH] = kib(r.ephemeral_storage)
        row[PODS] = r.allowed_pod_number
        for name, qty in r.scalar.items():
            row[self.column(name)] = qty
        return row

    def encode_requests(
        self, rl: ResourceList, *, ceil_bytes: bool = True, grow: bool = True
    ) -> Tuple[np.ndarray, bool]:
        """Returns (row, unknown): ``unknown`` is True when ``grow=False``
        and the list names a scalar resource with no column -- i.e. a
        resource no node in the cluster advertises, so the request is
        unsatisfiable by definition (fit.go: allocatable 0 < request)."""
        kib = _kib_ceil if ceil_bytes else _kib_floor
        row = np.zeros(self.num_dims, dtype=np.int32)
        unknown = False
        for name, qty in rl.items():
            if name == RESOURCE_CPU:
                row[CPU] = qty
            elif name == RESOURCE_MEMORY:
                row[MEM] = kib(qty)
            elif name == RESOURCE_EPHEMERAL_STORAGE:
                row[EPH] = kib(qty)
            elif name == RESOURCE_PODS:
                row[PODS] = qty
            elif not grow and name not in self._scalar_cols:
                if qty > 0:
                    unknown = True
            else:
                row[self.column(name)] = qty
        return row, unknown


@dataclass
class TensorDelta:
    """What one ``NodeTensorCache.update`` actually changed, so callers
    can reconcile device-resident state in O(changed rows) instead of
    re-diffing the full ``[N, R]`` arrays.

    ``epoch`` is the cache's monotonic update counter after this update;
    every row repacked here carries it in the per-row epoch array (see
    ``rows_changed_since``). ``layout_epoch`` moves whenever row IDENTITY
    moved -- membership add/remove, order remap, schema growth, capacity
    growth -- i.e. whenever a device buffer built against the previous
    layout can no longer be patched row-wise and must be re-uploaded."""

    epoch: int
    layout_epoch: int
    changed_rows: np.ndarray  # int64 row indices repacked by THIS update
    full: bool  # True when every row was repacked (layout moved)


@dataclass
class NodeTensor:
    """The packed view handed to the solver. Rows [num_nodes:] are padding
    (allocatable all-zero => infeasible for any non-zero request; the
    ``valid`` mask guards zero-request pods)."""

    names: List[str]
    allocatable: np.ndarray  # [N, R] int32
    requested: np.ndarray  # [N, R] int32 (col PODS = current pod count)
    non_zero_requested: np.ndarray  # [N, 2] int32 (milliCPU, KiB)
    valid: np.ndarray  # [N] bool
    topology: np.ndarray  # [N, K] int32 interned topology values
    dims: ResourceDims
    topology_encoder: TopologyEncoder
    _row_of: Optional[Dict[str, int]] = field(default=None, repr=False)
    delta: Optional[TensorDelta] = field(default=None, repr=False)

    @property
    def capacity(self) -> int:
        return self.allocatable.shape[0]

    @property
    def num_nodes(self) -> int:
        return len(self.names)

    def row(self, name: str) -> int:
        if self._row_of is None:
            self._row_of = {n: i for i, n in enumerate(self.names)}
        return self._row_of[name]


class NodeTensorCache:
    """Incremental Snapshot -> NodeTensor packer.

    Mirrors cache.UpdateSnapshot's generation compare (cache.go:239): a row
    is repacked only when its NodeInfo.generation moved. Node add/remove
    and resource/topology schema growth trigger a full repack."""

    def __init__(
        self,
        dims: Optional[ResourceDims] = None,
        topology_encoder: Optional[TopologyEncoder] = None,
    ) -> None:
        self.dims = dims or ResourceDims()
        self.topology = topology_encoder or TopologyEncoder()
        self._row_of: Dict[str, int] = {}
        self._generations: List[int] = []
        self._names: List[str] = []
        self._alloc = np.zeros((0, self.dims.num_dims), dtype=np.int32)
        self._req = np.zeros((0, self.dims.num_dims), dtype=np.int32)
        self._nzr = np.zeros((0, 2), dtype=np.int32)
        self._topo = np.zeros((0, 0), dtype=np.int32)
        self._dims_version = self.dims.version
        self._topo_version = self.topology.version
        self.full_repacks = 0
        self.rows_repacked = 0
        self.reorders = 0  # pure order remaps (no repack of unmoved rows)
        # monotonic update epoch: every repacked row is stamped with the
        # epoch of the update that repacked it, so device-state consumers
        # reconcile via rows_changed_since(epoch) instead of re-diffing
        self._epoch = 0
        self._layout_epoch = 0
        self._row_epoch = np.zeros(0, dtype=np.int64)
        # change-tracking baseline: the snapshot whose change log we
        # follow and our private read cursor into it (O(changed) update
        # fast path; reads are cursor-based and never mutate the log, so
        # sibling caches sharing the snapshot cannot steal our notes)
        self._last_snapshot = None
        self._change_cursor = 0

    # -- packing one node ---------------------------------------------------

    def _pack_row(self, i: int, ni: NodeInfo) -> None:
        self._alloc[i] = self.dims.encode_resource(ni.allocatable, ceil_bytes=False)
        req = self.dims.encode_resource(ni.requested, ceil_bytes=True)
        req[PODS] = len(ni.pods)
        vol_cols = self.dims.volume_columns()
        if vol_cols:
            # attachable-volume columns: allocatable = CSINode limit /
            # in-tree default / unlimited; requested = additive in-use
            # count from resident pods (cache/node_info.py). Volume-free
            # pods skip these dims in the fit scan (zero request).
            viu = ni.volume_in_use
            alloc_row = self._alloc[i]
            for name, col in vol_cols.items():
                alloc_row[col] = ni.volume_limit(name)
                req[col] = viu.get(name, 0)
        self._req[i] = req
        self._nzr[i, 0] = ni.non_zero_requested.milli_cpu
        self._nzr[i, 1] = _kib_ceil(ni.non_zero_requested.memory)
        if self.topology.keys:
            self._topo[i] = self.topology.encode_node_labels(
                ni.node.metadata.labels if ni.node else {}
            )
        self._generations[i] = ni.generation
        self._row_epoch[i] = self._epoch

    def _grow(self, n: int) -> None:
        cap = max(NODE_BUCKET, NODE_BUCKET * math.ceil(n / NODE_BUCKET))
        r = self.dims.num_dims
        k = len(self.topology.keys)
        self._alloc = np.zeros((cap, r), dtype=np.int32)
        self._req = np.zeros((cap, r), dtype=np.int32)
        self._nzr = np.zeros((cap, 2), dtype=np.int32)
        self._topo = np.zeros((cap, k), dtype=np.int32)
        self._row_epoch = np.zeros(cap, dtype=np.int64)

    # -- epoch handshake support --------------------------------------------

    @property
    def epoch(self) -> int:
        return self._epoch

    @property
    def layout_epoch(self) -> int:
        return self._layout_epoch

    def rows_changed_since(self, epoch: int) -> np.ndarray:
        """Row indices repacked since ``epoch`` (an ``update()``'s
        ``delta.epoch``), valid while ``layout_epoch`` is unchanged. An
        O(N) int compare -- never O(N*R) content work."""
        return np.flatnonzero(self._row_epoch[: len(self._names)] > epoch)

    def _register_columns(self, ni: NodeInfo) -> None:
        dims = self.dims
        for name in ni.allocatable.scalar:
            dims.column(name)
        for name in ni.requested.scalar:
            dims.column(name)
        for name in ni.csi_volume_limits:
            dims.volume_column(name)
        for name in ni.volume_in_use:
            dims.volume_column(name)

    def _build_tensor(self, n: int, delta: TensorDelta) -> NodeTensor:
        valid = np.zeros(self._alloc.shape[0], dtype=bool)
        valid[:n] = True
        return NodeTensor(
            names=self._names,
            allocatable=self._alloc,
            requested=self._req,
            non_zero_requested=self._nzr,
            valid=valid,
            topology=self._topo,
            dims=self.dims,
            topology_encoder=self.topology,
            delta=delta,
        )

    # -- the update entry point --------------------------------------------

    def update(self, snapshot: Snapshot) -> NodeTensor:
        """Repack changed rows and return the tensor view plus a
        ``TensorDelta`` (``nt.delta``) naming exactly the rows this call
        repacked, so device-state consumers reconcile in O(changed rows).

        When the snapshot carries accumulated change notes (the
        scheduler's own snapshot, refreshed by ``cache.update_snapshot``),
        the update itself is O(changed): only the noted NodeInfos get the
        generation compare. Foreign snapshots (tests, tools) take the
        full generation walk -- same result, O(N) int compares."""
        self._epoch += 1
        tracked = None
        membership_hint = True
        if snapshot is self._last_snapshot:
            tracked, membership_hint, self._change_cursor = (
                snapshot.changes_since(self._change_cursor)
            )
        else:
            # new snapshot object: establish our cursor baseline and
            # take the full walk once
            self._last_snapshot = snapshot
            self._change_cursor = snapshot.change_cursor()
        if (
            tracked is not None
            and not membership_hint
            and self._names
            and len(self._names) == len(snapshot.node_info_list)
        ):
            nt = self._update_tracked(snapshot, tracked)
            if nt is not None:
                return nt
        return self._update_full(snapshot)

    def _update_tracked(
        self, snapshot: Snapshot, tracked
    ) -> Optional[NodeTensor]:
        """O(changed) fast path: only the snapshot-noted NodeInfos are
        compared/repacked. Returns None when the notes turn out to need
        the full walk (unknown name, node-object transition, schema or
        topology growth)."""
        changed_infos = []
        row_of = self._row_of
        info_map = snapshot.node_info_map
        for name in tracked:
            i = row_of.get(name)
            ni = info_map.get(name)
            if i is None or ni is None or ni.node is None:
                return None  # membership drift the hint missed
            changed_infos.append((i, ni))
        for _i, ni in changed_infos:
            self._register_columns(ni)
        if (
            self.dims.version != self._dims_version
            or self.topology.version != self._topo_version
        ):
            return None  # schema grew: full repack
        changed_rows = []
        for i, ni in changed_infos:
            if self._generations[i] != ni.generation:
                self._pack_row(i, ni)
                self.rows_repacked += 1
                changed_rows.append(i)
        changed_rows.sort()
        return self._build_tensor(
            len(self._names),
            TensorDelta(
                epoch=self._epoch,
                layout_epoch=self._layout_epoch,
                changed_rows=np.asarray(changed_rows, dtype=np.int64),
                full=False,
            ),
        )

    def _update_full(self, snapshot: Snapshot) -> NodeTensor:
        infos = snapshot.list_node_infos()
        names = [ni.node_name for ni in infos]
        # Register scalar-resource columns BEFORE sizing arrays: packing a
        # row must never grow the schema mid-update.
        for ni in infos:
            self._register_columns(ni)
        schema_moved = (
            self.dims.version != self._dims_version
            or self.topology.version != self._topo_version
        )
        membership_moved = names != self._names
        if (
            membership_moved
            and not schema_moved
            and len(names) == len(self._names)
            and set(names) == set(self._names)
        ):
            # pure ordering change: permute the packed rows to the new
            # order instead of repacking all of them, then fall through
            # to the normal generation compare. Row identity moved, so
            # the layout epoch bumps (device buffers must resync).
            m = len(names)
            perm = np.fromiter(
                (self._row_of[n] for n in names), dtype=np.intp, count=m
            )
            self._alloc[:m] = self._alloc[perm]
            self._req[:m] = self._req[perm]
            self._nzr[:m] = self._nzr[perm]
            self._topo[:m] = self._topo[perm]
            gens = self._generations
            self._generations = [gens[j] for j in perm]
            self._row_epoch[:m] = self._row_epoch[perm]
            self._names = list(names)
            self._row_of = {n: i for i, n in enumerate(names)}
            self._layout_epoch += 1
            self.reorders += 1
            membership_moved = False
        full = False
        if schema_moved or membership_moved or self._alloc.shape[0] < len(infos):
            # full repack (node set or schema changed)
            self._names = list(names)
            self._row_of = {n: i for i, n in enumerate(names)}
            self._generations = [0] * len(infos)
            self._grow(len(infos))
            for i, ni in enumerate(infos):
                self._pack_row(i, ni)
            self.full_repacks += 1
            self.rows_repacked += len(infos)
            self._layout_epoch += 1
            full = True
            changed_rows = np.arange(len(infos), dtype=np.int64)
        else:
            changed = []
            for i, ni in enumerate(infos):
                if self._generations[i] != ni.generation:
                    self._pack_row(i, ni)
                    self.rows_repacked += 1
                    changed.append(i)
            changed_rows = np.asarray(changed, dtype=np.int64)
        self._dims_version = self.dims.version
        self._topo_version = self.topology.version
        return self._build_tensor(
            len(infos),
            TensorDelta(
                epoch=self._epoch,
                layout_epoch=self._layout_epoch,
                changed_rows=changed_rows,
                full=full,
            ),
        )


@dataclass
class PodBatch:
    """A batch of pending pods packed for the solver."""

    pods: List[Pod]
    requests: np.ndarray  # [B, R] int32 (col PODS == 1)
    non_zero_requests: np.ndarray  # [B, 2] int32
    priorities: np.ndarray  # [B] int32
    order: np.ndarray  # [B] int32: solve order (priority desc, FIFO)
    unsatisfiable: np.ndarray  # [B] bool: requests a resource no node has

    @property
    def size(self) -> int:
        return len(self.pods)


def pack_pod_batch(
    pods: List[Pod],
    dims: ResourceDims,
    timestamps: Optional[List[float]] = None,
) -> PodBatch:
    """Pack pending pods into a batch. Solve order matches the activeQ
    comparator (queuesort/priority_sort.go: priority desc, then enqueue
    time) so batched greedy assignment replays the sequential order.

    The schema is frozen here (``grow=False``): a pod requesting a scalar
    resource no node advertises is flagged ``unsatisfiable`` instead of
    growing the dim set mid-batch (which would shape-mismatch the
    already-packed node tensor)."""
    b = len(pods)
    # Content-deduplicated encode: a burst is overwhelmingly homogeneous
    # (a deployment scale-up packs thousands of identical specs), so
    # encode each DISTINCT request map once and gather rows vectorized --
    # the per-pod np.zeros + column-write loop was ~60% of pack time.
    row_cache: Dict[Tuple, int] = {}
    uniq_rows: List[np.ndarray] = []
    uniq_unknown: List[bool] = []
    idx = np.empty(b, dtype=np.int32)
    nzr = np.empty((b, 2), dtype=np.int32)
    prio_list = [0] * b
    for i, pod in enumerate(pods):
        req = pod_resource_requests(pod)
        # prime the accounting memo on the ORIGINAL pod here: the commit
        # path's assume/bind clones copy __dict__, so the memo rides into
        # every clone and NodeInfo.add_pod never re-derives it
        pod_hot_info(pod)
        # resolved attachable-volume counts (admission classifier memo,
        # scheduler/admission.py): they ride the request row as volume
        # columns so the fit scan enforces per-node attach limits
        vc = pod.__dict__.get("_volcount_memo") or ()
        key = (tuple(req.items()), vc)
        u = row_cache.get(key)
        if u is None:
            row, unknown = dims.encode_requests(req, grow=False)
            row[PODS] = 1
            for name, qty in vc:
                col = dims.existing_column(name)
                if col is not None:
                    # unregistered names (a nominee classified by an
                    # older scheduler instance) are skipped: the overlay
                    # under-reserves rather than shape-mismatching
                    row[col] += qty
            u = len(uniq_rows)
            uniq_rows.append(row)
            uniq_unknown.append(unknown)
            row_cache[key] = u
        idx[i] = u
        cpu, mem = non_zero_requests(pod)
        nzr[i, 0] = cpu
        nzr[i, 1] = _kib_ceil(mem)
        prio_list[i] = pod.spec.priority
    if uniq_rows:
        requests = np.stack(uniq_rows)[idx]
        unsatisfiable = np.asarray(uniq_unknown, dtype=bool)[idx]
    else:  # empty batch: preserve the [0, R] contract
        requests = np.zeros((0, dims.num_dims), dtype=np.int32)
        unsatisfiable = np.zeros(0, dtype=bool)
    priorities = np.asarray(prio_list, dtype=np.int32)
    ts = timestamps or [pod.metadata.creation_timestamp for pod in pods]
    # pop_batch already drains the activeQ in comparator order (priority
    # desc, enqueue time asc) -- detect the sorted common case and skip
    # the Python sort
    if all(
        prio_list[i] > prio_list[i + 1]
        or (prio_list[i] == prio_list[i + 1] and ts[i] <= ts[i + 1])
        for i in range(b - 1)
    ):
        order = np.arange(b, dtype=np.int32)
    else:
        order = np.array(
            sorted(range(b), key=lambda i: (-prio_list[i], ts[i])),
            dtype=np.int32,
        )
    return PodBatch(
        pods=list(pods),
        requests=requests,
        non_zero_requests=nzr,
        priorities=priorities,
        order=order,
        unsatisfiable=unsatisfiable,
    )
