"""NodeTensor: the ``[N, R]`` packed cluster state + incremental updates.

This lifts the reference's NodeInfo aggregates
(/root/reference/pkg/scheduler/nodeinfo/node_info.go:47: allocatable,
requestedResource, nonzeroRequest) into dense int32 device-ready arrays,
and mirrors the generation-based incremental snapshot update
(internal/cache/cache.go:203 UpdateSnapshot: only changed nodes are
copied) as an incremental row repack.

Units (chosen so int32 masks are EXACT, matching the reference's integer
quantity comparisons; see Fit semantics fit.go:181-252):
  col 0: cpu          milliCPU
  col 1: memory       KiB (allocatable floored, requests ceiled --
                      conservative: never admits a pod the byte-exact
                      check would reject)
  col 2: ephemeral    KiB (same rounding)
  col 3: pods         pod count / allowed pod number
  col 4+: extended/scalar resources, whole units, in ``ResourceDims`` order

Capacity is padded to the next multiple of 128 (TPU lane width) so the
solver JITs once per bucket, not per node-count (SURVEY.md section 7
"hardest parts (b)": pad to buckets, mask).
"""

from __future__ import annotations

import heapq
import math
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from kubernetes_tpu.api.types import (
    Pod,
    RESOURCE_CPU,
    RESOURCE_EPHEMERAL_STORAGE,
    RESOURCE_MEMORY,
    RESOURCE_PODS,
    ResourceList,
    pod_resource_requests,
)
from kubernetes_tpu.cache.node_info import (
    NodeInfo,
    Resource,
    non_zero_requests,
    pod_hot_info,
)
from kubernetes_tpu.cache.snapshot import Snapshot
from kubernetes_tpu import native as _native
from kubernetes_tpu.tensors.encoding import TopologyEncoder
from kubernetes_tpu.utils import metrics as _metrics

NODE_BUCKET = 128  # row padding granularity (TPU lane width)

#: extra row slots allocated past the live node count so membership
#: churn (autoscaler adds, spot replacements) claims pre-zeroed rows
#: instead of forcing a full repack + re-upload: max(NODE_BUCKET/2,
#: n/8) before bucket rounding, so a 5k-node cluster absorbs ~600 net
#: adds and a small cluster a full bucket before the layout moves
def _row_headroom(n: int) -> int:
    return max(NODE_BUCKET // 2, n // 8)

CPU, MEM, EPH, PODS = 0, 1, 2, 3
NUM_FIXED_DIMS = 4

VALUE_FLOOR = 128


def value_capacity(n_cap: int, floor: int = VALUE_FLOOR) -> int:
    """Interned topology-value slots per key for the device count
    tensors (affinity/spread/score families): label values come from
    node labels, so hostname-keyed terms (the canonical
    spread-replicas-across-nodes workload) need as many slots as nodes.
    The cap adapts to the padded node capacity -- n_cap is already
    bucketed, so the derived shapes are re-JIT-stable per cluster."""
    return max(floor, n_cap)


def _kib_floor(b: int) -> int:
    return b // 1024


def _kib_ceil(b: int) -> int:
    return -((-b) // 1024)


class ResourceDims:
    """Resource name -> tensor column. Fixed dims 0-3; scalar/extended
    resources get columns as they first appear. Growing the dim set bumps
    ``version`` which invalidates packed tensors.

    Attachable-volume count limits (``attachable-volumes-*``, see
    cache/node_info.py) register through ``volume_column``: they share
    the scalar column space -- the fit scan already treats any scalar
    column with a zero request as "not requested" -- but are tracked
    separately so the node packer knows to fill their allocatable from
    CSINode limits / in-tree defaults and their requested from the
    node's in-use counts rather than from the Resource aggregates.

    Registration is thread-safe: the admission classifier registers
    volume columns from informer threads while the dispatcher packs."""

    def __init__(self) -> None:
        self._scalar_cols: Dict[str, int] = {}
        self._volume_names: set = set()
        self._volume_cols_cache: Optional[Dict[str, int]] = None
        self._reg_lock = threading.Lock()
        self.version = 0

    @property
    def num_dims(self) -> int:
        return NUM_FIXED_DIMS + len(self._scalar_cols)

    def scalar_names(self) -> List[str]:
        return sorted(self._scalar_cols, key=self._scalar_cols.__getitem__)

    def column(self, resource: str) -> int:
        if resource == RESOURCE_CPU:
            return CPU
        if resource == RESOURCE_MEMORY:
            return MEM
        if resource == RESOURCE_EPHEMERAL_STORAGE:
            return EPH
        if resource == RESOURCE_PODS:
            return PODS
        col = self._scalar_cols.get(resource)
        if col is None:
            with self._reg_lock:
                col = self._scalar_cols.get(resource)
                if col is None:
                    col = NUM_FIXED_DIMS + len(self._scalar_cols)
                    self._scalar_cols[resource] = col
                    self.version += 1
        return col

    def volume_column(self, resource: str) -> int:
        """Register ``resource`` as an attachable-volume count column."""
        col = self.column(resource)
        if resource not in self._volume_names:
            with self._reg_lock:
                self._volume_names.add(resource)
                self._volume_cols_cache = None
        return col

    def existing_column(self, resource: str) -> Optional[int]:
        """Column for ``resource`` without growing the schema."""
        return self._scalar_cols.get(resource)

    def volume_columns(self) -> Dict[str, int]:
        """name -> column for every registered volume-count resource
        (cached; invalidated on registration). Built under the
        registration lock so a concurrent volume_column() can never
        mutate the name set mid-iteration; the returned dict is
        replaced atomically and safe to read lock-free."""
        cache = self._volume_cols_cache
        if cache is None:
            with self._reg_lock:
                cache = {
                    name: self._scalar_cols[name]
                    for name in self._volume_names
                }
                self._volume_cols_cache = cache
        return cache

    def encode_resource(self, r: Resource, *, ceil_bytes: bool) -> np.ndarray:
        kib = _kib_ceil if ceil_bytes else _kib_floor
        row = np.zeros(self.num_dims, dtype=np.int32)
        row[CPU] = r.milli_cpu
        row[MEM] = kib(r.memory)
        row[EPH] = kib(r.ephemeral_storage)
        row[PODS] = r.allowed_pod_number
        for name, qty in r.scalar.items():
            row[self.column(name)] = qty
        return row

    def encode_requests(
        self, rl: ResourceList, *, ceil_bytes: bool = True, grow: bool = True
    ) -> Tuple[np.ndarray, bool]:
        """Returns (row, unknown): ``unknown`` is True when ``grow=False``
        and the list names a scalar resource with no column -- i.e. a
        resource no node in the cluster advertises, so the request is
        unsatisfiable by definition (fit.go: allocatable 0 < request)."""
        kib = _kib_ceil if ceil_bytes else _kib_floor
        row = np.zeros(self.num_dims, dtype=np.int32)
        unknown = False
        for name, qty in rl.items():
            if name == RESOURCE_CPU:
                row[CPU] = qty
            elif name == RESOURCE_MEMORY:
                row[MEM] = kib(qty)
            elif name == RESOURCE_EPHEMERAL_STORAGE:
                row[EPH] = kib(qty)
            elif name == RESOURCE_PODS:
                row[PODS] = qty
            elif not grow and name not in self._scalar_cols:
                if qty > 0:
                    unknown = True
            else:
                row[self.column(name)] = qty
        return row, unknown


@dataclass
class TensorDelta:
    """What one ``NodeTensorCache.update`` actually changed, so callers
    can reconcile device-resident state in O(changed rows) instead of
    re-diffing the full ``[N, R]`` arrays.

    ``epoch`` is the cache's monotonic update counter after this update;
    every row repacked here carries it in the per-row epoch array (see
    ``rows_changed_since``). ``layout_epoch`` moves only when existing
    row identity can no longer be patched row-wise -- schema growth or
    slot-capacity exhaustion (full repack). Pure membership add/remove
    claims/retires SLOTS in place: the affected rows land in
    ``membership_rows`` (and ``changed_rows``) so device-state consumers
    patch them as O(changed) scatters instead of re-uploading [N, R]."""

    epoch: int
    layout_epoch: int
    changed_rows: np.ndarray  # int64 row indices repacked by THIS update
    full: bool  # True when every row was repacked (layout moved)
    # row slots whose IDENTITY changed this update (node added into the
    # slot, or the slot's node retired): expected resets for the device
    # handshake, never divergences
    membership_rows: np.ndarray = field(
        default_factory=lambda: np.zeros(0, dtype=np.int64)
    )


@dataclass
class NodeTensor:
    """The packed view handed to the solver. Rows are SLOTS: a retired
    node's slot stays in place (zeroed, ``valid`` False, name ``""``)
    until a later add reclaims it, so membership churn never moves the
    surviving rows. Rows [num_nodes:] are capacity padding; both padding
    and free slots are infeasible for any non-zero request (allocatable
    all-zero) and masked off for zero-request pods by ``valid``."""

    names: List[str]  # slot -> node name; "" marks a free (retired) slot
    allocatable: np.ndarray  # [N, R] int32
    requested: np.ndarray  # [N, R] int32 (col PODS = current pod count)
    non_zero_requested: np.ndarray  # [N, 2] int32 (milliCPU, KiB)
    valid: np.ndarray  # [N] bool (occupied slots only)
    topology: np.ndarray  # [N, K] int32 interned topology values
    dims: ResourceDims
    topology_encoder: TopologyEncoder
    #: tensor row per entry of the snapshot's node_info_list: packers
    #: iterating the snapshot MUST index node-dimension tensors through
    #: this (snapshot order stopped being row order when slots arrived)
    info_rows: Optional[np.ndarray] = field(default=None, repr=False)
    _row_of: Optional[Dict[str, int]] = field(default=None, repr=False)
    delta: Optional[TensorDelta] = field(default=None, repr=False)

    @property
    def capacity(self) -> int:
        return self.allocatable.shape[0]

    @property
    def num_nodes(self) -> int:
        """Slot count (the indexable prefix of ``names``): >= the live
        node count whenever retired slots exist."""
        return len(self.names)

    def row(self, name: str) -> int:
        if self._row_of is None:
            self._row_of = {
                n: i for i, n in enumerate(self.names) if n
            }
        return self._row_of[name]

    def rows_for(self, infos: List[NodeInfo]) -> np.ndarray:
        """Tensor row per entry of ``infos`` (the snapshot's
        node_info_list, the order every packer iterates in). Packers MUST
        index node-dimension tensors through this: with the slot layout,
        snapshot position j and tensor row diverge as soon as one
        membership change lands. Falls back to the identity map for
        tensors built without a row map (direct construction in
        tests/tools, where no slots have ever moved)."""
        if self.info_rows is not None and len(self.info_rows) == len(infos):
            return self.info_rows
        return np.arange(len(infos), dtype=np.int64)


class NodeTensorCache:
    """Incremental Snapshot -> NodeTensor packer.

    Mirrors cache.UpdateSnapshot's generation compare (cache.go:239): a row
    is repacked only when its NodeInfo.generation moved. Rows are SLOTS
    with pre-allocated headroom and a free-row list: node add/remove
    claims or retires a slot in place -- O(changed rows), no layout move
    -- and a pure ordering change is a no-op. A full repack (counted,
    layout_epoch bump) happens only for resource/topology schema growth
    or when adds exhaust the slot headroom."""

    def __init__(
        self,
        dims: Optional[ResourceDims] = None,
        topology_encoder: Optional[TopologyEncoder] = None,
    ) -> None:
        self.dims = dims or ResourceDims()
        self.topology = topology_encoder or TopologyEncoder()
        self._row_of: Dict[str, int] = {}
        self._generations: List[int] = []
        self._names: List[str] = []  # slot -> name, "" = free slot
        self._free_rows: List[int] = []  # min-heap of retired slots
        self._node_count = 0
        self._alloc = np.zeros((0, self.dims.num_dims), dtype=np.int32)
        self._req = np.zeros((0, self.dims.num_dims), dtype=np.int32)
        self._nzr = np.zeros((0, 2), dtype=np.int32)
        self._topo = np.zeros((0, 0), dtype=np.int32)
        self._occupied = np.zeros(0, dtype=bool)
        self._dims_version = self.dims.version
        self._topo_version = self.topology.version
        self.full_repacks = 0
        self.rows_repacked = 0
        self.rows_added = 0  # slots claimed by incremental node adds
        self.rows_retired = 0  # slots freed by incremental node removals
        self.reorders = 0  # ordering-only snapshot changes (zero work now)
        # monotonic update epoch: every repacked row is stamped with the
        # epoch of the update that repacked it, so device-state consumers
        # reconcile via rows_changed_since(epoch) instead of re-diffing;
        # membership (identity) changes additionally stamp the member
        # epoch so the handshake can tell expected slot resets apart
        # from divergences
        self._epoch = 0
        self._layout_epoch = 0
        self._row_epoch = np.zeros(0, dtype=np.int64)
        self._row_member_epoch = np.zeros(0, dtype=np.int64)
        # snapshot-position -> tensor row map handed to the packers via
        # NodeTensor.info_rows; rebuilt only when membership/order moved
        self._info_rows: Optional[np.ndarray] = None
        # change-tracking baseline: the snapshot whose change log we
        # follow and our private read cursor into it (O(changed) update
        # fast path; reads are cursor-based and never mutate the log, so
        # sibling caches sharing the snapshot cannot steal our notes)
        self._last_snapshot = None
        self._change_cursor = 0

    # -- packing one node ---------------------------------------------------

    def _pack_row(self, i: int, ni: NodeInfo) -> None:
        self._alloc[i] = self.dims.encode_resource(ni.allocatable, ceil_bytes=False)
        req = self.dims.encode_resource(ni.requested, ceil_bytes=True)
        req[PODS] = len(ni.pods)
        vol_cols = self.dims.volume_columns()
        if vol_cols:
            # attachable-volume columns: allocatable = CSINode limit /
            # in-tree default / unlimited; requested = additive in-use
            # count from resident pods (cache/node_info.py). Volume-free
            # pods skip these dims in the fit scan (zero request).
            viu = ni.volume_in_use
            alloc_row = self._alloc[i]
            for name, col in vol_cols.items():
                alloc_row[col] = ni.volume_limit(name)
                req[col] = viu.get(name, 0)
        self._req[i] = req
        self._nzr[i, 0] = ni.non_zero_requested.milli_cpu
        self._nzr[i, 1] = _kib_ceil(ni.non_zero_requested.memory)
        if self.topology.keys:
            self._topo[i] = self.topology.encode_node_labels(
                ni.node.metadata.labels if ni.node else {}
            )
        self._generations[i] = ni.generation
        self._occupied[i] = True
        self._row_epoch[i] = self._epoch

    def _grow(self, n: int) -> None:
        target = max(n + _row_headroom(n), NODE_BUCKET)
        cap = NODE_BUCKET * math.ceil(target / NODE_BUCKET)
        r = self.dims.num_dims
        k = len(self.topology.keys)
        self._alloc = np.zeros((cap, r), dtype=np.int32)
        self._req = np.zeros((cap, r), dtype=np.int32)
        self._nzr = np.zeros((cap, 2), dtype=np.int32)
        self._topo = np.zeros((cap, k), dtype=np.int32)
        self._occupied = np.zeros(cap, dtype=bool)
        self._row_epoch = np.zeros(cap, dtype=np.int64)
        self._row_member_epoch = np.zeros(cap, dtype=np.int64)

    # -- slot lifecycle (incremental membership) -----------------------------

    def _retire_row(self, i: int) -> None:
        """Free an occupied slot in place: zero its content (free slots
        must be infeasible exactly like capacity padding), stamp both
        epochs, and put it on the free list for the next add."""
        self._alloc[i] = 0
        self._req[i] = 0
        self._nzr[i] = 0
        if self._topo.shape[1]:
            self._topo[i] = 0
        self._generations[i] = 0
        self._occupied[i] = False
        self._row_epoch[i] = self._epoch
        self._row_member_epoch[i] = self._epoch
        heapq.heappush(self._free_rows, i)
        self.rows_retired += 1
        _metrics.tensor_rows_retired.inc()

    def _claim_row(self) -> Optional[int]:
        """A slot for a new node: lowest free slot first, else the next
        never-used slot inside the allocated capacity. None = headroom
        exhausted (caller must full-repack with fresh headroom)."""
        if self._free_rows:
            return heapq.heappop(self._free_rows)
        i = len(self._names)
        if i >= self._alloc.shape[0]:
            return None
        self._names.append("")
        self._generations.append(0)
        return i

    # -- epoch handshake support --------------------------------------------

    @property
    def epoch(self) -> int:
        return self._epoch

    @property
    def layout_epoch(self) -> int:
        return self._layout_epoch

    def rows_changed_since(self, epoch: int) -> np.ndarray:
        """Row indices repacked since ``epoch`` (an ``update()``'s
        ``delta.epoch``), valid while ``layout_epoch`` is unchanged. An
        O(N) int compare -- never O(N*R) content work."""
        return np.flatnonzero(self._row_epoch[: len(self._names)] > epoch)

    def membership_rows_since(self, epoch: int) -> np.ndarray:
        """Row slots whose IDENTITY changed since ``epoch`` (a node was
        added into the slot or retired from it), valid while
        ``layout_epoch`` is unchanged. These are EXPECTED resets for the
        device-state handshake: their host content legitimately differs
        from the mirrored expectation and must be scatter-adopted, not
        counted as divergence. Same O(N) int compare as
        ``rows_changed_since``."""
        return np.flatnonzero(
            self._row_member_epoch[: len(self._names)] > epoch
        )

    def _register_columns(self, ni: NodeInfo) -> None:
        dims = self.dims
        for name in ni.allocatable.scalar:
            dims.column(name)
        for name in ni.requested.scalar:
            dims.column(name)
        for name in ni.csi_volume_limits:
            dims.volume_column(name)
        for name in ni.volume_in_use:
            dims.volume_column(name)

    def _build_tensor(self, delta: TensorDelta) -> NodeTensor:
        return NodeTensor(
            names=self._names,
            allocatable=self._alloc,
            requested=self._req,
            non_zero_requested=self._nzr,
            valid=self._occupied.copy(),
            topology=self._topo,
            dims=self.dims,
            topology_encoder=self.topology,
            info_rows=self._info_rows,
            delta=delta,
        )

    def _refresh_info_rows(self, infos: List[NodeInfo]) -> None:
        row_of = self._row_of
        self._info_rows = np.fromiter(
            (row_of[ni.node_name] for ni in infos),
            dtype=np.int64,
            count=len(infos),
        )

    # -- the update entry point --------------------------------------------

    def update(self, snapshot: Snapshot) -> NodeTensor:
        """Repack changed rows and return the tensor view plus a
        ``TensorDelta`` (``nt.delta``) naming exactly the rows this call
        repacked, so device-state consumers reconcile in O(changed rows).

        When the snapshot carries accumulated change notes (the
        scheduler's own snapshot, refreshed by ``cache.update_snapshot``),
        the update itself is O(changed): only the noted NodeInfos get the
        generation compare. Membership changes take an O(N) set diff and
        touch only the affected slots (retire into the free list / claim
        a free or headroom slot). Foreign snapshots (tests, tools) take
        the full generation walk -- same result, O(N) int compares."""
        self._epoch += 1
        tracked = None
        membership_hint = True
        if snapshot is self._last_snapshot:
            tracked, membership_hint, self._change_cursor = (
                snapshot.changes_since(self._change_cursor)
            )
        else:
            # new snapshot object: establish our cursor baseline and
            # take the full walk once (no ordering signal to count)
            self._last_snapshot = snapshot
            self._change_cursor = snapshot.change_cursor()
            membership_hint = False
        if (
            tracked is not None
            and not membership_hint
            and self._names
            and self._node_count == len(snapshot.node_info_list)
        ):
            nt = self._update_tracked(snapshot, tracked)
            if nt is not None:
                return nt
            tracked = None  # notes insufficient: full generation walk
        elif not membership_hint:
            tracked = None
        return self._update_full(snapshot, tracked, membership_hint)

    def _update_tracked(
        self, snapshot: Snapshot, tracked
    ) -> Optional[NodeTensor]:
        """O(changed) fast path: only the snapshot-noted NodeInfos are
        compared/repacked. Returns None when the notes turn out to need
        the full walk (unknown name, node-object transition, schema or
        topology growth)."""
        changed_infos = []
        row_of = self._row_of
        info_map = snapshot.node_info_map
        for name in tracked:
            i = row_of.get(name)
            ni = info_map.get(name)
            if i is None or ni is None or ni.node is None:
                return None  # membership drift the hint missed
            changed_infos.append((i, ni))
        for _i, ni in changed_infos:
            self._register_columns(ni)
        if (
            self.dims.version != self._dims_version
            or self.topology.version != self._topo_version
        ):
            return None  # schema grew: full repack
        changed_rows = []
        for i, ni in changed_infos:
            if self._generations[i] != ni.generation:
                self._pack_row(i, ni)
                self.rows_repacked += 1
                changed_rows.append(i)
        changed_rows.sort()
        return self._build_tensor(
            TensorDelta(
                epoch=self._epoch,
                layout_epoch=self._layout_epoch,
                changed_rows=np.asarray(changed_rows, dtype=np.int64),
                full=False,
            ),
        )

    def _update_full(
        self, snapshot: Snapshot, tracked=None, membership_hint=True
    ) -> NodeTensor:
        """Membership diff + generation compare. ``tracked`` (when the
        change log survived) limits the generation compare to the noted
        names; None means compare every row."""
        infos = snapshot.list_node_infos()
        info_map = snapshot.node_info_map
        # Register scalar-resource columns BEFORE sizing arrays: packing a
        # row must never grow the schema mid-update.
        if tracked is None:
            for ni in infos:
                self._register_columns(ni)
        else:
            for name in tracked:
                ni = info_map.get(name)
                if ni is not None and ni.node is not None:
                    self._register_columns(ni)
        schema_moved = (
            self.dims.version != self._dims_version
            or self.topology.version != self._topo_version
        )
        names_now = [ni.node_name for ni in infos]
        current = set(names_now)
        removed = [n for n in self._row_of if n not in current]
        added = [n for n in names_now if n not in self._row_of]
        slots_available = (
            len(self._free_rows)
            + len(removed)
            + (self._alloc.shape[0] - len(self._names))
        )
        if schema_moved or len(added) > slots_available:
            # full repack: schema grew, or adds exhausted the slot
            # headroom -- counted, layout moves, fresh headroom
            self._names = list(names_now)
            self._row_of = {n: i for i, n in enumerate(names_now)}
            self._generations = [0] * len(infos)
            self._free_rows = []
            self._node_count = len(infos)
            self._grow(len(infos))
            for i, ni in enumerate(infos):
                self._pack_row(i, ni)
            self.full_repacks += 1
            _metrics.tensor_full_repacks.inc()
            self.rows_repacked += len(infos)
            self._layout_epoch += 1
            self._row_member_epoch[:] = self._epoch
            self._refresh_info_rows(infos)
            self._dims_version = self.dims.version
            self._topo_version = self.topology.version
            return self._build_tensor(
                TensorDelta(
                    epoch=self._epoch,
                    layout_epoch=self._layout_epoch,
                    changed_rows=np.arange(len(infos), dtype=np.int64),
                    full=True,
                ),
            )
        member_rows: List[int] = []
        if removed or added:
            # copy-on-write: NodeTensors captured by in-flight batches
            # keep resolving assignment indices against the layout they
            # were dispatched with
            self._names = list(self._names)
            for n in removed:
                i = self._row_of.pop(n)
                self._names[i] = ""
                self._retire_row(i)
                member_rows.append(i)
            for n in added:
                i = self._claim_row()
                self._row_of[n] = i
                self._names[i] = n
                self._pack_row(i, info_map[n])
                self._row_member_epoch[i] = self._epoch
                self.rows_added += 1
                _metrics.tensor_rows_added.inc()
                self.rows_repacked += 1
                member_rows.append(i)
            self._node_count = len(infos)
        elif membership_hint and self._info_rows is not None:
            # ordering-only change: slots do not move, nothing repacks
            self.reorders += 1
        # snapshot positions may have shifted even without add/remove
        # (ordering change) -- refresh the packers' position->row map on
        # any full-path update (it is O(N) dict gets, and this path
        # already walked the list)
        self._refresh_info_rows(infos)
        changed: List[int] = []
        row_of = self._row_of
        if tracked is None:
            for ni in infos:
                i = row_of[ni.node_name]
                if self._generations[i] != ni.generation:
                    self._pack_row(i, ni)
                    self.rows_repacked += 1
                    changed.append(i)
        else:
            for name in tracked:
                ni = info_map.get(name)
                i = row_of.get(name)
                if ni is None or ni.node is None or i is None:
                    continue  # removed this update: already retired
                if self._generations[i] != ni.generation:
                    self._pack_row(i, ni)
                    self.rows_repacked += 1
                    changed.append(i)
        changed_rows = np.asarray(
            sorted(changed + member_rows), dtype=np.int64
        )
        self._dims_version = self.dims.version
        self._topo_version = self.topology.version
        return self._build_tensor(
            TensorDelta(
                epoch=self._epoch,
                layout_epoch=self._layout_epoch,
                changed_rows=changed_rows,
                full=False,
                membership_rows=np.asarray(
                    sorted(member_rows), dtype=np.int64
                ),
            ),
        )


@dataclass
class PodBatch:
    """A batch of pending pods packed for the solver."""

    pods: List[Pod]
    requests: np.ndarray  # [B, R] int32 (col PODS == 1)
    non_zero_requests: np.ndarray  # [B, 2] int32
    priorities: np.ndarray  # [B] int32
    order: np.ndarray  # [B] int32: solve order (priority desc, FIFO)
    unsatisfiable: np.ndarray  # [B] bool: requests a resource no node has

    @property
    def size(self) -> int:
        return len(self.pods)


def stamp_pack_row(pod: Pod) -> Tuple:
    """Build (and memoize as ``pod._packrow``) the pod's pack-ready row
    record: ``((request_items, vol_counts), nzr_cpu, nzr_mem_kib,
    priority)``. Stamped at informer ingest by the admission classifier
    (scheduler/admission.py -- natively for plain pods via
    ``ingest_stamp``), invalidated by the same paths that strip the
    other spec memos (apiserver ``_ALL_MEMOS``), so ``pack_pod_batch``
    and ``pack_preemption_state`` gather memoized rows instead of
    re-walking specs per pod per cycle. Also primes ``pod_hot_info`` so
    the commit path's clones carry the accounting memo -- ``_packrow``
    present implies ``_hot_memo`` present."""
    req = pod_resource_requests(pod)
    pod_hot_info(pod)
    # resolved attachable-volume counts (admission classifier memo):
    # they ride the request row as volume columns so the fit scan
    # enforces per-node attach limits
    vc = tuple(pod.__dict__.get("_volcount_memo") or ())
    cpu, mem = non_zero_requests(pod)
    memo = (
        (tuple(req.items()), vc), cpu, _kib_ceil(mem), pod.spec.priority,
    )
    pod.__dict__["_packrow"] = memo
    return memo


def _pack_gather_py(
    pods: List[Pod], stamp, row_cache: Dict, idx, nzr, prio,
) -> List[Tuple]:
    """Pure-Python twin of native ``pack_gather`` (identical semantics;
    tests/test_native_ingest.py fuzzes the two): gather each pod's
    ``_packrow`` memo (stamping on miss) into the preallocated int32
    buffers, dedup request keys through ``row_cache``, return the
    distinct keys first seen this call in order."""
    new_keys: List[Tuple] = []
    for i, pod in enumerate(pods):
        memo = pod.__dict__.get("_packrow")
        if memo is None:
            memo = stamp(pod)
        key = memo[0]
        u = row_cache.get(key)
        if u is None:
            u = len(row_cache)
            row_cache[key] = u
            new_keys.append(key)
        idx[i] = u
        nzr[i, 0] = memo[1]
        nzr[i, 1] = memo[2]
        prio[i] = memo[3]
    return new_keys


def pack_pod_batch(
    pods: List[Pod],
    dims: ResourceDims,
    timestamps: Optional[List[float]] = None,
) -> PodBatch:
    """Pack pending pods into a batch. Solve order matches the activeQ
    comparator (queuesort/priority_sort.go: priority desc, then enqueue
    time) so batched greedy assignment replays the sequential order.

    The per-pod spec walk lives at INGEST now (``stamp_pack_row``, run
    by the admission classifier when the pod enters the queue): the
    per-cycle work here is one gather over the ``_packrow`` memos into
    preallocated ``[B]``/``[B, 2]`` buffers -- a single C pass when the
    native ingest plane is available -- plus one schema encode per
    DISTINCT request row (a burst is overwhelmingly homogeneous).

    The schema is frozen here (``grow=False``): a pod requesting a scalar
    resource no node advertises is flagged ``unsatisfiable`` instead of
    growing the dim set mid-batch (which would shape-mismatch the
    already-packed node tensor)."""
    b = len(pods)
    if b == 0:  # empty batch: preserve the [0, R] contract
        return PodBatch(
            pods=[],
            requests=np.zeros((0, dims.num_dims), dtype=np.int32),
            non_zero_requests=np.zeros((0, 2), dtype=np.int32),
            priorities=np.zeros(0, dtype=np.int32),
            order=np.arange(0, dtype=np.int32),
            unsatisfiable=np.zeros(0, dtype=bool),
        )
    row_cache: Dict[Tuple, int] = {}
    idx = np.empty(b, dtype=np.int32)
    nzr = np.empty((b, 2), dtype=np.int32)
    prio = np.empty(b, dtype=np.int32)
    pods_l = pods if isinstance(pods, list) else list(pods)
    gather, expected = _native.ingest_fn("pack_gather")
    if gather is not None:
        new_keys = gather(pods_l, stamp_pack_row, row_cache, idx, nzr, prio)
    else:
        if expected:
            _metrics.ingest_native_fallbacks.inc(site="pack-gather")
        new_keys = _pack_gather_py(
            pods_l, stamp_pack_row, row_cache, idx, nzr, prio
        )
    # encode each DISTINCT request row once and gather vectorized
    uniq_rows: List[np.ndarray] = []
    uniq_unknown: List[bool] = []
    for req_items, vc in new_keys:
        row, unknown = dims.encode_requests(dict(req_items), grow=False)
        row[PODS] = 1
        for name, qty in vc:
            col = dims.existing_column(name)
            if col is not None:
                # unregistered names (a nominee classified by an older
                # scheduler instance) are skipped: the overlay
                # under-reserves rather than shape-mismatching
                row[col] += qty
        uniq_rows.append(row)
        uniq_unknown.append(unknown)
    requests = np.stack(uniq_rows)[idx]
    unsatisfiable = np.asarray(uniq_unknown, dtype=bool)[idx]
    ts = timestamps or [pod.metadata.creation_timestamp for pod in pods_l]
    # pop_batch already drains the activeQ in comparator order (priority
    # desc, enqueue time asc) -- detect the sorted common case and skip
    # the Python sort (vectorized: the old per-pod generator was O(B)
    # interpreter work per pack)
    ts_arr = np.asarray(ts, dtype=np.float64)
    if b <= 1 or bool(
        np.all(
            (prio[:-1] > prio[1:])
            | ((prio[:-1] == prio[1:]) & (ts_arr[:-1] <= ts_arr[1:]))
        )
    ):
        order = np.arange(b, dtype=np.int32)
    else:
        order = np.array(
            sorted(range(b), key=lambda i: (-int(prio[i]), ts[i])),
            dtype=np.int32,
        )
    return PodBatch(
        pods=list(pods_l),
        requests=requests,
        non_zero_requests=nzr,
        priorities=prio,
        order=order,
        unsatisfiable=unsatisfiable,
    )
