"""Tensorized cluster state: the bridge from API objects to device arrays.

This is the TPU-native replacement for the reference's NodeInfo snapshot
(/root/reference/pkg/scheduler/internal/cache/snapshot.go): instead of a
list of per-node Go structs walked by 16 goroutines, cluster state is packed
into dense ``[N, R]`` integer tensors that the JAX solver
(kubernetes_tpu.ops) consumes, with generation-based incremental repacking
mirroring cache.UpdateSnapshot (cache.go:203).
"""

from kubernetes_tpu.tensors.node_tensor import (
    NodeTensor,
    NodeTensorCache,
    PodBatch,
    ResourceDims,
    pack_pod_batch,
)
from kubernetes_tpu.tensors.encoding import StringInterner, TopologyEncoder

__all__ = [
    "NodeTensor",
    "NodeTensorCache",
    "PodBatch",
    "ResourceDims",
    "pack_pod_batch",
    "StringInterner",
    "TopologyEncoder",
]
