"""Native host data plane (SURVEY.md section 2.4).

The C extension (_hotpath.c) is compiled ON FIRST IMPORT with the
toolchain baked into the image (g++ against the running interpreter's
headers -- no pip, no pybind11). A build or import failure degrades
silently to the pure-Python implementations in api/selectors.py, which
carry identical semantics (differentially fuzzed in
tests/test_native_selectors.py).
"""

from __future__ import annotations

import logging
import os
import subprocess
import sysconfig

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "_hotpath.c")
_SO = os.path.join(
    _DIR, "_hotpath" + (sysconfig.get_config_var("EXT_SUFFIX") or ".so")
)


def _build() -> bool:
    include = sysconfig.get_paths()["include"]
    tmp = _SO + f".build.{os.getpid()}"
    for cc in ("g++", "cc", "gcc"):
        try:
            subprocess.run(
                [
                    cc, "-O2", "-shared", "-fPIC", "-x", "c",
                    f"-I{include}", _SRC, "-o", tmp,
                ],
                check=True,
                capture_output=True,
                timeout=120,
            )
            # atomic publish: concurrent importers never dlopen a
            # half-written binary
            os.replace(tmp, _SO)
            return True
        except FileNotFoundError:
            continue
        except Exception as e:  # noqa: BLE001 - try the next compiler
            logger.debug("native build with %s failed: %s", cc, e)
            continue
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass
    return False


hotpath = None
try:
    if not os.path.exists(_SO) or (
        os.path.getmtime(_SO) < os.path.getmtime(_SRC)
    ):
        _build()
    # gate the import on the binary being CURRENT: importing a stale .so
    # after a failed rebuild would silently run old matching semantics
    if os.path.exists(_SO) and (
        os.path.getmtime(_SO) >= os.path.getmtime(_SRC)
    ):
        from kubernetes_tpu.native import _hotpath as hotpath  # type: ignore
except Exception:  # noqa: BLE001 - pure-Python fallback
    hotpath = None

#: single source of truth for the native clone fast path: callers do
#: ``from kubernetes_tpu.native import cow_clone`` and fall back to
#: copy.copy chains when it is None (build/import failure, stale .so)
cow_clone = getattr(hotpath, "cow_clone", None)
#: one-call commit-path loops (see _hotpath.c "bulk commit spine")
assume_clones = getattr(hotpath, "assume_clones", None)
bind_assumed_bulk = getattr(hotpath, "bind_assumed_bulk", None)
commit_gather = getattr(hotpath, "commit_gather", None)

# -- the ingest plane (see _hotpath.c "ingest spine") ---------------------
#
# Gated separately from the commit-path loops by KTPU_NATIVE_INGEST
# (default on): =0 forces the pure-Python twins at every ingest call
# site, the differential-test and A/B-bench switch. The env var is read
# PER CALL of ``ingest_fn`` (cheap: once per frame/batch, not per pod)
# so tests can flip it without re-importing the world.

_INGEST_FNS = {
    name: getattr(hotpath, name, None)
    for name in (
        "ingest_decode", "ingest_apply", "ingest_stamp",
        "pack_gather", "queue_shape", "mirror_scatter",
    )
}


def ingest_on() -> bool:
    """True when the native ingest plane is not disabled by env."""
    return os.environ.get("KTPU_NATIVE_INGEST", "1") not in ("0", "false")


def ingest_native_active() -> bool:
    """True when ingest calls will actually run the C path (env on AND
    the extension built) -- the machine-readable bench label."""
    return ingest_on() and _INGEST_FNS.get("ingest_apply") is not None


def ingest_fn(name: str):
    """(callable_or_None, expected): the native ingest entry point, or
    None with ``expected`` telling the caller whether running the
    Python twin counts as a FALLBACK (native wanted but unavailable --
    the caller books scheduler_ingest_native_fallbacks_total) or as the
    configured path (KTPU_NATIVE_INGEST=0)."""
    if not ingest_on():
        return None, False
    return _INGEST_FNS.get(name), True
