"""Native host data plane (SURVEY.md section 2.4).

The C extension (_hotpath.c) is compiled ON FIRST IMPORT with the
toolchain baked into the image (g++ against the running interpreter's
headers -- no pip, no pybind11). A build or import failure degrades
silently to the pure-Python implementations in api/selectors.py, which
carry identical semantics (differentially fuzzed in
tests/test_native_selectors.py).
"""

from __future__ import annotations

import logging
import os
import subprocess
import sysconfig

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "_hotpath.c")
_SO = os.path.join(
    _DIR, "_hotpath" + (sysconfig.get_config_var("EXT_SUFFIX") or ".so")
)


def _build() -> bool:
    include = sysconfig.get_paths()["include"]
    tmp = _SO + f".build.{os.getpid()}"
    for cc in ("g++", "cc", "gcc"):
        try:
            subprocess.run(
                [
                    cc, "-O2", "-shared", "-fPIC", "-x", "c",
                    f"-I{include}", _SRC, "-o", tmp,
                ],
                check=True,
                capture_output=True,
                timeout=120,
            )
            # atomic publish: concurrent importers never dlopen a
            # half-written binary
            os.replace(tmp, _SO)
            return True
        except FileNotFoundError:
            continue
        except Exception as e:  # noqa: BLE001 - try the next compiler
            logger.debug("native build with %s failed: %s", cc, e)
            continue
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass
    return False


hotpath = None
try:
    if not os.path.exists(_SO) or (
        os.path.getmtime(_SO) < os.path.getmtime(_SRC)
    ):
        _build()
    # gate the import on the binary being CURRENT: importing a stale .so
    # after a failed rebuild would silently run old matching semantics
    if os.path.exists(_SO) and (
        os.path.getmtime(_SO) >= os.path.getmtime(_SRC)
    ):
        from kubernetes_tpu.native import _hotpath as hotpath  # type: ignore
except Exception:  # noqa: BLE001 - pure-Python fallback
    hotpath = None

#: single source of truth for the native clone fast path: callers do
#: ``from kubernetes_tpu.native import cow_clone`` and fall back to
#: copy.copy chains when it is None (build/import failure, stale .so)
cow_clone = getattr(hotpath, "cow_clone", None)
#: one-call commit-path loops (see _hotpath.c "bulk commit spine")
assume_clones = getattr(hotpath, "assume_clones", None)
bind_assumed_bulk = getattr(hotpath, "bind_assumed_bulk", None)
commit_gather = getattr(hotpath, "commit_gather", None)
