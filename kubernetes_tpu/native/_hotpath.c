/* Native host data plane: the hot label-selector matcher.
 *
 * SURVEY.md section 2.4: the reference has no native scheduling code (all
 * Go); the native components owed here are the NEW performance core. On
 * the host side the single hottest string operation is label-selector
 * matching -- every pack family (affinity/spread/selector-spread/
 * preferred-affinity count tensors), PDB budget filtering, the disruption
 * controller, and the affinity queue wakeups all reduce to
 * labels_match_selector() over (pod labels, selector) pairs, O(pods x
 * rows) per batch. This module implements the match against a
 * PRE-COMPILED selector form (built once per selector object by
 * kubernetes_tpu/api/selectors.py):
 *
 *   compiled = (match_labels_dict,
 *               ((key, opcode, values_frozenset), ...))
 *   opcodes: 0=In 1=NotIn 2=Exists 3=DoesNotExist
 *
 * Exposed functions:
 *   match_compiled(labels_dict, compiled) -> bool
 *   match_mask(labels_list, compiled) -> bytes   (one byte per entry;
 *       the packers' inner loops over many pods per selector)
 *   dict_covers(labels_dict, selector_dict) -> bool  (plain map
 *       selectors: every kv present; empty selector -> False, matching
 *       label_selector_as_dict_matches)
 *
 * Python fallbacks with identical semantics live in api/selectors.py;
 * tests/test_native_selectors.py differentially fuzzes the two.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

static int
match_compiled_impl(PyObject *labels, PyObject *compiled)
{
    /* returns 1 match, 0 no match, -1 error */
    PyObject *ml = PyTuple_GET_ITEM(compiled, 0);   /* dict */
    PyObject *exprs = PyTuple_GET_ITEM(compiled, 1); /* tuple */

    PyObject *key, *value;
    Py_ssize_t pos = 0;
    while (PyDict_Next(ml, &pos, &key, &value)) {
        PyObject *got = PyDict_GetItemWithError(labels, key);
        if (got == NULL) {
            if (PyErr_Occurred())
                return -1;
            return 0;
        }
        int eq = PyObject_RichCompareBool(got, value, Py_EQ);
        if (eq < 0)
            return -1;
        if (!eq)
            return 0;
    }

    Py_ssize_t n = PyTuple_GET_SIZE(exprs);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *req = PyTuple_GET_ITEM(exprs, i);
        PyObject *rkey = PyTuple_GET_ITEM(req, 0);
        long op = PyLong_AsLong(PyTuple_GET_ITEM(req, 1));
        PyObject *values = PyTuple_GET_ITEM(req, 2);
        PyObject *got = PyDict_GetItemWithError(labels, rkey);
        if (got == NULL && PyErr_Occurred())
            return -1;
        int ok;
        switch (op) {
        case 0: /* In */
            if (got == NULL) {
                ok = 0;
            } else {
                ok = PySet_Contains(values, got);
                if (ok < 0)
                    return -1;
            }
            break;
        case 1: /* NotIn */
            if (got == NULL) {
                ok = 1;
            } else {
                int in = PySet_Contains(values, got);
                if (in < 0)
                    return -1;
                ok = !in;
            }
            break;
        case 2: /* Exists */
            ok = got != NULL;
            break;
        case 3: /* DoesNotExist */
            ok = got == NULL;
            break;
        default:
            /* opcode -1: an operator the compiler didn't recognize;
             * raised only when evaluation reaches it, matching the
             * Python path's short-circuit semantics */
            PyErr_SetString(PyExc_ValueError,
                            "unknown label selector operator");
            return -1;
        }
        if (!ok)
            return 0;
    }
    return 1;
}

static PyObject *
match_compiled(PyObject *self, PyObject *args)
{
    PyObject *labels, *compiled;
    if (!PyArg_ParseTuple(args, "O!O!", &PyDict_Type, &labels,
                          &PyTuple_Type, &compiled))
        return NULL;
    int r = match_compiled_impl(labels, compiled);
    if (r < 0)
        return NULL;
    if (r)
        Py_RETURN_TRUE;
    Py_RETURN_FALSE;
}

static PyObject *
match_mask(PyObject *self, PyObject *args)
{
    PyObject *labels_list, *compiled;
    if (!PyArg_ParseTuple(args, "O!O!", &PyList_Type, &labels_list,
                          &PyTuple_Type, &compiled))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(labels_list);
    PyObject *out = PyBytes_FromStringAndSize(NULL, n);
    if (out == NULL)
        return NULL;
    char *buf = PyBytes_AS_STRING(out);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *labels = PyList_GET_ITEM(labels_list, i);
        if (!PyDict_Check(labels)) {
            Py_DECREF(out);
            PyErr_SetString(PyExc_TypeError, "labels entries must be dicts");
            return NULL;
        }
        int r = match_compiled_impl(labels, compiled);
        if (r < 0) {
            Py_DECREF(out);
            return NULL;
        }
        buf[i] = (char)r;
    }
    return out;
}

static PyObject *
dict_covers(PyObject *self, PyObject *args)
{
    PyObject *labels, *selector;
    if (!PyArg_ParseTuple(args, "O!O!", &PyDict_Type, &labels,
                          &PyDict_Type, &selector))
        return NULL;
    if (PyDict_GET_SIZE(selector) == 0)
        Py_RETURN_FALSE; /* empty map selector matches nothing */
    PyObject *key, *value;
    Py_ssize_t pos = 0;
    while (PyDict_Next(selector, &pos, &key, &value)) {
        PyObject *got = PyDict_GetItemWithError(labels, key);
        if (got == NULL) {
            if (PyErr_Occurred())
                return NULL;
            Py_RETURN_FALSE;
        }
        int eq = PyObject_RichCompareBool(got, value, Py_EQ);
        if (eq < 0)
            return NULL;
        if (!eq)
            Py_RETURN_FALSE;
    }
    Py_RETURN_TRUE;
}

/* -- copy-on-write object clones (the commit-path hot loop) -------------
 *
 * The bulk bind/assume pipeline clones every pod 2-4 times per commit
 * (assumed_clone: pod+spec; _bind_locked: pod+metadata+spec+status).
 * copy.copy() routes each clone through __reduce_ex__/_reconstruct at
 * ~5-7us a call; at 10k pods x 6 clones that is ~0.4s of the measured
 * burst window. cow_clone() does the same thing the direct way: allocate
 * via the type (no __init__), dict-copy __dict__, and shallow-clone the
 * named nested attributes in the same call. Reference analogue: the Go
 * scheduler's pod.DeepCopy() before assume (scheduler.go:474) -- ours is
 * shallow because downstream only writes spec.node_name /
 * metadata.resource_version (the informer-cache read-only contract).
 */

static PyObject *str_dict = NULL; /* interned "__dict__" */

static PyObject *
shallow_clone_one(PyObject *obj)
{
    PyTypeObject *tp = Py_TYPE(obj);
    PyObject *new = tp->tp_alloc(tp, 0);
    if (new == NULL)
        return NULL;
    PyObject *d = PyObject_GetAttr(obj, str_dict);
    if (d == NULL) {
        Py_DECREF(new);
        return NULL;
    }
    PyObject *dc = PyDict_Copy(d);
    Py_DECREF(d);
    if (dc == NULL) {
        Py_DECREF(new);
        return NULL;
    }
    if (PyObject_SetAttr(new, str_dict, dc) < 0) {
        Py_DECREF(dc);
        Py_DECREF(new);
        return NULL;
    }
    Py_DECREF(dc);
    return new;
}

static PyObject *
cow_clone(PyObject *self, PyObject *args)
{
    /* cow_clone(obj, ("spec", "status", ...)) -> clone
     * Shallow-clones obj, then shallow-clones each named attribute on
     * the clone so the caller may mutate those sub-objects freely. */
    PyObject *obj, *attrs;
    if (!PyArg_ParseTuple(args, "OO!", &obj, &PyTuple_Type, &attrs))
        return NULL;
    PyObject *new = shallow_clone_one(obj);
    if (new == NULL)
        return NULL;
    Py_ssize_t n = PyTuple_GET_SIZE(attrs);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *name = PyTuple_GET_ITEM(attrs, i);
        PyObject *sub = PyObject_GetAttr(obj, name);
        if (sub == NULL)
            goto fail;
        PyObject *subc = shallow_clone_one(sub);
        Py_DECREF(sub);
        if (subc == NULL)
            goto fail;
        int r = PyObject_SetAttr(new, name, subc);
        Py_DECREF(subc);
        if (r < 0)
            goto fail;
    }
    return new;
fail:
    Py_DECREF(new);
    return NULL;
}

static PyMethodDef methods[] = {
    {"match_compiled", match_compiled, METH_VARARGS,
     "match_compiled(labels, compiled) -> bool"},
    {"match_mask", match_mask, METH_VARARGS,
     "match_mask(labels_list, compiled) -> bytes"},
    {"dict_covers", dict_covers, METH_VARARGS,
     "dict_covers(labels, selector_dict) -> bool"},
    {"cow_clone", cow_clone, METH_VARARGS,
     "cow_clone(obj, attr_names) -> shallow clone with named attrs "
     "also shallow-cloned"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_hotpath",
    "native label-selector matching (SURVEY section 2.4 host data plane)",
    -1, methods,
};

PyMODINIT_FUNC
PyInit__hotpath(void)
{
    str_dict = PyUnicode_InternFromString("__dict__");
    if (str_dict == NULL)
        return NULL;
    return PyModule_Create(&moduledef);
}
