/* Native host data plane: the hot label-selector matcher.
 *
 * SURVEY.md section 2.4: the reference has no native scheduling code (all
 * Go); the native components owed here are the NEW performance core. On
 * the host side the single hottest string operation is label-selector
 * matching -- every pack family (affinity/spread/selector-spread/
 * preferred-affinity count tensors), PDB budget filtering, the disruption
 * controller, and the affinity queue wakeups all reduce to
 * labels_match_selector() over (pod labels, selector) pairs, O(pods x
 * rows) per batch. This module implements the match against a
 * PRE-COMPILED selector form (built once per selector object by
 * kubernetes_tpu/api/selectors.py):
 *
 *   compiled = (match_labels_dict,
 *               ((key, opcode, values_frozenset), ...))
 *   opcodes: 0=In 1=NotIn 2=Exists 3=DoesNotExist
 *
 * Exposed functions:
 *   match_compiled(labels_dict, compiled) -> bool
 *   match_mask(labels_list, compiled) -> bytes   (one byte per entry;
 *       the packers' inner loops over many pods per selector)
 *   dict_covers(labels_dict, selector_dict) -> bool  (plain map
 *       selectors: every kv present; empty selector -> False, matching
 *       label_selector_as_dict_matches)
 *
 * Python fallbacks with identical semantics live in api/selectors.py;
 * tests/test_native_selectors.py differentially fuzzes the two.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>

static int
match_compiled_impl(PyObject *labels, PyObject *compiled)
{
    /* returns 1 match, 0 no match, -1 error */
    PyObject *ml = PyTuple_GET_ITEM(compiled, 0);   /* dict */
    PyObject *exprs = PyTuple_GET_ITEM(compiled, 1); /* tuple */

    PyObject *key, *value;
    Py_ssize_t pos = 0;
    while (PyDict_Next(ml, &pos, &key, &value)) {
        PyObject *got = PyDict_GetItemWithError(labels, key);
        if (got == NULL) {
            if (PyErr_Occurred())
                return -1;
            return 0;
        }
        int eq = PyObject_RichCompareBool(got, value, Py_EQ);
        if (eq < 0)
            return -1;
        if (!eq)
            return 0;
    }

    Py_ssize_t n = PyTuple_GET_SIZE(exprs);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *req = PyTuple_GET_ITEM(exprs, i);
        PyObject *rkey = PyTuple_GET_ITEM(req, 0);
        long op = PyLong_AsLong(PyTuple_GET_ITEM(req, 1));
        PyObject *values = PyTuple_GET_ITEM(req, 2);
        PyObject *got = PyDict_GetItemWithError(labels, rkey);
        if (got == NULL && PyErr_Occurred())
            return -1;
        int ok;
        switch (op) {
        case 0: /* In */
            if (got == NULL) {
                ok = 0;
            } else {
                ok = PySet_Contains(values, got);
                if (ok < 0)
                    return -1;
            }
            break;
        case 1: /* NotIn */
            if (got == NULL) {
                ok = 1;
            } else {
                int in = PySet_Contains(values, got);
                if (in < 0)
                    return -1;
                ok = !in;
            }
            break;
        case 2: /* Exists */
            ok = got != NULL;
            break;
        case 3: /* DoesNotExist */
            ok = got == NULL;
            break;
        default:
            /* opcode -1: an operator the compiler didn't recognize;
             * raised only when evaluation reaches it, matching the
             * Python path's short-circuit semantics */
            PyErr_SetString(PyExc_ValueError,
                            "unknown label selector operator");
            return -1;
        }
        if (!ok)
            return 0;
    }
    return 1;
}

static PyObject *
match_compiled(PyObject *self, PyObject *args)
{
    PyObject *labels, *compiled;
    if (!PyArg_ParseTuple(args, "O!O!", &PyDict_Type, &labels,
                          &PyTuple_Type, &compiled))
        return NULL;
    int r = match_compiled_impl(labels, compiled);
    if (r < 0)
        return NULL;
    if (r)
        Py_RETURN_TRUE;
    Py_RETURN_FALSE;
}

static PyObject *
match_mask(PyObject *self, PyObject *args)
{
    PyObject *labels_list, *compiled;
    if (!PyArg_ParseTuple(args, "O!O!", &PyList_Type, &labels_list,
                          &PyTuple_Type, &compiled))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(labels_list);
    PyObject *out = PyBytes_FromStringAndSize(NULL, n);
    if (out == NULL)
        return NULL;
    char *buf = PyBytes_AS_STRING(out);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *labels = PyList_GET_ITEM(labels_list, i);
        if (!PyDict_Check(labels)) {
            Py_DECREF(out);
            PyErr_SetString(PyExc_TypeError, "labels entries must be dicts");
            return NULL;
        }
        int r = match_compiled_impl(labels, compiled);
        if (r < 0) {
            Py_DECREF(out);
            return NULL;
        }
        buf[i] = (char)r;
    }
    return out;
}

static PyObject *
dict_covers(PyObject *self, PyObject *args)
{
    PyObject *labels, *selector;
    if (!PyArg_ParseTuple(args, "O!O!", &PyDict_Type, &labels,
                          &PyDict_Type, &selector))
        return NULL;
    if (PyDict_GET_SIZE(selector) == 0)
        Py_RETURN_FALSE; /* empty map selector matches nothing */
    PyObject *key, *value;
    Py_ssize_t pos = 0;
    while (PyDict_Next(selector, &pos, &key, &value)) {
        PyObject *got = PyDict_GetItemWithError(labels, key);
        if (got == NULL) {
            if (PyErr_Occurred())
                return NULL;
            Py_RETURN_FALSE;
        }
        int eq = PyObject_RichCompareBool(got, value, Py_EQ);
        if (eq < 0)
            return NULL;
        if (!eq)
            Py_RETURN_FALSE;
    }
    Py_RETURN_TRUE;
}

/* -- copy-on-write object clones (the commit-path hot loop) -------------
 *
 * The bulk bind/assume pipeline clones every pod 2-4 times per commit
 * (assumed_clone: pod+spec; _bind_locked: pod+metadata+spec+status).
 * copy.copy() routes each clone through __reduce_ex__/_reconstruct at
 * ~5-7us a call; at 10k pods x 6 clones that is ~0.4s of the measured
 * burst window. cow_clone() does the same thing the direct way: allocate
 * via the type (no __init__), dict-copy __dict__, and shallow-clone the
 * named nested attributes in the same call. Reference analogue: the Go
 * scheduler's pod.DeepCopy() before assume (scheduler.go:474) -- ours is
 * shallow because downstream only writes spec.node_name /
 * metadata.resource_version (the informer-cache read-only contract).
 */

static PyObject *str_dict = NULL; /* interned "__dict__" */

static PyObject *
shallow_clone_one(PyObject *obj)
{
    PyTypeObject *tp = Py_TYPE(obj);
    PyObject *new = tp->tp_alloc(tp, 0);
    if (new == NULL)
        return NULL;
    PyObject *d = PyObject_GetAttr(obj, str_dict);
    if (d == NULL) {
        Py_DECREF(new);
        return NULL;
    }
    PyObject *dc = PyDict_Copy(d);
    Py_DECREF(d);
    if (dc == NULL) {
        Py_DECREF(new);
        return NULL;
    }
    if (PyObject_SetAttr(new, str_dict, dc) < 0) {
        Py_DECREF(dc);
        Py_DECREF(new);
        return NULL;
    }
    Py_DECREF(dc);
    return new;
}

static PyObject *
cow_clone(PyObject *self, PyObject *args)
{
    /* cow_clone(obj, ("spec", "status", ...)) -> clone
     * Shallow-clones obj, then shallow-clones each named attribute on
     * the clone so the caller may mutate those sub-objects freely. */
    PyObject *obj, *attrs;
    if (!PyArg_ParseTuple(args, "OO!", &obj, &PyTuple_Type, &attrs))
        return NULL;
    PyObject *new = shallow_clone_one(obj);
    if (new == NULL)
        return NULL;
    Py_ssize_t n = PyTuple_GET_SIZE(attrs);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *name = PyTuple_GET_ITEM(attrs, i);
        PyObject *sub = PyObject_GetAttr(obj, name);
        if (sub == NULL)
            goto fail;
        PyObject *subc = shallow_clone_one(sub);
        Py_DECREF(sub);
        if (subc == NULL)
            goto fail;
        int r = PyObject_SetAttr(new, name, subc);
        Py_DECREF(subc);
        if (r < 0)
            goto fail;
    }
    return new;
fail:
    Py_DECREF(new);
    return NULL;
}

/* -- bulk commit spine ---------------------------------------------------
 *
 * The 10k-burst commit window spends most of its host budget in two
 * per-pod loops: (a) assumed_clone + spec.node_name per committed pod
 * (batch.py commit.clone) and (b) the apiserver bind transaction
 * (server.py bind_bulk: lookup, uid/bound checks, cow clone, rv bump,
 * store write, watch-event build). Both are pure object-graph work with
 * no Python-level semantics beyond dict/attr ops, so they live here as
 * single C loops: assume_clones() and bind_assumed_bulk(). The Python
 * fallbacks (api/types.py assumed_clone, server.py _bind_locked) carry
 * the same semantics; tests/test_native_commit.py differentially
 * exercises native vs fallback on the same inputs.
 */

static PyObject *str_spec = NULL;
static PyObject *str_node_name = NULL;
static PyObject *str_metadata = NULL;
static PyObject *str_namespace = NULL;
static PyObject *str_name = NULL;
static PyObject *str_uid = NULL;
static PyObject *str_resource_version = NULL;
static PyObject *str_sig_memo = NULL;
static PyObject *str_modified = NULL;

/* Install dict `dc` (reference stolen) as `obj`'s instance dict via the
 * dict pointer when the layout allows it, else through the __dict__
 * descriptor. Returns 0 ok / -1 error (dc released either way). */
static int
install_dict(PyObject *obj, PyObject *dc)
{
    PyObject **dp = _PyObject_GetDictPtr(obj);
    if (dp != NULL) {
        Py_XSETREF(*dp, dc);
        return 0;
    }
    int r = PyObject_SetAttr(obj, str_dict, dc);
    Py_DECREF(dc);
    return r;
}

/* Shallow-clone obj by dict copy; optionally override one key in (and/or
 * drop one key from) the copied dict before installing it. */
static PyObject *
clone_with_dict(PyObject *obj, PyObject *override_key, PyObject *override_val,
                PyObject *drop_key)
{
    PyTypeObject *tp = Py_TYPE(obj);
    PyObject *new = tp->tp_alloc(tp, 0);
    if (new == NULL)
        return NULL;
    PyObject *d = PyObject_GetAttr(obj, str_dict);
    if (d == NULL) {
        Py_DECREF(new);
        return NULL;
    }
    PyObject *dc = PyDict_Copy(d);
    Py_DECREF(d);
    if (dc == NULL) {
        Py_DECREF(new);
        return NULL;
    }
    if (override_key != NULL &&
        PyDict_SetItem(dc, override_key, override_val) < 0) {
        Py_DECREF(dc);
        Py_DECREF(new);
        return NULL;
    }
    if (drop_key != NULL && PyDict_Contains(dc, drop_key) == 1 &&
        PyDict_DelItem(dc, drop_key) < 0) {
        Py_DECREF(dc);
        Py_DECREF(new);
        return NULL;
    }
    if (install_dict(new, dc) < 0) {
        Py_DECREF(new);
        return NULL;
    }
    return new;
}

static PyObject *
assume_clones(PyObject *self, PyObject *args)
{
    /* assume_clones(pods, hosts) -> [clone] where clone = shallow pod
     * with shallow spec and spec.node_name = host (the one-call form of
     * Pod.assumed_clone() + node_name assignment per committed pod). */
    PyObject *pods, *hosts;
    if (!PyArg_ParseTuple(args, "O!O!", &PyList_Type, &pods,
                          &PyList_Type, &hosts))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(pods);
    if (PyList_GET_SIZE(hosts) != n) {
        PyErr_SetString(PyExc_ValueError, "pods/hosts length mismatch");
        return NULL;
    }
    PyObject *out = PyList_New(n);
    if (out == NULL)
        return NULL;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *pod = PyList_GET_ITEM(pods, i);
        PyObject *host = PyList_GET_ITEM(hosts, i);
        PyObject *spec = PyObject_GetAttr(pod, str_spec);
        if (spec == NULL)
            goto fail;
        PyObject *specc = clone_with_dict(spec, str_node_name, host, NULL);
        Py_DECREF(spec);
        if (specc == NULL)
            goto fail;
        PyObject *podc = clone_with_dict(pod, str_spec, specc, NULL);
        Py_DECREF(specc);
        if (podc == NULL)
            goto fail;
        PyList_SET_ITEM(out, i, podc);
    }
    return out;
fail:
    Py_DECREF(out);
    return NULL;
}

static PyObject *str_pod = NULL;

static PyObject *
commit_gather(PyObject *self, PyObject *args)
{
    /* commit_gather(solver_infos, order, assignments, names)
     *   -> (pod_infos, clones, hosts)
     *
     * One C pass over a solved batch's PLACED slots (the committer
     * splits NO_NODE slots off with numpy before calling): slot j
     * gathers pod_info = solver_infos[order[j]], resolves
     * host = names[assignments[j]], and builds the assumed clone
     * (shallow pod + shallow spec with spec.node_name = host) in the
     * same step -- fusing the commit loop's gather with the
     * assume_clones pass so the per-pod Python work of the bulk commit
     * is three parallel C-built lists. order/assignments are plain int
     * lists (numpy .tolist() output); semantics match the Python
     * fallback in scheduler/batch.py (_commit_gather_py),
     * differentially tested in tests/test_native_commit.py. */
    PyObject *infos, *order, *assigns, *names;
    if (!PyArg_ParseTuple(args, "O!O!O!O!", &PyList_Type, &infos,
                          &PyList_Type, &order, &PyList_Type, &assigns,
                          &PyList_Type, &names))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(order);
    if (PyList_GET_SIZE(assigns) != n) {
        PyErr_SetString(PyExc_ValueError, "order/assignments length mismatch");
        return NULL;
    }
    Py_ssize_t n_infos = PyList_GET_SIZE(infos);
    Py_ssize_t n_names = PyList_GET_SIZE(names);
    PyObject *pis = PyList_New(n);
    PyObject *clones = PyList_New(n);
    PyObject *hosts = PyList_New(n);
    if (pis == NULL || clones == NULL || hosts == NULL)
        goto fail;
    for (Py_ssize_t j = 0; j < n; j++) {
        long oi = PyLong_AsLong(PyList_GET_ITEM(order, j));
        long ci = PyLong_AsLong(PyList_GET_ITEM(assigns, j));
        if ((oi == -1 || ci == -1) && PyErr_Occurred())
            goto fail;
        if (oi < 0 || oi >= n_infos || ci < 0 || ci >= n_names) {
            PyErr_SetString(PyExc_IndexError,
                            "commit_gather index out of range");
            goto fail;
        }
        PyObject *pi = PyList_GET_ITEM(infos, oi);
        PyObject *host = PyList_GET_ITEM(names, ci);
        PyObject *pod = PyObject_GetAttr(pi, str_pod);
        if (pod == NULL)
            goto fail;
        PyObject *spec = PyObject_GetAttr(pod, str_spec);
        if (spec == NULL) {
            Py_DECREF(pod);
            goto fail;
        }
        PyObject *specc = clone_with_dict(spec, str_node_name, host, NULL);
        Py_DECREF(spec);
        if (specc == NULL) {
            Py_DECREF(pod);
            goto fail;
        }
        PyObject *podc = clone_with_dict(pod, str_spec, specc, NULL);
        Py_DECREF(specc);
        Py_DECREF(pod);
        if (podc == NULL)
            goto fail;
        Py_INCREF(pi);
        PyList_SET_ITEM(pis, j, pi);
        PyList_SET_ITEM(clones, j, podc);
        Py_INCREF(host);
        PyList_SET_ITEM(hosts, j, host);
    }
    return Py_BuildValue("(NNN)", pis, clones, hosts);
fail:
    Py_XDECREF(pis);
    Py_XDECREF(clones);
    Py_XDECREF(hosts);
    return NULL;
}

static PyObject *
bind_assumed_bulk(PyObject *self, PyObject *args)
{
    /* bind_assumed_bulk(store, assumed_list, rv, event_cls)
     *   -> (errors, events, new_rv)
     *
     * One C pass over the whole bulk-bind transaction (caller holds the
     * store lock). Per slot, semantics match server._bind_locked: lookup
     * by (namespace, name), uid check, already-bound check, target
     * check, copy-on-write clone of the STORED pod (metadata+spec;
     * status stays shared -- see inline note) with spec.node_name set,
     * _sig_memo dropped, resource_version assigned sequentially from
     * rv+1. errors = [(index, code, msg)] with code 0=NotFound
     * 1=Conflict 2=ValueError 3=internal; events = [event_cls(MODIFIED,
     * pod, rv)] for the successes, in store order. Per-slot failures
     * (including unexpected ones) never abort the slots already
     * committed. Differential parity with the Python fallback:
     * tests/test_native_commit.py. */
    PyObject *store, *assumed_list, *event_cls;
    long rv;
    if (!PyArg_ParseTuple(args, "O!O!lO", &PyDict_Type, &store,
                          &PyList_Type, &assumed_list, &rv, &event_cls))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(assumed_list);
    PyObject *errors = PyList_New(0);
    PyObject *events = PyList_New(0);
    if (errors == NULL || events == NULL) {
        Py_XDECREF(errors);
        Py_XDECREF(events);
        return NULL;
    }

    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *assumed = PyList_GET_ITEM(assumed_list, i);
        PyObject *meta = NULL, *ns = NULL, *name = NULL, *uid = NULL;
        PyObject *spec = NULL, *target = NULL, *key = NULL;
        int errcode = -1;
        int rv_bumped = 0;
        const char *errfmt = NULL;

        meta = PyObject_GetAttr(assumed, str_metadata);
        if (meta == NULL)
            goto hard_fail;
        ns = PyObject_GetAttr(meta, str_namespace);
        name = PyObject_GetAttr(meta, str_name);
        uid = PyObject_GetAttr(meta, str_uid);
        Py_DECREF(meta);
        if (ns == NULL || name == NULL || uid == NULL)
            goto hard_fail;
        spec = PyObject_GetAttr(assumed, str_spec);
        if (spec == NULL)
            goto hard_fail;
        target = PyObject_GetAttr(spec, str_node_name);
        Py_DECREF(spec);
        if (target == NULL)
            goto hard_fail;

        key = PyTuple_Pack(2, ns, name);
        if (key == NULL)
            goto hard_fail;
        PyObject *old = PyDict_GetItemWithError(store, key); /* borrowed */
        if (old == NULL) {
            if (PyErr_Occurred())
                goto hard_fail;
            errcode = 0;
            errfmt = "Pod %U/%U not found";
            goto slot_error;
        }

        PyObject *old_meta = PyObject_GetAttr(old, str_metadata);
        if (old_meta == NULL)
            goto hard_fail;
        PyObject *old_uid = PyObject_GetAttr(old_meta, str_uid);
        if (old_uid == NULL) {
            Py_DECREF(old_meta);
            goto hard_fail;
        }
        int uid_true = PyObject_IsTrue(uid);
        if (uid_true > 0) {
            int eq = PyObject_RichCompareBool(old_uid, uid, Py_EQ);
            if (eq < 0) {
                Py_DECREF(old_uid);
                Py_DECREF(old_meta);
                goto hard_fail;
            }
            if (!eq) {
                Py_DECREF(old_uid);
                Py_DECREF(old_meta);
                errcode = 1;
                errfmt = "pod %U/%U uid mismatch";
                goto slot_error;
            }
        } else if (uid_true < 0) {
            Py_DECREF(old_uid);
            Py_DECREF(old_meta);
            goto hard_fail;
        }
        Py_DECREF(old_uid);

        PyObject *old_spec = PyObject_GetAttr(old, str_spec);
        if (old_spec == NULL) {
            Py_DECREF(old_meta);
            goto hard_fail;
        }
        PyObject *old_nn = PyObject_GetAttr(old_spec, str_node_name);
        if (old_nn == NULL) {
            Py_DECREF(old_spec);
            Py_DECREF(old_meta);
            goto hard_fail;
        }
        int bound = PyObject_IsTrue(old_nn);
        if (bound > 0) {
            int same = PyObject_RichCompareBool(old_nn, target, Py_EQ);
            if (same < 0) {
                Py_DECREF(old_nn);
                Py_DECREF(old_spec);
                Py_DECREF(old_meta);
                goto hard_fail;
            }
            if (!same) {
                Py_DECREF(old_nn);
                Py_DECREF(old_spec);
                Py_DECREF(old_meta);
                errcode = 1;
                errfmt = "pod %U/%U is already bound";
                goto slot_error;
            }
            /* already bound to the SAME node: idempotent success (a
             * retried commit whose first attempt landed, or a restarted
             * scheduler re-driving a recovered placement) -- the store
             * already holds exactly the requested state, so no write,
             * no rv bump, no event (parity: _bind_locked changed=False) */
            Py_DECREF(old_nn);
            Py_DECREF(old_spec);
            Py_DECREF(old_meta);
            Py_DECREF(key);
            Py_DECREF(ns);
            Py_DECREF(name);
            Py_DECREF(uid);
            Py_DECREF(target);
            continue;
        } else if (bound < 0) {
            Py_DECREF(old_nn);
            Py_DECREF(old_spec);
            Py_DECREF(old_meta);
            goto hard_fail;
        }
        Py_DECREF(old_nn);

        /* target required -- checked LAST, matching _bind_locked's
         * check order (uid, already-bound, then target) */
        int target_true = PyObject_IsTrue(target);
        if (target_true < 0) {
            Py_DECREF(old_spec);
            Py_DECREF(old_meta);
            goto hard_fail;
        }
        if (!target_true) {
            Py_DECREF(old_spec);
            Py_DECREF(old_meta);
            errcode = 2;
            errfmt = "binding for %U/%U has no target node";
            goto slot_error;
        }

        /* success: COW clone of the stored pod */
        rv += 1;
        rv_bumped = 1;
        PyObject *rv_obj = PyLong_FromLong(rv);
        if (rv_obj == NULL) {
            Py_DECREF(old_spec);
            Py_DECREF(old_meta);
            goto hard_fail;
        }
        /* status stays SHARED between old and new: every status write
         * goes through guaranteed_update/update_pod_status, which clone
         * status themselves before mutating (the informer read-only
         * contract makes the shared reference safe). */
        PyObject *metac =
            clone_with_dict(old_meta, str_resource_version, rv_obj, NULL);
        Py_DECREF(old_meta);
        PyObject *specc =
            clone_with_dict(old_spec, str_node_name, target, NULL);
        Py_DECREF(old_spec);
        if (metac == NULL || specc == NULL) {
            Py_XDECREF(metac);
            Py_XDECREF(specc);
            Py_DECREF(rv_obj);
            goto hard_fail;
        }

        PyTypeObject *tp = Py_TYPE(old);
        PyObject *podc = tp->tp_alloc(tp, 0);
        PyObject *d = podc ? PyObject_GetAttr(old, str_dict) : NULL;
        PyObject *dc = d ? PyDict_Copy(d) : NULL;
        Py_XDECREF(d);
        int ok = podc != NULL && dc != NULL &&
                 PyDict_SetItem(dc, str_metadata, metac) == 0 &&
                 PyDict_SetItem(dc, str_spec, specc) == 0;
        if (ok && PyDict_Contains(dc, str_sig_memo) == 1)
            ok = PyDict_DelItem(dc, str_sig_memo) == 0;
        if (ok) {
            ok = install_dict(podc, dc) == 0;
            dc = NULL; /* reference consumed by install_dict */
        }
        Py_XDECREF(dc);
        Py_DECREF(metac);
        Py_DECREF(specc);
        if (!ok) {
            Py_XDECREF(podc);
            Py_DECREF(rv_obj);
            goto hard_fail;
        }
        /* event BEFORE the store write: a failure here leaves the slot
         * (and the store) untouched, so the transaction stays
         * event-consistent per slot */
        PyObject *event = PyObject_CallFunctionObjArgs(
            event_cls, str_modified, podc, rv_obj, NULL);
        Py_DECREF(rv_obj);
        if (event == NULL) {
            Py_DECREF(podc);
            goto hard_fail;
        }
        Py_INCREF(old); /* keep alive across the store replace for rollback */
        if (PyDict_SetItem(store, key, podc) < 0) {
            Py_DECREF(old);
            Py_DECREF(podc);
            Py_DECREF(event);
            goto hard_fail;
        }
        int ap = PyList_Append(events, event);
        Py_DECREF(event);
        if (ap < 0) {
            /* roll the slot back so store and events stay consistent */
            if (PyDict_SetItem(store, key, old) < 0)
                PyErr_Clear();
            Py_DECREF(old);
            Py_DECREF(podc);
            goto hard_fail;
        }
        Py_DECREF(old);
        Py_DECREF(podc);
        Py_DECREF(key);
        Py_DECREF(ns);
        Py_DECREF(name);
        Py_DECREF(uid);
        Py_DECREF(target);
        continue;

    slot_error: {
        PyObject *msg = PyUnicode_FromFormat(errfmt, ns, name);
        PyObject *slot =
            msg ? Py_BuildValue("(niN)", i, errcode, msg) : NULL;
        Py_XDECREF(key);
        Py_DECREF(ns);
        Py_DECREF(name);
        Py_DECREF(uid);
        Py_DECREF(target);
        if (slot == NULL)
            goto abort_fail;
        int ap = PyList_Append(errors, slot);
        Py_DECREF(slot);
        if (ap < 0)
            goto abort_fail;
        continue;
    }

    hard_fail: {
        /* An unexpected per-slot failure (allocation, broken attribute)
         * must NOT abort the transaction: earlier slots already mutated
         * the store and their watch events/rv advance must still reach
         * the caller. Convert to a slot error (code 3) and continue;
         * the failed slot itself left the store untouched -- including
         * its provisional rv, matching the Python path where _next_rv
         * only runs after validation. */
        if (rv_bumped)
            rv -= 1;
        Py_XDECREF(key);
        Py_XDECREF(ns);
        Py_XDECREF(name);
        Py_XDECREF(uid);
        Py_XDECREF(target);
        PyObject *et = NULL, *ev = NULL, *tb = NULL;
        PyErr_Fetch(&et, &ev, &tb);
        PyObject *msg = NULL;
        if (ev != NULL)
            msg = PyObject_Str(ev);
        else if (et != NULL)
            msg = PyObject_Str(et);
        else
            msg = PyUnicode_FromString("internal bind error");
        Py_XDECREF(et);
        Py_XDECREF(ev);
        Py_XDECREF(tb);
        if (msg == NULL)
            goto abort_fail;
        PyObject *slot = Py_BuildValue("(niN)", i, 3, msg);
        if (slot == NULL)
            goto abort_fail;
        int ap = PyList_Append(errors, slot);
        Py_DECREF(slot);
        if (ap < 0)
            goto abort_fail;
        continue;
    }

    abort_fail:
        /* only reachable when even recording the error fails (OOM on
         * OOM); nothing sensible left to report */
        PyErr_Clear();
        PyErr_SetString(PyExc_MemoryError,
                        "bind_assumed_bulk: cannot record slot error");
        Py_DECREF(errors);
        Py_DECREF(events);
        return NULL;
    }
    return Py_BuildValue("(NNl)", errors, events, rv);
}

/* -- ingest spine --------------------------------------------------------
 *
 * The host-side control-plane FRONT END (watch frame -> informer store ->
 * admission memo -> queue entry -> pack row) walked Python objects per
 * event per informer set and per pod per pack cycle; after the device-
 * side delta/carry work the solver outran its input (ROADMAP item 5).
 * These loops move that walking into C, in three layers:
 *
 *   ingest_decode / ingest_apply -- watch frames are decoded ONCE per
 *     apiserver transaction into an immutable (namespace, name) key
 *     record memoized on the WatchEvent (`decoded` slot); every informer
 *     cursor (N partitioned stacks share the per-kind event log) applies
 *     the frame to its store and builds the handler dispatch list in one
 *     C pass over those shared records.
 *
 *   ingest_stamp -- the admission classifier's fast path: a PLAIN pod
 *     (no volumes, no affinity, no spread, no NUMA annotation, no gang
 *     label, no host ports, no unresolved priority class) gets its
 *     entire ingest record built in one C pass: _req_memo, _nzr_memo,
 *     _hot_memo, the pack-ready _packrow, _band_priority, and the
 *     SHARED plain Admission record. Non-plain pods are returned by
 *     index for the full Python classifier.
 *
 *   pack_gather -- pack_pod_batch's per-pod-per-cycle spec walk becomes
 *     a C gather over the _packrow memos into preallocated int32
 *     buffers, deduping request rows through a caller-owned dict (only
 *     DISTINCT rows go back to Python for schema encoding).
 *
 *   queue_shape -- the bulk apiserver->queue path: one C pass over a
 *     create burst's pods producing (keys, priorities, nominations) so
 *     PriorityQueue.add_many builds its heap entries without per-pod
 *     attribute walks.
 *
 * Pure-Python twins with identical semantics live next to each call
 * site (client/informer.py, scheduler/admission.py,
 * tensors/node_tensor.py, queue/scheduling_queue.py), selected by
 * KTPU_NATIVE_INGEST=0; tests/test_native_ingest.py differentially
 * fuzzes the two.
 */

static PyObject *str_obj_attr = NULL;      /* "object" */
static PyObject *str_type_attr = NULL;     /* "type" */
static PyObject *str_decoded = NULL;
static PyObject *str_added = NULL;         /* "ADDED" */
static PyObject *str_deleted = NULL;       /* "DELETED" */
static PyObject *str_status = NULL;
static PyObject *str_nominated = NULL;     /* "nominated_node_name" */
static PyObject *str_priority = NULL;
static PyObject *str_priority_class = NULL;
static PyObject *str_annotations = NULL;
static PyObject *str_labels = NULL;
static PyObject *str_volumes = NULL;
static PyObject *str_affinity = NULL;
static PyObject *str_spread = NULL;        /* "topology_spread_constraints" */
static PyObject *str_containers = NULL;
static PyObject *str_init_containers = NULL;
static PyObject *str_overhead = NULL;
static PyObject *str_resources = NULL;
static PyObject *str_requests = NULL;
static PyObject *str_ports = NULL;
static PyObject *str_host_port = NULL;
static PyObject *str_packrow = NULL;       /* "_packrow" */
static PyObject *str_band_priority = NULL; /* "_band_priority" */
static PyObject *str_admission = NULL;     /* "_admission" */
static PyObject *str_req_memo = NULL;
static PyObject *str_nzr_memo = NULL;
static PyObject *str_hot_memo = NULL;

/* Decode one WatchEvent into its shared (namespace, name) key record,
 * memoized on ev.decoded. Returns a NEW reference. */
static PyObject *
decode_event_key(PyObject *ev)
{
    PyObject *dec = PyObject_GetAttr(ev, str_decoded);
    if (dec == NULL)
        return NULL;
    if (dec != Py_None)
        return dec;
    Py_DECREF(dec);
    PyObject *obj = PyObject_GetAttr(ev, str_obj_attr);
    if (obj == NULL)
        return NULL;
    PyObject *meta = PyObject_GetAttr(obj, str_metadata);
    Py_DECREF(obj);
    if (meta == NULL)
        return NULL;
    PyObject *ns = PyObject_GetAttr(meta, str_namespace);
    PyObject *name = PyObject_GetAttr(meta, str_name);
    Py_DECREF(meta);
    if (ns == NULL || name == NULL) {
        Py_XDECREF(ns);
        Py_XDECREF(name);
        return NULL;
    }
    PyObject *key = PyTuple_Pack(2, ns, name);
    Py_DECREF(ns);
    Py_DECREF(name);
    if (key == NULL)
        return NULL;
    if (PyObject_SetAttr(ev, str_decoded, key) < 0) {
        Py_DECREF(key);
        return NULL;
    }
    return key;
}

static PyObject *
ingest_decode(PyObject *self, PyObject *args)
{
    /* ingest_decode(events) -> [key]: decode (and memoize) every
     * event's key record in one pass; later consumers -- including
     * sibling informer sets draining the same shared log -- read the
     * memo instead of re-walking obj.metadata. */
    PyObject *events;
    if (!PyArg_ParseTuple(args, "O!", &PyList_Type, &events))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(events);
    PyObject *out = PyList_New(n);
    if (out == NULL)
        return NULL;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *key = decode_event_key(PyList_GET_ITEM(events, i));
        if (key == NULL) {
            Py_DECREF(out);
            return NULL;
        }
        PyList_SET_ITEM(out, i, key);
    }
    return out;
}

static int
ev_type_is(PyObject *t, PyObject *interned)
{
    /* identity first (the constants flow from one module), value
     * compare as the fallback; -1 on error */
    if (t == interned)
        return 1;
    return PyObject_RichCompareBool(t, interned, Py_EQ);
}

static PyObject *
ingest_apply(PyObject *self, PyObject *args)
{
    /* ingest_apply(store, events) -> [(etype, old, new)]
     *
     * The informer's per-frame store update + dispatch build in one C
     * pass (semantics: client/informer.py _apply_batch_py, the
     * differential twin). Caller holds the informer store lock. Events
     * with an unknown type are skipped, matching the Python branch
     * structure. */
    PyObject *store, *events;
    if (!PyArg_ParseTuple(args, "O!O!", &PyDict_Type, &store,
                          &PyList_Type, &events))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(events);
    PyObject *dispatch = PyList_New(0);
    if (dispatch == NULL)
        return NULL;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *ev = PyList_GET_ITEM(events, i);
        PyObject *key = decode_event_key(ev);
        if (key == NULL)
            goto fail;
        PyObject *obj = PyObject_GetAttr(ev, str_obj_attr);
        PyObject *t = obj ? PyObject_GetAttr(ev, str_type_attr) : NULL;
        if (obj == NULL || t == NULL) {
            Py_XDECREF(obj);
            Py_XDECREF(t);
            Py_DECREF(key);
            goto fail;
        }
        PyObject *slot = NULL;
        int r = ev_type_is(t, str_added);
        if (r < 0)
            goto ev_fail;
        if (r) {
            if (PyDict_SetItem(store, key, obj) < 0)
                goto ev_fail;
            slot = PyTuple_Pack(3, t, Py_None, obj);
        } else if ((r = ev_type_is(t, str_modified)) != 0) {
            if (r < 0)
                goto ev_fail;
            PyObject *old = PyDict_GetItemWithError(store, key);
            if (old == NULL && PyErr_Occurred())
                goto ev_fail;
            Py_XINCREF(old);
            if (PyDict_SetItem(store, key, obj) < 0) {
                Py_XDECREF(old);
                goto ev_fail;
            }
            slot = PyTuple_Pack(3, t, old ? old : Py_None, obj);
            Py_XDECREF(old);
        } else if ((r = ev_type_is(t, str_deleted)) != 0) {
            if (r < 0)
                goto ev_fail;
            PyObject *old = PyDict_GetItemWithError(store, key);
            if (old == NULL && PyErr_Occurred())
                goto ev_fail;
            if (old != NULL && PyDict_DelItem(store, key) < 0)
                goto ev_fail;
            slot = PyTuple_Pack(3, t, Py_None, obj);
        } else {
            /* unknown event type: no dispatch, no store change */
            Py_DECREF(obj);
            Py_DECREF(t);
            Py_DECREF(key);
            continue;
        }
        Py_DECREF(obj);
        Py_DECREF(t);
        Py_DECREF(key);
        if (slot == NULL)
            goto fail;
        if (PyList_Append(dispatch, slot) < 0) {
            Py_DECREF(slot);
            goto fail;
        }
        Py_DECREF(slot);
        continue;
    ev_fail:
        Py_DECREF(obj);
        Py_DECREF(t);
        Py_DECREF(key);
        goto fail;
    }
    return dispatch;
fail:
    Py_DECREF(dispatch);
    return NULL;
}

/* ceil-divide a nonnegative byte count to KiB (tensors _kib_ceil) */
static long long
kib_ceil_ll(long long b)
{
    return (b + 1023) / 1024;
}

/* Build the plain pod's ingest record. Returns 1 stamped, 0 not-plain
 * (caller routes to the Python classifier), -1 error. cfg layout (built
 * once by scheduler/batch.py):
 *   (plain_admission, aligned_key, group_label,
 *    cpu_name, mem_name, eph_name, pods_name,
 *    default_cpu, default_mem) */
static int
stamp_one(PyObject *pod, PyObject **cfg, long long default_cpu,
          long long default_mem)
{
    PyObject *spec = NULL, *meta = NULL, *req = NULL;
    PyObject *containers = NULL, *inits = NULL, *overhead = NULL;
    PyObject *prio = NULL;
    long long nzr_cpu = 0, nzr_mem = 0;
    int plain = 0;

    spec = PyObject_GetAttr(pod, str_spec);
    meta = PyObject_GetAttr(pod, str_metadata);
    if (spec == NULL || meta == NULL)
        goto error;

    /* -- plainness gate (mirror admission._is_plain_pod) ------------- */
    {
        PyObject *ann = PyObject_GetAttr(meta, str_annotations);
        if (ann == NULL)
            goto error;
        if (!PyDict_Check(ann)) {
            Py_DECREF(ann);
            goto not_plain;
        }
        PyObject *got = PyDict_GetItemWithError(ann, cfg[1]);
        Py_DECREF(ann);
        if (got != NULL)
            goto not_plain;
        if (PyErr_Occurred())
            goto error;
    }
    {
        PyObject *labels = PyObject_GetAttr(meta, str_labels);
        if (labels == NULL)
            goto error;
        if (!PyDict_Check(labels)) {
            Py_DECREF(labels);
            goto not_plain;
        }
        PyObject *got = PyDict_GetItemWithError(labels, cfg[2]);
        Py_DECREF(labels);
        if (got != NULL)
            goto not_plain;
        if (PyErr_Occurred())
            goto error;
    }
    {
        PyObject *v = PyObject_GetAttr(spec, str_volumes);
        if (v == NULL)
            goto error;
        int truth = PyObject_IsTrue(v);
        Py_DECREF(v);
        if (truth != 0)
            goto not_plain; /* has volumes, or error (route to Python) */
        v = PyObject_GetAttr(spec, str_affinity);
        if (v == NULL)
            goto error;
        int none = (v == Py_None);
        Py_DECREF(v);
        if (!none)
            goto not_plain;
        v = PyObject_GetAttr(spec, str_spread);
        if (v == NULL)
            goto error;
        truth = PyObject_IsTrue(v);
        Py_DECREF(v);
        if (truth != 0)
            goto not_plain;
    }
    prio = PyObject_GetAttr(spec, str_priority);
    if (prio == NULL)
        goto error;
    if (!PyLong_Check(prio))
        goto not_plain;
    {
        int prio_true = PyObject_IsTrue(prio);
        if (prio_true < 0)
            goto error;
        if (!prio_true) {
            /* bare priorityClassName needs the lister resolver */
            PyObject *pcn = PyObject_GetAttr(spec, str_priority_class);
            if (pcn == NULL)
                goto error;
            int has_pcn = PyObject_IsTrue(pcn);
            Py_DECREF(pcn);
            if (has_pcn != 0)
                goto not_plain;
        }
    }

    /* -- request walk (pod_resource_requests + non_zero_requests) ---- */
    containers = PyObject_GetAttr(spec, str_containers);
    inits = PyObject_GetAttr(spec, str_init_containers);
    overhead = PyObject_GetAttr(spec, str_overhead);
    if (containers == NULL || inits == NULL || overhead == NULL)
        goto error;
    if (!PyList_Check(containers) || !PyList_Check(inits) ||
        !PyDict_Check(overhead))
        goto not_plain;
    req = PyDict_New();
    if (req == NULL)
        goto error;
    for (Py_ssize_t c = 0; c < PyList_GET_SIZE(containers); c++) {
        PyObject *cont = PyList_GET_ITEM(containers, c);
        PyObject *ports = PyObject_GetAttr(cont, str_ports);
        if (ports == NULL)
            goto error;
        if (!PyList_Check(ports)) {
            Py_DECREF(ports);
            goto not_plain;
        }
        for (Py_ssize_t p = 0; p < PyList_GET_SIZE(ports); p++) {
            PyObject *hp =
                PyObject_GetAttr(PyList_GET_ITEM(ports, p), str_host_port);
            if (hp == NULL) {
                Py_DECREF(ports);
                goto error;
            }
            int truth = PyObject_IsTrue(hp);
            Py_DECREF(hp);
            if (truth != 0) {
                Py_DECREF(ports);
                goto not_plain;
            }
        }
        Py_DECREF(ports);
        PyObject *res = PyObject_GetAttr(cont, str_resources);
        PyObject *reqs = res ? PyObject_GetAttr(res, str_requests) : NULL;
        Py_XDECREF(res);
        if (reqs == NULL)
            goto error;
        if (!PyDict_Check(reqs)) {
            Py_DECREF(reqs);
            goto not_plain;
        }
        PyObject *rk, *rv;
        Py_ssize_t rpos = 0;
        while (PyDict_Next(reqs, &rpos, &rk, &rv)) {
            if (!PyLong_Check(rv)) {
                Py_DECREF(reqs);
                goto not_plain;
            }
            PyObject *cur = PyDict_GetItemWithError(req, rk);
            if (cur == NULL && PyErr_Occurred()) {
                Py_DECREF(reqs);
                goto error;
            }
            PyObject *sum;
            if (cur == NULL) {
                sum = rv;
                Py_INCREF(sum);
            } else {
                sum = PyNumber_Add(cur, rv);
                if (sum == NULL) {
                    Py_DECREF(reqs);
                    goto error;
                }
            }
            int sr = PyDict_SetItem(req, rk, sum);
            Py_DECREF(sum);
            if (sr < 0) {
                Py_DECREF(reqs);
                goto error;
            }
        }
        /* non-zero defaults (util/non_zero.go semantics) */
        PyObject *ccpu = PyDict_GetItemWithError(reqs, cfg[3]);
        if (ccpu == NULL && PyErr_Occurred()) {
            Py_DECREF(reqs);
            goto error;
        }
        PyObject *cmem = PyDict_GetItemWithError(reqs, cfg[4]);
        if (cmem == NULL && PyErr_Occurred()) {
            Py_DECREF(reqs);
            goto error;
        }
        nzr_cpu += (ccpu != NULL && PyObject_IsTrue(ccpu) == 1)
                       ? PyLong_AsLongLong(ccpu)
                       : default_cpu;
        nzr_mem += (cmem != NULL && PyObject_IsTrue(cmem) == 1)
                       ? PyLong_AsLongLong(cmem)
                       : default_mem;
        Py_DECREF(reqs);
        if (PyErr_Occurred())
            goto error;
    }
    for (Py_ssize_t c = 0; c < PyList_GET_SIZE(inits); c++) {
        PyObject *cont = PyList_GET_ITEM(inits, c);
        PyObject *res = PyObject_GetAttr(cont, str_resources);
        PyObject *reqs = res ? PyObject_GetAttr(res, str_requests) : NULL;
        Py_XDECREF(res);
        if (reqs == NULL)
            goto error;
        if (!PyDict_Check(reqs)) {
            Py_DECREF(reqs);
            goto not_plain;
        }
        PyObject *rk, *rv;
        Py_ssize_t rpos = 0;
        while (PyDict_Next(reqs, &rpos, &rk, &rv)) {
            if (!PyLong_Check(rv)) {
                Py_DECREF(reqs);
                goto not_plain;
            }
            PyObject *cur = PyDict_GetItemWithError(req, rk);
            if (cur == NULL && PyErr_Occurred()) {
                Py_DECREF(reqs);
                goto error;
            }
            /* Python twin: `if qty > out.get(name, 0)` -- an absent
             * name compares against 0 */
            PyObject *zero = PyLong_FromLong(0);
            if (zero == NULL) {
                Py_DECREF(reqs);
                goto error;
            }
            int gt = PyObject_RichCompareBool(rv, cur ? cur : zero, Py_GT);
            Py_DECREF(zero);
            if (gt < 0) {
                Py_DECREF(reqs);
                goto error;
            }
            if (gt && PyDict_SetItem(req, rk, rv) < 0) {
                Py_DECREF(reqs);
                goto error;
            }
        }
        Py_DECREF(reqs);
    }
    {
        PyObject *rk, *rv;
        Py_ssize_t rpos = 0;
        while (PyDict_Next(overhead, &rpos, &rk, &rv)) {
            if (!PyLong_Check(rv))
                goto not_plain;
            PyObject *cur = PyDict_GetItemWithError(req, rk);
            if (cur == NULL && PyErr_Occurred())
                goto error;
            PyObject *sum;
            if (cur == NULL) {
                sum = rv;
                Py_INCREF(sum);
            } else {
                sum = PyNumber_Add(cur, rv);
                if (sum == NULL)
                    goto error;
            }
            int sr = PyDict_SetItem(req, rk, sum);
            Py_DECREF(sum);
            if (sr < 0)
                goto error;
        }
    }

    /* -- build + install the memos ----------------------------------- */
    {
        PyObject *zero = PyLong_FromLong(0);
        PyObject *items = NULL, *scalar = NULL, *hot = NULL, *nzr = NULL;
        PyObject *packrow = NULL, *key = NULL;
        PyObject *cpu_q = NULL, *mem_q = NULL, *eph_q = NULL;
        PyObject *nzr_cpu_obj = NULL, *nzr_mem_obj = NULL, *kib_obj = NULL;
        PyObject *d = NULL;
        int ok = 0;
        if (zero == NULL)
            goto build_done;

        Py_ssize_t nreq = PyDict_GET_SIZE(req);
        items = PyTuple_New(nreq);
        scalar = PyList_New(0);
        if (items == NULL || scalar == NULL)
            goto build_done;
        {
            PyObject *rk, *rv;
            Py_ssize_t rpos = 0, j = 0;
            while (PyDict_Next(req, &rpos, &rk, &rv)) {
                PyObject *pair = PyTuple_Pack(2, rk, rv);
                if (pair == NULL)
                    goto build_done;
                PyTuple_SET_ITEM(items, j++, pair);
                int fixed =
                    PyObject_RichCompareBool(rk, cfg[3], Py_EQ) == 1 ||
                    PyObject_RichCompareBool(rk, cfg[4], Py_EQ) == 1 ||
                    PyObject_RichCompareBool(rk, cfg[5], Py_EQ) == 1 ||
                    PyObject_RichCompareBool(rk, cfg[6], Py_EQ) == 1;
                if (PyErr_Occurred())
                    goto build_done;
                if (!fixed) {
                    PyObject *spair = PyTuple_Pack(2, rk, rv);
                    if (spair == NULL)
                        goto build_done;
                    int ap = PyList_Append(scalar, spair);
                    Py_DECREF(spair);
                    if (ap < 0)
                        goto build_done;
                }
            }
        }
        cpu_q = PyDict_GetItemWithError(req, cfg[3]);
        mem_q = PyDict_GetItemWithError(req, cfg[4]);
        eph_q = PyDict_GetItemWithError(req, cfg[5]);
        if (PyErr_Occurred())
            goto build_done;
        if (cpu_q == NULL)
            cpu_q = zero;
        if (mem_q == NULL)
            mem_q = zero;
        if (eph_q == NULL)
            eph_q = zero;
        nzr_cpu_obj = PyLong_FromLongLong(nzr_cpu);
        nzr_mem_obj = PyLong_FromLongLong(nzr_mem);
        kib_obj = PyLong_FromLongLong(kib_ceil_ll(nzr_mem));
        if (nzr_cpu_obj == NULL || nzr_mem_obj == NULL || kib_obj == NULL)
            goto build_done;
        {
            PyObject *scalar_t = PyList_AsTuple(scalar);
            if (scalar_t == NULL)
                goto build_done;
            PyObject *empty = PyTuple_New(0);
            if (empty == NULL) {
                Py_DECREF(scalar_t);
                goto build_done;
            }
            hot = PyTuple_Pack(8, cpu_q, mem_q, eph_q, scalar_t,
                               nzr_cpu_obj, nzr_mem_obj, Py_False, empty);
            Py_DECREF(scalar_t);
            Py_DECREF(empty);
        }
        nzr = PyTuple_Pack(2, nzr_cpu_obj, nzr_mem_obj);
        if (hot == NULL || nzr == NULL)
            goto build_done;
        {
            PyObject *empty = PyTuple_New(0);
            if (empty == NULL)
                goto build_done;
            key = PyTuple_Pack(2, items, empty);
            Py_DECREF(empty);
        }
        if (key == NULL)
            goto build_done;
        packrow = PyTuple_Pack(4, key, nzr_cpu_obj, kib_obj, prio);
        if (packrow == NULL)
            goto build_done;

        d = PyObject_GetAttr(pod, str_dict);
        if (d == NULL || !PyDict_Check(d))
            goto build_done;
        if (PyDict_SetItem(d, str_req_memo, req) < 0 ||
            PyDict_SetItem(d, str_nzr_memo, nzr) < 0 ||
            PyDict_SetItem(d, str_hot_memo, hot) < 0 ||
            PyDict_SetItem(d, str_packrow, packrow) < 0 ||
            PyDict_SetItem(d, str_band_priority, prio) < 0 ||
            PyDict_SetItem(d, str_admission, cfg[0]) < 0)
            goto build_done;
        ok = 1;
    build_done:
        Py_XDECREF(zero);
        Py_XDECREF(items);
        Py_XDECREF(scalar);
        Py_XDECREF(hot);
        Py_XDECREF(nzr);
        Py_XDECREF(key);
        Py_XDECREF(packrow);
        Py_XDECREF(nzr_cpu_obj);
        Py_XDECREF(nzr_mem_obj);
        Py_XDECREF(kib_obj);
        Py_XDECREF(d);
        if (!ok)
            goto error;
    }
    plain = 1;
    goto done;

not_plain:
    /* several gates route a FAILED truth test here ("broken shape: let
     * the Python classifier own the error") -- the pending exception
     * must not leak into the caller's success return */
    PyErr_Clear();
    plain = 0;
    goto done;
error:
    plain = -1;
done:
    Py_XDECREF(spec);
    Py_XDECREF(meta);
    Py_XDECREF(req);
    Py_XDECREF(containers);
    Py_XDECREF(inits);
    Py_XDECREF(overhead);
    Py_XDECREF(prio);
    return plain;
}

static PyObject *
ingest_stamp(PyObject *self, PyObject *args)
{
    /* ingest_stamp(pods, cfg) -> [index of non-plain pods]
     *
     * One C pass over a watch frame's new pending pods: plain pods get
     * their full ingest record (memos + shared Admission) stamped here;
     * the returned indices take the full Python classifier. Semantics:
     * scheduler/admission.py stamp_plain_pods (the differential
     * twin). */
    PyObject *pods, *cfg_t;
    if (!PyArg_ParseTuple(args, "O!O!", &PyList_Type, &pods,
                          &PyTuple_Type, &cfg_t))
        return NULL;
    if (PyTuple_GET_SIZE(cfg_t) != 9) {
        PyErr_SetString(PyExc_ValueError, "ingest_stamp cfg must have 9 items");
        return NULL;
    }
    PyObject *cfg[9];
    for (int i = 0; i < 9; i++)
        cfg[i] = PyTuple_GET_ITEM(cfg_t, i);
    long long default_cpu = PyLong_AsLongLong(cfg[7]);
    long long default_mem = PyLong_AsLongLong(cfg[8]);
    if (PyErr_Occurred())
        return NULL;
    PyObject *rest = PyList_New(0);
    if (rest == NULL)
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(pods);
    for (Py_ssize_t i = 0; i < n; i++) {
        int r = stamp_one(PyList_GET_ITEM(pods, i), cfg, default_cpu,
                          default_mem);
        if (r < 0) {
            /* a broken pod object routes to the Python classifier,
             * which owns the error handling (classify wraps in
             * try/except) -- the fast path never half-stamps */
            PyErr_Clear();
            r = 0;
        }
        if (r == 0) {
            PyObject *idx = PyLong_FromSsize_t(i);
            if (idx == NULL) {
                Py_DECREF(rest);
                return NULL;
            }
            int ap = PyList_Append(rest, idx);
            Py_DECREF(idx);
            if (ap < 0) {
                Py_DECREF(rest);
                return NULL;
            }
        }
    }
    return rest;
}

static PyObject *
pack_gather(PyObject *self, PyObject *args)
{
    /* pack_gather(pods, stamp, row_cache, idx, nzr, prio) -> new_keys
     *
     * The pack-ready-row gather: per pod, read the _packrow memo
     * (calling back into `stamp` for the rare miss), dedup its request
     * key through `row_cache` (key -> uniq index), and write
     * idx/nzr/prio straight into the caller's preallocated int32
     * buffers. Returns the DISTINCT keys first seen this call, in
     * order -- the only per-row work left in Python is encoding those
     * few distinct rows against the schema. Twin:
     * tensors/node_tensor.py _pack_gather_py. */
    PyObject *pods, *stamp, *row_cache;
    Py_buffer idx_buf, nzr_buf, prio_buf;
    if (!PyArg_ParseTuple(args, "O!OO!w*w*w*", &PyList_Type, &pods, &stamp,
                          &PyDict_Type, &row_cache, &idx_buf, &nzr_buf,
                          &prio_buf))
        return NULL;
    Py_ssize_t b = PyList_GET_SIZE(pods);
    PyObject *new_keys = NULL;
    if ((Py_ssize_t)(idx_buf.len) < b * 4 ||
        (Py_ssize_t)(nzr_buf.len) < b * 8 ||
        (Py_ssize_t)(prio_buf.len) < b * 4) {
        PyErr_SetString(PyExc_ValueError, "pack_gather buffers too small");
        goto out;
    }
    new_keys = PyList_New(0);
    if (new_keys == NULL)
        goto out;
    {
        int32_t *idx32 = (int32_t *)idx_buf.buf;
        int32_t *nzr32 = (int32_t *)nzr_buf.buf;
        int32_t *prio32 = (int32_t *)prio_buf.buf;
        for (Py_ssize_t i = 0; i < b; i++) {
            PyObject *pod = PyList_GET_ITEM(pods, i);
            PyObject *d = PyObject_GetAttr(pod, str_dict);
            if (d == NULL)
                goto fail;
            PyObject *memo =
                PyDict_Check(d) ? PyDict_GetItemWithError(d, str_packrow)
                                : NULL;
            Py_XINCREF(memo);
            Py_DECREF(d);
            if (memo == NULL) {
                if (PyErr_Occurred())
                    goto fail;
                memo = PyObject_CallFunctionObjArgs(stamp, pod, NULL);
                if (memo == NULL)
                    goto fail;
            }
            if (!PyTuple_Check(memo) || PyTuple_GET_SIZE(memo) != 4) {
                Py_DECREF(memo);
                PyErr_SetString(PyExc_TypeError, "bad _packrow memo");
                goto fail;
            }
            PyObject *key = PyTuple_GET_ITEM(memo, 0);
            PyObject *u_obj = PyDict_GetItemWithError(row_cache, key);
            long u;
            if (u_obj == NULL) {
                if (PyErr_Occurred()) {
                    Py_DECREF(memo);
                    goto fail;
                }
                u = (long)PyDict_GET_SIZE(row_cache);
                PyObject *u_new = PyLong_FromLong(u);
                if (u_new == NULL ||
                    PyDict_SetItem(row_cache, key, u_new) < 0 ||
                    PyList_Append(new_keys, key) < 0) {
                    Py_XDECREF(u_new);
                    Py_DECREF(memo);
                    goto fail;
                }
                Py_DECREF(u_new);
            } else {
                u = PyLong_AsLong(u_obj);
                if (u == -1 && PyErr_Occurred()) {
                    Py_DECREF(memo);
                    goto fail;
                }
            }
            long long cpu = PyLong_AsLongLong(PyTuple_GET_ITEM(memo, 1));
            long long mem = PyLong_AsLongLong(PyTuple_GET_ITEM(memo, 2));
            long long pr = PyLong_AsLongLong(PyTuple_GET_ITEM(memo, 3));
            Py_DECREF(memo);
            if (PyErr_Occurred())
                goto fail;
            /* the Python twin's numpy int32 assignment raises
             * OverflowError on out-of-range values -- silent wraparound
             * here would corrupt the fit/score inputs and diverge the
             * two paths */
            if (cpu < INT32_MIN || cpu > INT32_MAX ||
                mem < INT32_MIN || mem > INT32_MAX ||
                pr < INT32_MIN || pr > INT32_MAX) {
                PyErr_SetString(PyExc_OverflowError,
                                "_packrow value out of int32 range");
                goto fail;
            }
            idx32[i] = (int32_t)u;
            nzr32[2 * i] = (int32_t)cpu;
            nzr32[2 * i + 1] = (int32_t)mem;
            prio32[i] = (int32_t)pr;
        }
    }
    goto out;
fail:
    Py_XDECREF(new_keys);
    new_keys = NULL;
out:
    PyBuffer_Release(&idx_buf);
    PyBuffer_Release(&nzr_buf);
    PyBuffer_Release(&prio_buf);
    return new_keys;
}

static PyObject *
queue_shape(PyObject *self, PyObject *args)
{
    /* queue_shape(pods) -> (keys, prios, noms)
     *
     * One C pass shaping a create burst for the bulk activeQ add:
     * "ns/name" key strings (the heap's key space), spec.priority (the
     * PrioritySort sort-key component), and status.nominated_node_name
     * per pod. Twin: queue/scheduling_queue.py _queue_shape_py. */
    PyObject *pods;
    if (!PyArg_ParseTuple(args, "O!", &PyList_Type, &pods))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(pods);
    PyObject *keys = PyList_New(n);
    PyObject *prios = PyList_New(n);
    PyObject *noms = PyList_New(n);
    if (keys == NULL || prios == NULL || noms == NULL)
        goto fail;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *pod = PyList_GET_ITEM(pods, i);
        PyObject *meta = PyObject_GetAttr(pod, str_metadata);
        if (meta == NULL)
            goto fail;
        PyObject *ns = PyObject_GetAttr(meta, str_namespace);
        PyObject *name = PyObject_GetAttr(meta, str_name);
        Py_DECREF(meta);
        if (ns == NULL || name == NULL) {
            Py_XDECREF(ns);
            Py_XDECREF(name);
            goto fail;
        }
        PyObject *key = PyUnicode_FromFormat("%U/%U", ns, name);
        Py_DECREF(ns);
        Py_DECREF(name);
        if (key == NULL)
            goto fail;
        PyList_SET_ITEM(keys, i, key);
        PyObject *spec = PyObject_GetAttr(pod, str_spec);
        PyObject *prio = spec ? PyObject_GetAttr(spec, str_priority) : NULL;
        Py_XDECREF(spec);
        if (prio == NULL)
            goto fail;
        PyList_SET_ITEM(prios, i, prio);
        PyObject *status = PyObject_GetAttr(pod, str_status);
        PyObject *nom =
            status ? PyObject_GetAttr(status, str_nominated) : NULL;
        Py_XDECREF(status);
        if (nom == NULL)
            goto fail;
        PyList_SET_ITEM(noms, i, nom);
    }
    return Py_BuildValue("(NNN)", keys, prios, noms);
fail:
    Py_XDECREF(keys);
    Py_XDECREF(prios);
    Py_XDECREF(noms);
    return NULL;
}

static PyObject *
mirror_scatter(PyObject *self, PyObject *args)
{
    /* mirror_scatter(a, req, nzr, req_shadow, nzr_shadow,
     *                rows_out, req_out, nzr_out) -> k
     *
     * The bind-echo -> shadow-mirror hot loop (ISSUE 18): one pass over
     * the batch's int32 assignments compacts the placed rows into
     * rows_out/req_out/nzr_out AND scatter-adds the per-pod demand into
     * the int32 shadow expectation, replacing the committer's
     * fancy-index + two np.add.at passes. Every index is validated
     * BEFORE any buffer is mutated so a failure here can always fall
     * back to the Python twin (scheduler/batch.py _mirror_scatter_py)
     * without double-applying. Layout contract (all C-contiguous):
     * a int32[b], req int32[b,r], nzr int32[b,2], req_shadow int32[n,r]
     * (writable), nzr_shadow int32[n,2] (writable), rows_out int64[b],
     * req_out int32[b,r], nzr_out int32[b,2]. */
    Py_buffer a_buf, req_buf, nzr_buf, rs_buf, ns_buf;
    Py_buffer ro_buf, qo_buf, zo_buf;
    if (!PyArg_ParseTuple(args, "y*y*y*w*w*w*w*w*", &a_buf, &req_buf,
                          &nzr_buf, &rs_buf, &ns_buf, &ro_buf, &qo_buf,
                          &zo_buf))
        return NULL;
    PyObject *ret = NULL;
    Py_ssize_t b = a_buf.len / 4;
    Py_ssize_t n = ns_buf.len / 8;
    Py_ssize_t r = (b > 0) ? req_buf.len / (4 * b) : 0;
    if (b == 0) {
        ret = PyLong_FromSsize_t(0);
        goto out;
    }
    if (r <= 0 || req_buf.len != b * r * 4 || nzr_buf.len != b * 8 ||
        rs_buf.len != n * r * 4 || ns_buf.len != n * 8 ||
        ro_buf.len < b * 8 || qo_buf.len < b * r * 4 ||
        zo_buf.len < b * 8) {
        PyErr_SetString(PyExc_ValueError,
                        "mirror_scatter buffer shape mismatch");
        goto out;
    }
    {
        const int32_t *a32 = (const int32_t *)a_buf.buf;
        const int32_t *q32 = (const int32_t *)req_buf.buf;
        const int32_t *z32 = (const int32_t *)nzr_buf.buf;
        int32_t *rs32 = (int32_t *)rs_buf.buf;
        int32_t *ns32 = (int32_t *)ns_buf.buf;
        int64_t *ro64 = (int64_t *)ro_buf.buf;
        int32_t *qo32 = (int32_t *)qo_buf.buf;
        int32_t *zo32 = (int32_t *)zo_buf.buf;
        /* validate-before-mutate: the twin must stay a safe retry */
        for (Py_ssize_t i = 0; i < b; i++) {
            int32_t v = a32[i];
            if (v != -1 && (v < 0 || (Py_ssize_t)v >= n)) {
                PyErr_SetString(PyExc_ValueError,
                                "mirror_scatter assignment out of range");
                goto out;
            }
        }
        Py_ssize_t k = 0;
        for (Py_ssize_t i = 0; i < b; i++) {
            int32_t v = a32[i];
            if (v == -1)
                continue;
            const int32_t *qrow = q32 + i * r;
            int32_t *srow = rs32 + (Py_ssize_t)v * r;
            int32_t *orow = qo32 + k * r;
            for (Py_ssize_t j = 0; j < r; j++) {
                srow[j] += qrow[j];
                orow[j] = qrow[j];
            }
            ns32[2 * v] += z32[2 * i];
            ns32[2 * v + 1] += z32[2 * i + 1];
            zo32[2 * k] = z32[2 * i];
            zo32[2 * k + 1] = z32[2 * i + 1];
            ro64[k] = (int64_t)v;
            k++;
        }
        ret = PyLong_FromSsize_t(k);
    }
out:
    PyBuffer_Release(&a_buf);
    PyBuffer_Release(&req_buf);
    PyBuffer_Release(&nzr_buf);
    PyBuffer_Release(&rs_buf);
    PyBuffer_Release(&ns_buf);
    PyBuffer_Release(&ro_buf);
    PyBuffer_Release(&qo_buf);
    PyBuffer_Release(&zo_buf);
    return ret;
}

static PyMethodDef methods[] = {
    {"match_compiled", match_compiled, METH_VARARGS,
     "match_compiled(labels, compiled) -> bool"},
    {"match_mask", match_mask, METH_VARARGS,
     "match_mask(labels_list, compiled) -> bytes"},
    {"dict_covers", dict_covers, METH_VARARGS,
     "dict_covers(labels, selector_dict) -> bool"},
    {"cow_clone", cow_clone, METH_VARARGS,
     "cow_clone(obj, attr_names) -> shallow clone with named attrs "
     "also shallow-cloned"},
    {"assume_clones", assume_clones, METH_VARARGS,
     "assume_clones(pods, hosts) -> [assumed clone with spec.node_name "
     "set]"},
    {"commit_gather", commit_gather, METH_VARARGS,
     "commit_gather(solver_infos, order, assignments, names) -> "
     "(pod_infos, clones, hosts)"},
    {"bind_assumed_bulk", bind_assumed_bulk, METH_VARARGS,
     "bind_assumed_bulk(store, assumed_list, rv, event_cls) -> "
     "(errors, events, new_rv)"},
    {"ingest_decode", ingest_decode, METH_VARARGS,
     "ingest_decode(events) -> [key]: memoize per-event key records"},
    {"ingest_apply", ingest_apply, METH_VARARGS,
     "ingest_apply(store, events) -> [(etype, old, new)]"},
    {"ingest_stamp", ingest_stamp, METH_VARARGS,
     "ingest_stamp(pods, cfg) -> [non-plain indices]; plain pods get "
     "their full ingest record stamped in C"},
    {"pack_gather", pack_gather, METH_VARARGS,
     "pack_gather(pods, stamp, row_cache, idx, nzr, prio) -> new_keys"},
    {"queue_shape", queue_shape, METH_VARARGS,
     "queue_shape(pods) -> (keys, prios, noms)"},
    {"mirror_scatter", mirror_scatter, METH_VARARGS,
     "mirror_scatter(a, req, nzr, req_shadow, nzr_shadow, rows_out, "
     "req_out, nzr_out) -> placed count k"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_hotpath",
    "native label-selector matching (SURVEY section 2.4 host data plane)",
    -1, methods,
};

PyMODINIT_FUNC
PyInit__hotpath(void)
{
    str_dict = PyUnicode_InternFromString("__dict__");
    str_spec = PyUnicode_InternFromString("spec");
    str_node_name = PyUnicode_InternFromString("node_name");
    str_metadata = PyUnicode_InternFromString("metadata");
    str_namespace = PyUnicode_InternFromString("namespace");
    str_name = PyUnicode_InternFromString("name");
    str_uid = PyUnicode_InternFromString("uid");
    str_resource_version = PyUnicode_InternFromString("resource_version");
    str_sig_memo = PyUnicode_InternFromString("_sig_memo");
    str_modified = PyUnicode_InternFromString("MODIFIED");
    str_pod = PyUnicode_InternFromString("pod");
    str_obj_attr = PyUnicode_InternFromString("object");
    str_type_attr = PyUnicode_InternFromString("type");
    str_decoded = PyUnicode_InternFromString("decoded");
    str_added = PyUnicode_InternFromString("ADDED");
    str_deleted = PyUnicode_InternFromString("DELETED");
    str_status = PyUnicode_InternFromString("status");
    str_nominated = PyUnicode_InternFromString("nominated_node_name");
    str_priority = PyUnicode_InternFromString("priority");
    str_priority_class = PyUnicode_InternFromString("priority_class_name");
    str_annotations = PyUnicode_InternFromString("annotations");
    str_labels = PyUnicode_InternFromString("labels");
    str_volumes = PyUnicode_InternFromString("volumes");
    str_affinity = PyUnicode_InternFromString("affinity");
    str_spread =
        PyUnicode_InternFromString("topology_spread_constraints");
    str_containers = PyUnicode_InternFromString("containers");
    str_init_containers = PyUnicode_InternFromString("init_containers");
    str_overhead = PyUnicode_InternFromString("overhead");
    str_resources = PyUnicode_InternFromString("resources");
    str_requests = PyUnicode_InternFromString("requests");
    str_ports = PyUnicode_InternFromString("ports");
    str_host_port = PyUnicode_InternFromString("host_port");
    str_packrow = PyUnicode_InternFromString("_packrow");
    str_band_priority = PyUnicode_InternFromString("_band_priority");
    str_admission = PyUnicode_InternFromString("_admission");
    str_req_memo = PyUnicode_InternFromString("_req_memo");
    str_nzr_memo = PyUnicode_InternFromString("_nzr_memo");
    str_hot_memo = PyUnicode_InternFromString("_hot_memo");
    if (str_dict == NULL || str_spec == NULL || str_node_name == NULL ||
        str_metadata == NULL || str_namespace == NULL ||
        str_name == NULL || str_uid == NULL || str_resource_version == NULL ||
        str_sig_memo == NULL || str_modified == NULL || str_pod == NULL ||
        str_obj_attr == NULL || str_type_attr == NULL ||
        str_decoded == NULL || str_added == NULL || str_deleted == NULL ||
        str_status == NULL || str_nominated == NULL ||
        str_priority == NULL || str_priority_class == NULL ||
        str_annotations == NULL || str_labels == NULL ||
        str_volumes == NULL || str_affinity == NULL || str_spread == NULL ||
        str_containers == NULL || str_init_containers == NULL ||
        str_overhead == NULL || str_resources == NULL ||
        str_requests == NULL || str_ports == NULL ||
        str_host_port == NULL || str_packrow == NULL ||
        str_band_priority == NULL || str_admission == NULL ||
        str_req_memo == NULL || str_nzr_memo == NULL ||
        str_hot_memo == NULL)
        return NULL;
    return PyModule_Create(&moduledef);
}
