/* Native host data plane: the hot label-selector matcher.
 *
 * SURVEY.md section 2.4: the reference has no native scheduling code (all
 * Go); the native components owed here are the NEW performance core. On
 * the host side the single hottest string operation is label-selector
 * matching -- every pack family (affinity/spread/selector-spread/
 * preferred-affinity count tensors), PDB budget filtering, the disruption
 * controller, and the affinity queue wakeups all reduce to
 * labels_match_selector() over (pod labels, selector) pairs, O(pods x
 * rows) per batch. This module implements the match against a
 * PRE-COMPILED selector form (built once per selector object by
 * kubernetes_tpu/api/selectors.py):
 *
 *   compiled = (match_labels_dict,
 *               ((key, opcode, values_frozenset), ...))
 *   opcodes: 0=In 1=NotIn 2=Exists 3=DoesNotExist
 *
 * Exposed functions:
 *   match_compiled(labels_dict, compiled) -> bool
 *   match_mask(labels_list, compiled) -> bytes   (one byte per entry;
 *       the packers' inner loops over many pods per selector)
 *   dict_covers(labels_dict, selector_dict) -> bool  (plain map
 *       selectors: every kv present; empty selector -> False, matching
 *       label_selector_as_dict_matches)
 *
 * Python fallbacks with identical semantics live in api/selectors.py;
 * tests/test_native_selectors.py differentially fuzzes the two.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

static int
match_compiled_impl(PyObject *labels, PyObject *compiled)
{
    /* returns 1 match, 0 no match, -1 error */
    PyObject *ml = PyTuple_GET_ITEM(compiled, 0);   /* dict */
    PyObject *exprs = PyTuple_GET_ITEM(compiled, 1); /* tuple */

    PyObject *key, *value;
    Py_ssize_t pos = 0;
    while (PyDict_Next(ml, &pos, &key, &value)) {
        PyObject *got = PyDict_GetItemWithError(labels, key);
        if (got == NULL) {
            if (PyErr_Occurred())
                return -1;
            return 0;
        }
        int eq = PyObject_RichCompareBool(got, value, Py_EQ);
        if (eq < 0)
            return -1;
        if (!eq)
            return 0;
    }

    Py_ssize_t n = PyTuple_GET_SIZE(exprs);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *req = PyTuple_GET_ITEM(exprs, i);
        PyObject *rkey = PyTuple_GET_ITEM(req, 0);
        long op = PyLong_AsLong(PyTuple_GET_ITEM(req, 1));
        PyObject *values = PyTuple_GET_ITEM(req, 2);
        PyObject *got = PyDict_GetItemWithError(labels, rkey);
        if (got == NULL && PyErr_Occurred())
            return -1;
        int ok;
        switch (op) {
        case 0: /* In */
            if (got == NULL) {
                ok = 0;
            } else {
                ok = PySet_Contains(values, got);
                if (ok < 0)
                    return -1;
            }
            break;
        case 1: /* NotIn */
            if (got == NULL) {
                ok = 1;
            } else {
                int in = PySet_Contains(values, got);
                if (in < 0)
                    return -1;
                ok = !in;
            }
            break;
        case 2: /* Exists */
            ok = got != NULL;
            break;
        case 3: /* DoesNotExist */
            ok = got == NULL;
            break;
        default:
            /* opcode -1: an operator the compiler didn't recognize;
             * raised only when evaluation reaches it, matching the
             * Python path's short-circuit semantics */
            PyErr_SetString(PyExc_ValueError,
                            "unknown label selector operator");
            return -1;
        }
        if (!ok)
            return 0;
    }
    return 1;
}

static PyObject *
match_compiled(PyObject *self, PyObject *args)
{
    PyObject *labels, *compiled;
    if (!PyArg_ParseTuple(args, "O!O!", &PyDict_Type, &labels,
                          &PyTuple_Type, &compiled))
        return NULL;
    int r = match_compiled_impl(labels, compiled);
    if (r < 0)
        return NULL;
    if (r)
        Py_RETURN_TRUE;
    Py_RETURN_FALSE;
}

static PyObject *
match_mask(PyObject *self, PyObject *args)
{
    PyObject *labels_list, *compiled;
    if (!PyArg_ParseTuple(args, "O!O!", &PyList_Type, &labels_list,
                          &PyTuple_Type, &compiled))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(labels_list);
    PyObject *out = PyBytes_FromStringAndSize(NULL, n);
    if (out == NULL)
        return NULL;
    char *buf = PyBytes_AS_STRING(out);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *labels = PyList_GET_ITEM(labels_list, i);
        if (!PyDict_Check(labels)) {
            Py_DECREF(out);
            PyErr_SetString(PyExc_TypeError, "labels entries must be dicts");
            return NULL;
        }
        int r = match_compiled_impl(labels, compiled);
        if (r < 0) {
            Py_DECREF(out);
            return NULL;
        }
        buf[i] = (char)r;
    }
    return out;
}

static PyObject *
dict_covers(PyObject *self, PyObject *args)
{
    PyObject *labels, *selector;
    if (!PyArg_ParseTuple(args, "O!O!", &PyDict_Type, &labels,
                          &PyDict_Type, &selector))
        return NULL;
    if (PyDict_GET_SIZE(selector) == 0)
        Py_RETURN_FALSE; /* empty map selector matches nothing */
    PyObject *key, *value;
    Py_ssize_t pos = 0;
    while (PyDict_Next(selector, &pos, &key, &value)) {
        PyObject *got = PyDict_GetItemWithError(labels, key);
        if (got == NULL) {
            if (PyErr_Occurred())
                return NULL;
            Py_RETURN_FALSE;
        }
        int eq = PyObject_RichCompareBool(got, value, Py_EQ);
        if (eq < 0)
            return NULL;
        if (!eq)
            Py_RETURN_FALSE;
    }
    Py_RETURN_TRUE;
}

/* -- copy-on-write object clones (the commit-path hot loop) -------------
 *
 * The bulk bind/assume pipeline clones every pod 2-4 times per commit
 * (assumed_clone: pod+spec; _bind_locked: pod+metadata+spec+status).
 * copy.copy() routes each clone through __reduce_ex__/_reconstruct at
 * ~5-7us a call; at 10k pods x 6 clones that is ~0.4s of the measured
 * burst window. cow_clone() does the same thing the direct way: allocate
 * via the type (no __init__), dict-copy __dict__, and shallow-clone the
 * named nested attributes in the same call. Reference analogue: the Go
 * scheduler's pod.DeepCopy() before assume (scheduler.go:474) -- ours is
 * shallow because downstream only writes spec.node_name /
 * metadata.resource_version (the informer-cache read-only contract).
 */

static PyObject *str_dict = NULL; /* interned "__dict__" */

static PyObject *
shallow_clone_one(PyObject *obj)
{
    PyTypeObject *tp = Py_TYPE(obj);
    PyObject *new = tp->tp_alloc(tp, 0);
    if (new == NULL)
        return NULL;
    PyObject *d = PyObject_GetAttr(obj, str_dict);
    if (d == NULL) {
        Py_DECREF(new);
        return NULL;
    }
    PyObject *dc = PyDict_Copy(d);
    Py_DECREF(d);
    if (dc == NULL) {
        Py_DECREF(new);
        return NULL;
    }
    if (PyObject_SetAttr(new, str_dict, dc) < 0) {
        Py_DECREF(dc);
        Py_DECREF(new);
        return NULL;
    }
    Py_DECREF(dc);
    return new;
}

static PyObject *
cow_clone(PyObject *self, PyObject *args)
{
    /* cow_clone(obj, ("spec", "status", ...)) -> clone
     * Shallow-clones obj, then shallow-clones each named attribute on
     * the clone so the caller may mutate those sub-objects freely. */
    PyObject *obj, *attrs;
    if (!PyArg_ParseTuple(args, "OO!", &obj, &PyTuple_Type, &attrs))
        return NULL;
    PyObject *new = shallow_clone_one(obj);
    if (new == NULL)
        return NULL;
    Py_ssize_t n = PyTuple_GET_SIZE(attrs);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *name = PyTuple_GET_ITEM(attrs, i);
        PyObject *sub = PyObject_GetAttr(obj, name);
        if (sub == NULL)
            goto fail;
        PyObject *subc = shallow_clone_one(sub);
        Py_DECREF(sub);
        if (subc == NULL)
            goto fail;
        int r = PyObject_SetAttr(new, name, subc);
        Py_DECREF(subc);
        if (r < 0)
            goto fail;
    }
    return new;
fail:
    Py_DECREF(new);
    return NULL;
}

/* -- bulk commit spine ---------------------------------------------------
 *
 * The 10k-burst commit window spends most of its host budget in two
 * per-pod loops: (a) assumed_clone + spec.node_name per committed pod
 * (batch.py commit.clone) and (b) the apiserver bind transaction
 * (server.py bind_bulk: lookup, uid/bound checks, cow clone, rv bump,
 * store write, watch-event build). Both are pure object-graph work with
 * no Python-level semantics beyond dict/attr ops, so they live here as
 * single C loops: assume_clones() and bind_assumed_bulk(). The Python
 * fallbacks (api/types.py assumed_clone, server.py _bind_locked) carry
 * the same semantics; tests/test_native_commit.py differentially
 * exercises native vs fallback on the same inputs.
 */

static PyObject *str_spec = NULL;
static PyObject *str_node_name = NULL;
static PyObject *str_metadata = NULL;
static PyObject *str_namespace = NULL;
static PyObject *str_name = NULL;
static PyObject *str_uid = NULL;
static PyObject *str_resource_version = NULL;
static PyObject *str_sig_memo = NULL;
static PyObject *str_modified = NULL;

/* Install dict `dc` (reference stolen) as `obj`'s instance dict via the
 * dict pointer when the layout allows it, else through the __dict__
 * descriptor. Returns 0 ok / -1 error (dc released either way). */
static int
install_dict(PyObject *obj, PyObject *dc)
{
    PyObject **dp = _PyObject_GetDictPtr(obj);
    if (dp != NULL) {
        Py_XSETREF(*dp, dc);
        return 0;
    }
    int r = PyObject_SetAttr(obj, str_dict, dc);
    Py_DECREF(dc);
    return r;
}

/* Shallow-clone obj by dict copy; optionally override one key in (and/or
 * drop one key from) the copied dict before installing it. */
static PyObject *
clone_with_dict(PyObject *obj, PyObject *override_key, PyObject *override_val,
                PyObject *drop_key)
{
    PyTypeObject *tp = Py_TYPE(obj);
    PyObject *new = tp->tp_alloc(tp, 0);
    if (new == NULL)
        return NULL;
    PyObject *d = PyObject_GetAttr(obj, str_dict);
    if (d == NULL) {
        Py_DECREF(new);
        return NULL;
    }
    PyObject *dc = PyDict_Copy(d);
    Py_DECREF(d);
    if (dc == NULL) {
        Py_DECREF(new);
        return NULL;
    }
    if (override_key != NULL &&
        PyDict_SetItem(dc, override_key, override_val) < 0) {
        Py_DECREF(dc);
        Py_DECREF(new);
        return NULL;
    }
    if (drop_key != NULL && PyDict_Contains(dc, drop_key) == 1 &&
        PyDict_DelItem(dc, drop_key) < 0) {
        Py_DECREF(dc);
        Py_DECREF(new);
        return NULL;
    }
    if (install_dict(new, dc) < 0) {
        Py_DECREF(new);
        return NULL;
    }
    return new;
}

static PyObject *
assume_clones(PyObject *self, PyObject *args)
{
    /* assume_clones(pods, hosts) -> [clone] where clone = shallow pod
     * with shallow spec and spec.node_name = host (the one-call form of
     * Pod.assumed_clone() + node_name assignment per committed pod). */
    PyObject *pods, *hosts;
    if (!PyArg_ParseTuple(args, "O!O!", &PyList_Type, &pods,
                          &PyList_Type, &hosts))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(pods);
    if (PyList_GET_SIZE(hosts) != n) {
        PyErr_SetString(PyExc_ValueError, "pods/hosts length mismatch");
        return NULL;
    }
    PyObject *out = PyList_New(n);
    if (out == NULL)
        return NULL;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *pod = PyList_GET_ITEM(pods, i);
        PyObject *host = PyList_GET_ITEM(hosts, i);
        PyObject *spec = PyObject_GetAttr(pod, str_spec);
        if (spec == NULL)
            goto fail;
        PyObject *specc = clone_with_dict(spec, str_node_name, host, NULL);
        Py_DECREF(spec);
        if (specc == NULL)
            goto fail;
        PyObject *podc = clone_with_dict(pod, str_spec, specc, NULL);
        Py_DECREF(specc);
        if (podc == NULL)
            goto fail;
        PyList_SET_ITEM(out, i, podc);
    }
    return out;
fail:
    Py_DECREF(out);
    return NULL;
}

static PyObject *str_pod = NULL;

static PyObject *
commit_gather(PyObject *self, PyObject *args)
{
    /* commit_gather(solver_infos, order, assignments, names)
     *   -> (pod_infos, clones, hosts)
     *
     * One C pass over a solved batch's PLACED slots (the committer
     * splits NO_NODE slots off with numpy before calling): slot j
     * gathers pod_info = solver_infos[order[j]], resolves
     * host = names[assignments[j]], and builds the assumed clone
     * (shallow pod + shallow spec with spec.node_name = host) in the
     * same step -- fusing the commit loop's gather with the
     * assume_clones pass so the per-pod Python work of the bulk commit
     * is three parallel C-built lists. order/assignments are plain int
     * lists (numpy .tolist() output); semantics match the Python
     * fallback in scheduler/batch.py (_commit_gather_py),
     * differentially tested in tests/test_native_commit.py. */
    PyObject *infos, *order, *assigns, *names;
    if (!PyArg_ParseTuple(args, "O!O!O!O!", &PyList_Type, &infos,
                          &PyList_Type, &order, &PyList_Type, &assigns,
                          &PyList_Type, &names))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(order);
    if (PyList_GET_SIZE(assigns) != n) {
        PyErr_SetString(PyExc_ValueError, "order/assignments length mismatch");
        return NULL;
    }
    Py_ssize_t n_infos = PyList_GET_SIZE(infos);
    Py_ssize_t n_names = PyList_GET_SIZE(names);
    PyObject *pis = PyList_New(n);
    PyObject *clones = PyList_New(n);
    PyObject *hosts = PyList_New(n);
    if (pis == NULL || clones == NULL || hosts == NULL)
        goto fail;
    for (Py_ssize_t j = 0; j < n; j++) {
        long oi = PyLong_AsLong(PyList_GET_ITEM(order, j));
        long ci = PyLong_AsLong(PyList_GET_ITEM(assigns, j));
        if ((oi == -1 || ci == -1) && PyErr_Occurred())
            goto fail;
        if (oi < 0 || oi >= n_infos || ci < 0 || ci >= n_names) {
            PyErr_SetString(PyExc_IndexError,
                            "commit_gather index out of range");
            goto fail;
        }
        PyObject *pi = PyList_GET_ITEM(infos, oi);
        PyObject *host = PyList_GET_ITEM(names, ci);
        PyObject *pod = PyObject_GetAttr(pi, str_pod);
        if (pod == NULL)
            goto fail;
        PyObject *spec = PyObject_GetAttr(pod, str_spec);
        if (spec == NULL) {
            Py_DECREF(pod);
            goto fail;
        }
        PyObject *specc = clone_with_dict(spec, str_node_name, host, NULL);
        Py_DECREF(spec);
        if (specc == NULL) {
            Py_DECREF(pod);
            goto fail;
        }
        PyObject *podc = clone_with_dict(pod, str_spec, specc, NULL);
        Py_DECREF(specc);
        Py_DECREF(pod);
        if (podc == NULL)
            goto fail;
        Py_INCREF(pi);
        PyList_SET_ITEM(pis, j, pi);
        PyList_SET_ITEM(clones, j, podc);
        Py_INCREF(host);
        PyList_SET_ITEM(hosts, j, host);
    }
    return Py_BuildValue("(NNN)", pis, clones, hosts);
fail:
    Py_XDECREF(pis);
    Py_XDECREF(clones);
    Py_XDECREF(hosts);
    return NULL;
}

static PyObject *
bind_assumed_bulk(PyObject *self, PyObject *args)
{
    /* bind_assumed_bulk(store, assumed_list, rv, event_cls)
     *   -> (errors, events, new_rv)
     *
     * One C pass over the whole bulk-bind transaction (caller holds the
     * store lock). Per slot, semantics match server._bind_locked: lookup
     * by (namespace, name), uid check, already-bound check, target
     * check, copy-on-write clone of the STORED pod (metadata+spec;
     * status stays shared -- see inline note) with spec.node_name set,
     * _sig_memo dropped, resource_version assigned sequentially from
     * rv+1. errors = [(index, code, msg)] with code 0=NotFound
     * 1=Conflict 2=ValueError 3=internal; events = [event_cls(MODIFIED,
     * pod, rv)] for the successes, in store order. Per-slot failures
     * (including unexpected ones) never abort the slots already
     * committed. Differential parity with the Python fallback:
     * tests/test_native_commit.py. */
    PyObject *store, *assumed_list, *event_cls;
    long rv;
    if (!PyArg_ParseTuple(args, "O!O!lO", &PyDict_Type, &store,
                          &PyList_Type, &assumed_list, &rv, &event_cls))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(assumed_list);
    PyObject *errors = PyList_New(0);
    PyObject *events = PyList_New(0);
    if (errors == NULL || events == NULL) {
        Py_XDECREF(errors);
        Py_XDECREF(events);
        return NULL;
    }

    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *assumed = PyList_GET_ITEM(assumed_list, i);
        PyObject *meta = NULL, *ns = NULL, *name = NULL, *uid = NULL;
        PyObject *spec = NULL, *target = NULL, *key = NULL;
        int errcode = -1;
        int rv_bumped = 0;
        const char *errfmt = NULL;

        meta = PyObject_GetAttr(assumed, str_metadata);
        if (meta == NULL)
            goto hard_fail;
        ns = PyObject_GetAttr(meta, str_namespace);
        name = PyObject_GetAttr(meta, str_name);
        uid = PyObject_GetAttr(meta, str_uid);
        Py_DECREF(meta);
        if (ns == NULL || name == NULL || uid == NULL)
            goto hard_fail;
        spec = PyObject_GetAttr(assumed, str_spec);
        if (spec == NULL)
            goto hard_fail;
        target = PyObject_GetAttr(spec, str_node_name);
        Py_DECREF(spec);
        if (target == NULL)
            goto hard_fail;

        key = PyTuple_Pack(2, ns, name);
        if (key == NULL)
            goto hard_fail;
        PyObject *old = PyDict_GetItemWithError(store, key); /* borrowed */
        if (old == NULL) {
            if (PyErr_Occurred())
                goto hard_fail;
            errcode = 0;
            errfmt = "Pod %U/%U not found";
            goto slot_error;
        }

        PyObject *old_meta = PyObject_GetAttr(old, str_metadata);
        if (old_meta == NULL)
            goto hard_fail;
        PyObject *old_uid = PyObject_GetAttr(old_meta, str_uid);
        if (old_uid == NULL) {
            Py_DECREF(old_meta);
            goto hard_fail;
        }
        int uid_true = PyObject_IsTrue(uid);
        if (uid_true > 0) {
            int eq = PyObject_RichCompareBool(old_uid, uid, Py_EQ);
            if (eq < 0) {
                Py_DECREF(old_uid);
                Py_DECREF(old_meta);
                goto hard_fail;
            }
            if (!eq) {
                Py_DECREF(old_uid);
                Py_DECREF(old_meta);
                errcode = 1;
                errfmt = "pod %U/%U uid mismatch";
                goto slot_error;
            }
        } else if (uid_true < 0) {
            Py_DECREF(old_uid);
            Py_DECREF(old_meta);
            goto hard_fail;
        }
        Py_DECREF(old_uid);

        PyObject *old_spec = PyObject_GetAttr(old, str_spec);
        if (old_spec == NULL) {
            Py_DECREF(old_meta);
            goto hard_fail;
        }
        PyObject *old_nn = PyObject_GetAttr(old_spec, str_node_name);
        if (old_nn == NULL) {
            Py_DECREF(old_spec);
            Py_DECREF(old_meta);
            goto hard_fail;
        }
        int bound = PyObject_IsTrue(old_nn);
        if (bound > 0) {
            int same = PyObject_RichCompareBool(old_nn, target, Py_EQ);
            if (same < 0) {
                Py_DECREF(old_nn);
                Py_DECREF(old_spec);
                Py_DECREF(old_meta);
                goto hard_fail;
            }
            if (!same) {
                Py_DECREF(old_nn);
                Py_DECREF(old_spec);
                Py_DECREF(old_meta);
                errcode = 1;
                errfmt = "pod %U/%U is already bound";
                goto slot_error;
            }
            /* already bound to the SAME node: idempotent success (a
             * retried commit whose first attempt landed, or a restarted
             * scheduler re-driving a recovered placement) -- the store
             * already holds exactly the requested state, so no write,
             * no rv bump, no event (parity: _bind_locked changed=False) */
            Py_DECREF(old_nn);
            Py_DECREF(old_spec);
            Py_DECREF(old_meta);
            Py_DECREF(key);
            Py_DECREF(ns);
            Py_DECREF(name);
            Py_DECREF(uid);
            Py_DECREF(target);
            continue;
        } else if (bound < 0) {
            Py_DECREF(old_nn);
            Py_DECREF(old_spec);
            Py_DECREF(old_meta);
            goto hard_fail;
        }
        Py_DECREF(old_nn);

        /* target required -- checked LAST, matching _bind_locked's
         * check order (uid, already-bound, then target) */
        int target_true = PyObject_IsTrue(target);
        if (target_true < 0) {
            Py_DECREF(old_spec);
            Py_DECREF(old_meta);
            goto hard_fail;
        }
        if (!target_true) {
            Py_DECREF(old_spec);
            Py_DECREF(old_meta);
            errcode = 2;
            errfmt = "binding for %U/%U has no target node";
            goto slot_error;
        }

        /* success: COW clone of the stored pod */
        rv += 1;
        rv_bumped = 1;
        PyObject *rv_obj = PyLong_FromLong(rv);
        if (rv_obj == NULL) {
            Py_DECREF(old_spec);
            Py_DECREF(old_meta);
            goto hard_fail;
        }
        /* status stays SHARED between old and new: every status write
         * goes through guaranteed_update/update_pod_status, which clone
         * status themselves before mutating (the informer read-only
         * contract makes the shared reference safe). */
        PyObject *metac =
            clone_with_dict(old_meta, str_resource_version, rv_obj, NULL);
        Py_DECREF(old_meta);
        PyObject *specc =
            clone_with_dict(old_spec, str_node_name, target, NULL);
        Py_DECREF(old_spec);
        if (metac == NULL || specc == NULL) {
            Py_XDECREF(metac);
            Py_XDECREF(specc);
            Py_DECREF(rv_obj);
            goto hard_fail;
        }

        PyTypeObject *tp = Py_TYPE(old);
        PyObject *podc = tp->tp_alloc(tp, 0);
        PyObject *d = podc ? PyObject_GetAttr(old, str_dict) : NULL;
        PyObject *dc = d ? PyDict_Copy(d) : NULL;
        Py_XDECREF(d);
        int ok = podc != NULL && dc != NULL &&
                 PyDict_SetItem(dc, str_metadata, metac) == 0 &&
                 PyDict_SetItem(dc, str_spec, specc) == 0;
        if (ok && PyDict_Contains(dc, str_sig_memo) == 1)
            ok = PyDict_DelItem(dc, str_sig_memo) == 0;
        if (ok) {
            ok = install_dict(podc, dc) == 0;
            dc = NULL; /* reference consumed by install_dict */
        }
        Py_XDECREF(dc);
        Py_DECREF(metac);
        Py_DECREF(specc);
        if (!ok) {
            Py_XDECREF(podc);
            Py_DECREF(rv_obj);
            goto hard_fail;
        }
        /* event BEFORE the store write: a failure here leaves the slot
         * (and the store) untouched, so the transaction stays
         * event-consistent per slot */
        PyObject *event = PyObject_CallFunctionObjArgs(
            event_cls, str_modified, podc, rv_obj, NULL);
        Py_DECREF(rv_obj);
        if (event == NULL) {
            Py_DECREF(podc);
            goto hard_fail;
        }
        Py_INCREF(old); /* keep alive across the store replace for rollback */
        if (PyDict_SetItem(store, key, podc) < 0) {
            Py_DECREF(old);
            Py_DECREF(podc);
            Py_DECREF(event);
            goto hard_fail;
        }
        int ap = PyList_Append(events, event);
        Py_DECREF(event);
        if (ap < 0) {
            /* roll the slot back so store and events stay consistent */
            if (PyDict_SetItem(store, key, old) < 0)
                PyErr_Clear();
            Py_DECREF(old);
            Py_DECREF(podc);
            goto hard_fail;
        }
        Py_DECREF(old);
        Py_DECREF(podc);
        Py_DECREF(key);
        Py_DECREF(ns);
        Py_DECREF(name);
        Py_DECREF(uid);
        Py_DECREF(target);
        continue;

    slot_error: {
        PyObject *msg = PyUnicode_FromFormat(errfmt, ns, name);
        PyObject *slot =
            msg ? Py_BuildValue("(niN)", i, errcode, msg) : NULL;
        Py_XDECREF(key);
        Py_DECREF(ns);
        Py_DECREF(name);
        Py_DECREF(uid);
        Py_DECREF(target);
        if (slot == NULL)
            goto abort_fail;
        int ap = PyList_Append(errors, slot);
        Py_DECREF(slot);
        if (ap < 0)
            goto abort_fail;
        continue;
    }

    hard_fail: {
        /* An unexpected per-slot failure (allocation, broken attribute)
         * must NOT abort the transaction: earlier slots already mutated
         * the store and their watch events/rv advance must still reach
         * the caller. Convert to a slot error (code 3) and continue;
         * the failed slot itself left the store untouched -- including
         * its provisional rv, matching the Python path where _next_rv
         * only runs after validation. */
        if (rv_bumped)
            rv -= 1;
        Py_XDECREF(key);
        Py_XDECREF(ns);
        Py_XDECREF(name);
        Py_XDECREF(uid);
        Py_XDECREF(target);
        PyObject *et = NULL, *ev = NULL, *tb = NULL;
        PyErr_Fetch(&et, &ev, &tb);
        PyObject *msg = NULL;
        if (ev != NULL)
            msg = PyObject_Str(ev);
        else if (et != NULL)
            msg = PyObject_Str(et);
        else
            msg = PyUnicode_FromString("internal bind error");
        Py_XDECREF(et);
        Py_XDECREF(ev);
        Py_XDECREF(tb);
        if (msg == NULL)
            goto abort_fail;
        PyObject *slot = Py_BuildValue("(niN)", i, 3, msg);
        if (slot == NULL)
            goto abort_fail;
        int ap = PyList_Append(errors, slot);
        Py_DECREF(slot);
        if (ap < 0)
            goto abort_fail;
        continue;
    }

    abort_fail:
        /* only reachable when even recording the error fails (OOM on
         * OOM); nothing sensible left to report */
        PyErr_Clear();
        PyErr_SetString(PyExc_MemoryError,
                        "bind_assumed_bulk: cannot record slot error");
        Py_DECREF(errors);
        Py_DECREF(events);
        return NULL;
    }
    return Py_BuildValue("(NNl)", errors, events, rv);
}

static PyMethodDef methods[] = {
    {"match_compiled", match_compiled, METH_VARARGS,
     "match_compiled(labels, compiled) -> bool"},
    {"match_mask", match_mask, METH_VARARGS,
     "match_mask(labels_list, compiled) -> bytes"},
    {"dict_covers", dict_covers, METH_VARARGS,
     "dict_covers(labels, selector_dict) -> bool"},
    {"cow_clone", cow_clone, METH_VARARGS,
     "cow_clone(obj, attr_names) -> shallow clone with named attrs "
     "also shallow-cloned"},
    {"assume_clones", assume_clones, METH_VARARGS,
     "assume_clones(pods, hosts) -> [assumed clone with spec.node_name "
     "set]"},
    {"commit_gather", commit_gather, METH_VARARGS,
     "commit_gather(solver_infos, order, assignments, names) -> "
     "(pod_infos, clones, hosts)"},
    {"bind_assumed_bulk", bind_assumed_bulk, METH_VARARGS,
     "bind_assumed_bulk(store, assumed_list, rv, event_cls) -> "
     "(errors, events, new_rv)"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_hotpath",
    "native label-selector matching (SURVEY section 2.4 host data plane)",
    -1, methods,
};

PyMODINIT_FUNC
PyInit__hotpath(void)
{
    str_dict = PyUnicode_InternFromString("__dict__");
    str_spec = PyUnicode_InternFromString("spec");
    str_node_name = PyUnicode_InternFromString("node_name");
    str_metadata = PyUnicode_InternFromString("metadata");
    str_namespace = PyUnicode_InternFromString("namespace");
    str_name = PyUnicode_InternFromString("name");
    str_uid = PyUnicode_InternFromString("uid");
    str_resource_version = PyUnicode_InternFromString("resource_version");
    str_sig_memo = PyUnicode_InternFromString("_sig_memo");
    str_modified = PyUnicode_InternFromString("MODIFIED");
    str_pod = PyUnicode_InternFromString("pod");
    if (str_dict == NULL || str_spec == NULL || str_node_name == NULL ||
        str_metadata == NULL || str_namespace == NULL ||
        str_name == NULL || str_uid == NULL || str_resource_version == NULL ||
        str_sig_memo == NULL || str_modified == NULL || str_pod == NULL)
        return NULL;
    return PyModule_Create(&moduledef);
}
