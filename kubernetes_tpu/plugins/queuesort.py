"""PrioritySort queue-sort plugin
(reference framework/plugins/queuesort/priority_sort.go)."""

from __future__ import annotations

from kubernetes_tpu.framework.interface import Plugin, PodInfo


class PrioritySort(Plugin):
    NAME = "PrioritySort"

    def queue_sort_less(self, a: PodInfo, b: PodInfo) -> bool:
        """Higher priority first; ties broken by queue-entry time."""
        p1 = a.pod.spec.priority
        p2 = b.pod.spec.priority
        if p1 != p2:
            return p1 > p2
        return a.timestamp < b.timestamp

    def queue_sort_key(self, pi: PodInfo):
        """Total-order key equivalent to ``queue_sort_less`` -- lets the
        activeQ heap compare natively (C tuple compare) instead of calling
        back into Python per comparison."""
        return (-pi.pod.spec.priority, pi.timestamp)
