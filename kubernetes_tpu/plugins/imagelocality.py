"""ImageLocality score
(reference framework/plugins/imagelocality/image_locality.go)."""

from __future__ import annotations

from typing import Optional, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.framework.interface import CycleState, MAX_NODE_SCORE, Plugin, Status

MB = 1024 * 1024
MIN_THRESHOLD = 23 * MB  # image_locality.go:33
MAX_THRESHOLD = 1000 * MB  # image_locality.go:35


class ImageLocality(Plugin):
    NAME = "ImageLocality"

    def __init__(self, handle=None) -> None:
        self.handle = handle

    def score(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> Tuple[int, Optional[Status]]:
        snapshot = state.read("__snapshot__")
        ni = snapshot.get_node_info(node_name)
        if ni is None or ni.node is None:
            return 0, Status.error(f"node {node_name} not in snapshot")
        total_nodes = snapshot.num_nodes()
        image_counts = snapshot.image_num_nodes()
        # image spread factor: images on many nodes contribute more
        # (image_locality.go:76 scaledImageScore).
        score_sum = 0.0
        for container in pod.spec.containers:
            size = ni.image_states.get(container.image)
            if size is None:
                continue
            spread = image_counts.get(container.image, 0) / total_nodes if total_nodes else 0.0
            score_sum += size * spread
        return self._calculate_priority(score_sum), None

    @staticmethod
    def _calculate_priority(sum_scores: float) -> int:
        """image_locality.go:60 calculatePriority."""
        if sum_scores < MIN_THRESHOLD:
            sum_scores = MIN_THRESHOLD
        elif sum_scores > MAX_THRESHOLD:
            sum_scores = MAX_THRESHOLD
        return int(
            MAX_NODE_SCORE * (sum_scores - MIN_THRESHOLD) / (MAX_THRESHOLD - MIN_THRESHOLD)
        )
