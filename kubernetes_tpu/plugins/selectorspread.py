"""SelectorSpread (DefaultPodTopologySpread), ServiceAffinity, NodeLabel.

References:
- defaultpodtopologyspread/default_pod_topology_spread.go (:49
  zoneWeighting=2/3, :78 Score = matching-pod count on node, :107
  NormalizeScore with zone blending) + helper/spread.go:29 DefaultSelector
  (merged Service/RC selectors + RS/SS selector requirements)
- serviceaffinity/service_affinity.go (:108 createPreFilterState over
  service-mate pods, :233 Filter label homogeneity with backfilled
  "implicit selector", :273 Score, :310 NormalizeScore reversed)
- nodelabel/node_label.go (presence/absence filter + preference score)
- pkg/util/node GetZoneKey: region + ":\x00:" + zone.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.selectors import (
    label_selector_as_dict_matches,
    labels_match_selector,
)
from kubernetes_tpu.api.types import (
    LABEL_REGION_KEYS,
    LABEL_ZONE_KEYS,
    LabelSelector,
    Node,
    Pod,
)
from kubernetes_tpu.cache.node_info import NodeInfo
from kubernetes_tpu.framework.interface import (
    CycleState,
    MAX_NODE_SCORE,
    NodeScore,
    Plugin,
    PreFilterExtensions,
    Status,
)
from kubernetes_tpu.plugins.helpers import default_normalize_score

ZONE_WEIGHTING = 2.0 / 3.0

PRE_SCORE_SELECTOR_KEY = "PreScoreDefaultPodTopologySpread"
PRE_FILTER_SERVICE_AFFINITY_KEY = "PreFilterServiceAffinity"
PRE_SCORE_SERVICE_AFFINITY_KEY = "PreScoreServiceAffinity"

ERR_REASON_SERVICE_AFFINITY = "node(s) didn't match service affinity"


def get_zone_key(node: Optional[Node]) -> str:
    """pkg/util/node GetZoneKey: combined region/zone id."""
    if node is None:
        return ""
    labels = node.metadata.labels
    region = next((labels[k] for k in LABEL_REGION_KEYS if k in labels), "")
    zone = next((labels[k] for k in LABEL_ZONE_KEYS if k in labels), "")
    if not region and not zone:
        return ""
    return region + ":\x00:" + zone


class CombinedSelector:
    """The merged 'default selector' (helper/spread.go:29): Service + RC
    map selectors merged into one label set, plus RS/SS LabelSelector
    requirements ANDed on top. Empty => matches nothing."""

    def __init__(self) -> None:
        self.match_labels: Dict[str, str] = {}
        self.extra: List[LabelSelector] = []

    @property
    def empty(self) -> bool:
        return not self.match_labels and not self.extra

    def matches(self, labels: Dict[str, str]) -> bool:
        if self.empty:
            return False
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        for sel in self.extra:
            if not labels_match_selector(labels, sel):
                return False
        return True


def default_selector(pod: Pod, informers) -> CombinedSelector:
    out = CombinedSelector()
    if informers is None:
        return out
    ns, pod_labels = pod.metadata.namespace, pod.metadata.labels
    for svc in informers.services().list():
        if svc.metadata.namespace == ns and label_selector_as_dict_matches(
            svc.selector, pod_labels
        ):
            out.match_labels.update(svc.selector)
    for rc in informers.replication_controllers().list():
        if rc.metadata.namespace == ns and label_selector_as_dict_matches(
            rc.selector, pod_labels
        ):
            out.match_labels.update(rc.selector)
    for rs in informers.replica_sets().list():
        if rs.metadata.namespace == ns and labels_match_selector(
            pod_labels, rs.selector
        ):
            out.extra.append(rs.selector)
    for ss in informers.stateful_sets().list():
        if ss.metadata.namespace == ns and labels_match_selector(
            pod_labels, ss.selector
        ):
            out.extra.append(ss.selector)
    return out


def _count_matching_pods(
    namespace: str, selector: CombinedSelector, node_info: NodeInfo
) -> int:
    """default_pod_topology_spread.go:206 countMatchingPods."""
    if not node_info.pods or selector.empty:
        return 0
    count = 0
    for p in node_info.pods:
        if (
            p.metadata.namespace == namespace
            and p.metadata.deletion_timestamp is None
            and selector.matches(p.metadata.labels)
        ):
            count += 1
    return count


class DefaultPodTopologySpread(Plugin):
    NAME = "DefaultPodTopologySpread"

    def __init__(self, handle=None) -> None:
        self.handle = handle

    @staticmethod
    def _skip(pod: Pod) -> bool:
        return bool(pod.spec.topology_spread_constraints)

    def pre_score(
        self, state: CycleState, pod: Pod, nodes: List[NodeInfo]
    ) -> Optional[Status]:
        if self._skip(pod):
            return None  # score/normalize will ignore it anyway
        informers = getattr(self.handle, "informers", None)
        state.write(PRE_SCORE_SELECTOR_KEY, default_selector(pod, informers))
        return None

    def score(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> Tuple[int, Optional[Status]]:
        if self._skip(pod):
            return 0, None
        try:
            selector: CombinedSelector = state.read(PRE_SCORE_SELECTOR_KEY)
        except KeyError:
            return 0, Status.error(
                f"error reading {PRE_SCORE_SELECTOR_KEY!r} from cycleState"
            )
        snapshot = state.read("__snapshot__")
        ni = snapshot.get_node_info(node_name)
        if ni is None or ni.node is None:
            return 0, Status.error(f"node {node_name} not in snapshot")
        return _count_matching_pods(pod.metadata.namespace, selector, ni), None

    def normalize_score(
        self, state: CycleState, pod: Pod, scores: List[NodeScore]
    ) -> Optional[Status]:
        """default_pod_topology_spread.go:107: invert counts, blending
        2/3 zone-level spread when zones are labeled."""
        if self._skip(pod):
            return None
        snapshot = state.read("__snapshot__")
        counts_by_zone: Dict[str, int] = {}
        max_by_node = 0
        for ns in scores:
            max_by_node = max(max_by_node, ns.score)
            ni = snapshot.get_node_info(ns.name)
            zone_id = get_zone_key(ni.node if ni else None)
            if zone_id:
                counts_by_zone[zone_id] = counts_by_zone.get(zone_id, 0) + ns.score
        max_by_zone = max(counts_by_zone.values(), default=0)
        have_zones = bool(counts_by_zone)
        for ns in scores:
            f_score = float(MAX_NODE_SCORE)
            if max_by_node > 0:
                f_score = MAX_NODE_SCORE * (max_by_node - ns.score) / max_by_node
            if have_zones:
                ni = snapshot.get_node_info(ns.name)
                zone_id = get_zone_key(ni.node if ni else None)
                if zone_id:
                    zone_score = float(MAX_NODE_SCORE)
                    if max_by_zone > 0:
                        zone_score = (
                            MAX_NODE_SCORE
                            * (max_by_zone - counts_by_zone[zone_id])
                            / max_by_zone
                        )
                    f_score = (
                        f_score * (1.0 - ZONE_WEIGHTING)
                        + ZONE_WEIGHTING * zone_score
                    )
            ns.score = int(f_score)
        return None


class _ServiceAffinityState:
    def __init__(self, matching_pods: List[Pod]) -> None:
        self.matching_pods = matching_pods

    def clone(self) -> "_ServiceAffinityState":
        return _ServiceAffinityState(list(self.matching_pods))


class _ServiceAffinityExtensions(PreFilterExtensions):
    def add_pod(self, state, pod_to_schedule, pod_to_add, node_info):
        try:
            s: _ServiceAffinityState = state.read(PRE_FILTER_SERVICE_AFFINITY_KEY)
        except KeyError:
            return None
        if pod_to_add.metadata.namespace != pod_to_schedule.metadata.namespace:
            return None
        if pod_to_schedule.metadata.labels and all(
            pod_to_add.metadata.labels.get(k) == v
            for k, v in pod_to_schedule.metadata.labels.items()
        ):
            s.matching_pods.append(pod_to_add)
        return None

    def remove_pod(self, state, pod_to_schedule, pod_to_remove, node_info):
        try:
            s: _ServiceAffinityState = state.read(PRE_FILTER_SERVICE_AFFINITY_KEY)
        except KeyError:
            return None
        s.matching_pods = [
            p for p in s.matching_pods
            if not (
                p.metadata.name == pod_to_remove.metadata.name
                and p.metadata.namespace == pod_to_remove.metadata.namespace
            )
        ]
        return None


class ServiceAffinity(Plugin):
    """Policy-era plugin: service-mate pods land on nodes with identical
    values for the configured label keys."""

    NAME = "ServiceAffinity"

    def __init__(self, args: Optional[dict] = None, handle=None) -> None:
        args = args or {}
        self.affinity_labels: List[str] = list(args.get("affinity_labels", ()))
        self.anti_affinity_labels_preference: List[str] = list(
            args.get("anti_affinity_labels_preference", ())
        )
        self.handle = handle
        self._extensions = _ServiceAffinityExtensions()

    def _service_mate_pods(self, state: CycleState, pod: Pod) -> List[Pod]:
        """Scheduled pods selected by any service that also selects
        ``pod`` (service_affinity.go:108)."""
        informers = getattr(self.handle, "informers", None)
        if informers is None:
            return []
        snapshot = state.read("__snapshot__")
        selectors = [
            svc.selector
            for svc in informers.services().list()
            if svc.metadata.namespace == pod.metadata.namespace
            and label_selector_as_dict_matches(
                svc.selector, pod.metadata.labels
            )
        ]
        if not selectors:
            return []
        out = []
        for p in snapshot.list_pods():
            if p.metadata.namespace != pod.metadata.namespace:
                continue
            if any(
                label_selector_as_dict_matches(sel, p.metadata.labels)
                for sel in selectors
            ):
                out.append(p)
        return out

    def pre_filter(self, state: CycleState, pod: Pod) -> Optional[Status]:
        """service_affinity.go:108 createPreFilterState: matching pods are
        same-namespace pods carrying ALL of the incoming pod's labels (the
        pod's own labels as selector) -- the same predicate AddPod uses, so
        incremental updates equal a recompute."""
        if not self.affinity_labels:
            return None
        snapshot = state.read("__snapshot__")
        own = pod.metadata.labels
        matching = [
            p
            for p in snapshot.list_pods()
            if p.metadata.namespace == pod.metadata.namespace
            and own
            and all(p.metadata.labels.get(k) == v for k, v in own.items())
        ]
        state.write(
            PRE_FILTER_SERVICE_AFFINITY_KEY, _ServiceAffinityState(matching)
        )
        return None

    def pre_filter_extensions(self) -> PreFilterExtensions:
        return self._extensions

    def filter(
        self, state: CycleState, pod: Pod, node_info: NodeInfo
    ) -> Optional[Status]:
        """service_affinity.go:233: backfill unset affinity labels from an
        already-scheduled service mate's node, then require the candidate
        node to match them all."""
        if not self.affinity_labels:
            return None
        node = node_info.node
        if node is None:
            return Status.error("node not found")
        wanted: Dict[str, str] = {
            k: pod.spec.node_selector[k]
            for k in self.affinity_labels
            if k in pod.spec.node_selector
        }
        if len(wanted) < len(self.affinity_labels):
            try:
                s: _ServiceAffinityState = state.read(
                    PRE_FILTER_SERVICE_AFFINITY_KEY
                )
            except KeyError:
                self.pre_filter(state, pod)
                s = state.read(PRE_FILTER_SERVICE_AFFINITY_KEY)
            snapshot = state.read("__snapshot__")
            scheduled = [
                p for p in s.matching_pods if p.spec.node_name
            ]
            if scheduled:
                mate_ni = snapshot.get_node_info(scheduled[0].spec.node_name)
                if mate_ni is not None and mate_ni.node is not None:
                    for k in self.affinity_labels:
                        if k not in wanted and k in mate_ni.node.metadata.labels:
                            wanted[k] = mate_ni.node.metadata.labels[k]
        for k, v in wanted.items():
            if node.metadata.labels.get(k) != v:
                return Status.unschedulable(ERR_REASON_SERVICE_AFFINITY)
        return None

    def pre_score(
        self, state: CycleState, pod: Pod, nodes: List[NodeInfo]
    ) -> Optional[Status]:
        """Compute the (node-independent) service-mate set once per cycle;
        score() reads it instead of rescanning services x pods per node."""
        if self.anti_affinity_labels_preference:
            state.write(
                PRE_SCORE_SERVICE_AFFINITY_KEY,
                self._service_mate_pods(state, pod),
            )
        return None

    def score(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> Tuple[int, Optional[Status]]:
        """service_affinity.go:273: count service mates on nodes sharing
        this node's values for the preference labels."""
        if not self.anti_affinity_labels_preference:
            return 0, None
        snapshot = state.read("__snapshot__")
        ni = snapshot.get_node_info(node_name)
        if ni is None or ni.node is None:
            return 0, Status.error(f"node {node_name} not in snapshot")
        try:
            mates = state.read(PRE_SCORE_SERVICE_AFFINITY_KEY)
        except KeyError:
            mates = self._service_mate_pods(state, pod)
        score = 0
        for label in self.anti_affinity_labels_preference:
            node_val = ni.node.metadata.labels.get(label)
            if node_val is None:
                continue
            for mate in mates:
                if not mate.spec.node_name:
                    continue
                mate_ni = snapshot.get_node_info(mate.spec.node_name)
                if (
                    mate_ni is not None
                    and mate_ni.node is not None
                    and mate_ni.node.metadata.labels.get(label) == node_val
                ):
                    score += 1
        return score, None

    def normalize_score(
        self, state: CycleState, pod: Pod, scores: List[NodeScore]
    ) -> Optional[Status]:
        if not self.anti_affinity_labels_preference:
            return None
        default_normalize_score(MAX_NODE_SCORE, True, scores)  # reversed
        return None


ERR_REASON_NODE_LABEL = "node(s) didn't have the requested labels"


class NodeLabel(Plugin):
    """Policy-era presence/absence label plugin (nodelabel/node_label.go)."""

    NAME = "NodeLabel"

    def __init__(self, args: Optional[dict] = None) -> None:
        args = args or {}
        self.present_labels = list(args.get("present_labels", ()))
        self.absent_labels = list(args.get("absent_labels", ()))
        self.present_labels_preference = list(
            args.get("present_labels_preference", ())
        )
        self.absent_labels_preference = list(
            args.get("absent_labels_preference", ())
        )
        conflict = set(self.present_labels) & set(self.absent_labels)
        if conflict:
            raise ValueError(
                f"labels in both present and absent lists: {sorted(conflict)}"
            )

    def filter(
        self, state: CycleState, pod: Pod, node_info: NodeInfo
    ) -> Optional[Status]:
        node = node_info.node
        if node is None:
            return Status.error("node not found")
        labels = node.metadata.labels
        for l in self.present_labels:
            if l not in labels:
                return Status.unschedulable_and_unresolvable(
                    ERR_REASON_NODE_LABEL
                )
        for l in self.absent_labels:
            if l in labels:
                return Status.unschedulable_and_unresolvable(
                    ERR_REASON_NODE_LABEL
                )
        return None

    def score(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> Tuple[int, Optional[Status]]:
        snapshot = state.read("__snapshot__")
        ni = snapshot.get_node_info(node_name)
        if ni is None or ni.node is None:
            return 0, Status.error(f"node {node_name} not in snapshot")
        labels = ni.node.metadata.labels
        size = len(self.present_labels_preference) + len(
            self.absent_labels_preference
        )
        if size == 0:
            return 0, None
        score = 0
        for l in self.present_labels_preference:
            if l in labels:
                score += MAX_NODE_SCORE
        for l in self.absent_labels_preference:
            if l not in labels:
                score += MAX_NODE_SCORE
        return score // size, None
