"""NodeUnschedulable filter
(reference framework/plugins/nodeunschedulable/node_unschedulable.go)."""

from __future__ import annotations

from typing import Optional

from kubernetes_tpu.api.types import (
    TAINT_EFFECT_NO_SCHEDULE,
    Pod,
    Taint,
)
from kubernetes_tpu.cache.node_info import NodeInfo
from kubernetes_tpu.framework.interface import CycleState, Plugin, Status

TAINT_NODE_UNSCHEDULABLE = "node.kubernetes.io/unschedulable"

ERR_REASON_UNSCHEDULABLE = "node(s) were unschedulable"


class NodeUnschedulable(Plugin):
    NAME = "NodeUnschedulable"

    def filter(
        self, state: CycleState, pod: Pod, node_info: NodeInfo
    ) -> Optional[Status]:
        if node_info.node is None:
            return Status.unschedulable_and_unresolvable("node not found")
        if not node_info.node.spec.unschedulable:
            return None
        # A pod tolerating the unschedulable taint may still land here
        # (node_unschedulable.go:58).
        fake_taint = Taint(
            key=TAINT_NODE_UNSCHEDULABLE, effect=TAINT_EFFECT_NO_SCHEDULE
        )
        if any(t.tolerates(fake_taint) for t in pod.spec.tolerations):
            return None
        return Status.unschedulable_and_unresolvable(ERR_REASON_UNSCHEDULABLE)
