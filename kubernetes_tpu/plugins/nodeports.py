"""NodePorts PreFilter+Filter
(reference framework/plugins/nodeports/node_ports.go)."""

from __future__ import annotations

from typing import List, Optional, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.cache.node_info import NodeInfo, pod_host_ports
from kubernetes_tpu.framework.interface import CycleState, Plugin, Status

_STATE_KEY = "PreFilterNodePorts"
ERR_REASON = "node(s) didn't have free ports for the requested pod ports"


class _PortsState(list):
    def clone(self) -> "_PortsState":
        return _PortsState(self)


class NodePorts(Plugin):
    NAME = "NodePorts"

    def pre_filter(self, state: CycleState, pod: Pod) -> Optional[Status]:
        state.write(_STATE_KEY, _PortsState(pod_host_ports(pod)))
        return None

    def filter(
        self, state: CycleState, pod: Pod, node_info: NodeInfo
    ) -> Optional[Status]:
        try:
            want: List[Tuple[str, str, int]] = state.read(_STATE_KEY)
        except KeyError:
            want = pod_host_ports(pod)
        for ip, proto, port in want:
            if node_info.used_ports.conflicts(ip, proto, port):
                return Status.unschedulable(ERR_REASON)
        return None
