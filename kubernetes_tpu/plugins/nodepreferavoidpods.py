"""NodePreferAvoidPods score
(reference framework/plugins/nodepreferavoidpods/node_prefer_avoid_pods.go).

Nodes annotated with scheduler.alpha.kubernetes.io/preferAvoidPods get score
0 (vs 100) for pods controlled by a RC/RS listed in the annotation; the
default weight is 10000 so this dominates other scorers.
"""

from __future__ import annotations

import json
from typing import Optional, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.framework.interface import CycleState, MAX_NODE_SCORE, Plugin, Status

ANNOTATION_KEY = "scheduler.alpha.kubernetes.io/preferAvoidPods"


class NodePreferAvoidPods(Plugin):
    NAME = "NodePreferAvoidPods"

    def score(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> Tuple[int, Optional[Status]]:
        snapshot = state.read("__snapshot__")
        ni = snapshot.get_node_info(node_name)
        if ni is None or ni.node is None:
            return 0, Status.error(f"node {node_name} not in snapshot")
        raw = ni.node.metadata.annotations.get(ANNOTATION_KEY)
        if not raw:
            return MAX_NODE_SCORE, None
        controller = next(
            (ref for ref in pod.metadata.owner_references if ref.controller), None
        )
        # Only RC/RS-controlled pods are subject to avoidance
        # (node_prefer_avoid_pods.go:53).
        if controller is None or controller.kind not in (
            "ReplicationController",
            "ReplicaSet",
        ):
            return MAX_NODE_SCORE, None
        try:
            avoids = json.loads(raw).get("preferAvoidPods", [])
        except (ValueError, AttributeError):
            return MAX_NODE_SCORE, None
        for entry in avoids:
            ref = entry.get("podSignature", {}).get("podController", {})
            # exact UID equality (node_prefer_avoid_pods.go): an entry
            # without a uid matches nothing
            if (
                ref.get("kind") == controller.kind
                and ref.get("uid") == controller.uid
            ):
                return 0, None
        return MAX_NODE_SCORE, None
