"""Shared scoring helpers."""

from __future__ import annotations

from typing import List

from kubernetes_tpu.framework.interface import MAX_NODE_SCORE, NodeScore


def default_normalize_score(
    max_priority: int, reverse: bool, scores: List[NodeScore]
) -> None:
    """Reference pkg/scheduler/framework/plugins/helper/normalize_score.go:
    scale to [0, max_priority] by the max raw score; optionally reverse."""
    max_count = max((ns.score for ns in scores), default=0)
    if max_count == 0:
        if reverse:
            for ns in scores:
                ns.score = max_priority
        return
    for ns in scores:
        s = max_priority * ns.score // max_count
        ns.score = (max_priority - s) if reverse else s
