"""InterPodAffinity: required (Filter) and preferred (Score) pod
(anti-)affinity.

Reference: /root/reference/pkg/scheduler/framework/plugins/interpodaffinity/
(filtering.go: preFilterState :52, topologyToMatchedTermCount :119,
getTPMapMatchingExistingAntiAffinity :212,
getTPMapMatchingIncomingAffinityAntiAffinity :256, PreFilter :330,
satisfiesExistingPodsAntiAffinity :404, satisfiesPodsAffinityAntiAffinity
:479, Filter :516; scoring.go: preScoreState :36, processExistingPod :111,
PreScore :169, Score :267, NormalizeScore :294) and
pkg/scheduler/util/topologies.go (:28 GetNamespacesFromPodAffinityTerm,
:40 PodMatchesTermsNamespaceAndSelector).

On TPU the O(pods x nodes) prefilter becomes a single scatter pass into
``[num_topology_pairs]`` count tensors (kubernetes_tpu.ops); this host
implementation is the correctness oracle.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from kubernetes_tpu.api.selectors import labels_match_selector
from kubernetes_tpu.api.types import (
    LabelSelector,
    Node,
    Pod,
    PodAffinityTerm,
    WeightedPodAffinityTerm,
)
from kubernetes_tpu.cache.node_info import NodeInfo
from kubernetes_tpu.framework.interface import (
    CycleState,
    MAX_NODE_SCORE,
    NodeScore,
    Plugin,
    PreFilterExtensions,
    Status,
)

PRE_FILTER_STATE_KEY = "PreFilterInterPodAffinity"
PRE_SCORE_STATE_KEY = "PreScoreInterPodAffinity"

ERR_REASON_AFFINITY_NOT_MATCH = "node(s) didn't match pod affinity/anti-affinity"
ERR_REASON_EXISTING_ANTI_AFFINITY = (
    "node(s) didn't satisfy existing pods anti-affinity rules"
)
ERR_REASON_AFFINITY_RULES = "node(s) didn't match pod affinity rules"
ERR_REASON_ANTI_AFFINITY_RULES = "node(s) didn't match pod anti-affinity rules"

DEFAULT_HARD_POD_AFFINITY_WEIGHT = 1

TopologyPair = Tuple[str, str]


def _term_namespaces(pod: Pod, term: PodAffinityTerm) -> Set[str]:
    """Empty term namespaces default to the owner pod's namespace
    (topologies.go:28)."""
    if term.namespaces:
        return set(term.namespaces)
    return {pod.metadata.namespace}


def _pod_matches_term(pod: Pod, namespaces: Set[str], selector) -> bool:
    """topologies.go:40 PodMatchesTermsNamespaceAndSelector."""
    if pod.metadata.namespace not in namespaces:
        return False
    return labels_match_selector(pod.metadata.labels, selector)


class _Term:
    """Processed affinity term (filtering.go:170 affinityTerm)."""

    __slots__ = ("namespaces", "selector", "topology_key", "weight")

    def __init__(
        self, owner: Pod, term: PodAffinityTerm, weight: int = 0
    ) -> None:
        self.namespaces = _term_namespaces(owner, term)
        self.selector: Optional[LabelSelector] = term.label_selector
        self.topology_key = term.topology_key
        self.weight = weight

    def matches(self, pod: Pod) -> bool:
        return _pod_matches_term(pod, self.namespaces, self.selector)


def _required_affinity_terms(pod: Pod) -> List[PodAffinityTerm]:
    a = pod.spec.affinity
    if a is None or a.pod_affinity is None:
        return []
    return a.pod_affinity.required_during_scheduling


def _required_anti_affinity_terms(pod: Pod) -> List[PodAffinityTerm]:
    a = pod.spec.affinity
    if a is None or a.pod_anti_affinity is None:
        return []
    return a.pod_anti_affinity.required_during_scheduling


def _preferred_terms(terms: List[WeightedPodAffinityTerm], owner: Pod) -> List[_Term]:
    return [
        _Term(owner, wt.pod_affinity_term, wt.weight) for wt in terms
    ]


class TermCount:
    """topologyToMatchedTermCount (filtering.go:119): (key,value) -> count."""

    __slots__ = ("counts",)

    def __init__(self) -> None:
        self.counts: Dict[TopologyPair, int] = {}

    def clone(self) -> "TermCount":
        tc = TermCount()
        tc.counts = dict(self.counts)
        return tc

    def get(self, pair: TopologyPair) -> int:
        return self.counts.get(pair, 0)

    def _bump(self, pair: TopologyPair, value: int) -> None:
        n = self.counts.get(pair, 0) + value
        if n == 0:
            self.counts.pop(pair, None)
        else:
            self.counts[pair] = n

    def update_with_affinity_terms(
        self, target: Pod, target_node: Node, terms: List[_Term], value: int
    ) -> None:
        """Bump every term's pair iff target matches ALL terms
        (filtering.go:135)."""
        if not terms or not all(t.matches(target) for t in terms):
            return
        for t in terms:
            tp_val = target_node.metadata.labels.get(t.topology_key)
            if tp_val is not None:
                self._bump((t.topology_key, tp_val), value)

    def update_with_anti_affinity_terms(
        self, target: Pod, target_node: Node, terms: List[_Term], value: int
    ) -> None:
        """Bump per-term on ANY match (filtering.go:153)."""
        for t in terms:
            if t.matches(target):
                tp_val = target_node.metadata.labels.get(t.topology_key)
                if tp_val is not None:
                    self._bump((t.topology_key, tp_val), value)


class PreFilterState:
    """filtering.go:52 preFilterState."""

    def __init__(self) -> None:
        self.existing_anti_affinity = TermCount()
        self.affinity = TermCount()
        self.anti_affinity = TermCount()

    def clone(self) -> "PreFilterState":
        s = PreFilterState()
        s.existing_anti_affinity = self.existing_anti_affinity.clone()
        s.affinity = self.affinity.clone()
        s.anti_affinity = self.anti_affinity.clone()
        return s

    def update_with_pod(
        self, updated: Pod, pod: Pod, node: Optional[Node], multiplier: int
    ) -> None:
        """filtering.go:75 updateWithPod."""
        if node is None:
            return
        up_aff = updated.spec.affinity
        if up_aff is not None and up_aff.pod_anti_affinity is not None:
            terms = [
                _Term(updated, t)
                for t in _required_anti_affinity_terms(updated)
            ]
            self.existing_anti_affinity.update_with_anti_affinity_terms(
                pod, node, terms, multiplier
            )
        if pod.spec.affinity is not None and updated.spec.node_name:
            aff_terms = [_Term(pod, t) for t in _required_affinity_terms(pod)]
            if aff_terms:
                self.affinity.update_with_affinity_terms(
                    updated, node, aff_terms, multiplier
                )
            anti_terms = [
                _Term(pod, t) for t in _required_anti_affinity_terms(pod)
            ]
            if anti_terms:
                self.anti_affinity.update_with_anti_affinity_terms(
                    updated, node, anti_terms, multiplier
                )


class _AffinityPreFilterExtensions(PreFilterExtensions):
    def add_pod(self, state, pod_to_schedule, pod_to_add, node_info):
        s = _get_pre_filter_state(state)
        if isinstance(s, Status):
            return s
        s.update_with_pod(pod_to_add, pod_to_schedule, node_info.node, 1)
        return None

    def remove_pod(self, state, pod_to_schedule, pod_to_remove, node_info):
        s = _get_pre_filter_state(state)
        if isinstance(s, Status):
            return s
        s.update_with_pod(pod_to_remove, pod_to_schedule, node_info.node, -1)
        return None


def _get_pre_filter_state(state: CycleState):
    try:
        return state.read(PRE_FILTER_STATE_KEY)
    except KeyError:
        return Status.error(
            f"error reading {PRE_FILTER_STATE_KEY!r} from cycleState"
        )


class PreScoreState:
    """scoring.go:36 preScoreState."""

    def __init__(self) -> None:
        self.topology_score: Dict[str, Dict[str, int]] = {}
        self.affinity_terms: List[_Term] = []
        self.anti_affinity_terms: List[_Term] = []

    def clone(self) -> "PreScoreState":
        return self


class InterPodAffinity(Plugin):
    NAME = "InterPodAffinity"

    def __init__(self, args: Optional[dict] = None, handle=None) -> None:
        args = args or {}
        self.hard_pod_affinity_weight = int(
            args.get("hard_pod_affinity_weight", DEFAULT_HARD_POD_AFFINITY_WEIGHT)
        )
        self.handle = handle
        self._extensions = _AffinityPreFilterExtensions()

    # -- PreFilter / Filter -------------------------------------------------

    def pre_filter(self, state: CycleState, pod: Pod) -> Optional[Status]:
        """filtering.go:330 PreFilter."""
        snapshot = state.read("__snapshot__")
        all_nodes = snapshot.list_node_infos()
        affinity_nodes = snapshot.have_pods_with_affinity_list

        s = PreFilterState()
        # (1) existing pods' anti-affinity terms that match the incoming pod
        #     (filtering.go:212; only nodes that have pods with affinity).
        for ni in affinity_nodes:
            node = ni.node
            if node is None:
                continue
            for existing in ni.pods_with_affinity:
                terms = [
                    _Term(existing, t)
                    for t in _required_anti_affinity_terms(existing)
                ]
                s.existing_anti_affinity.update_with_anti_affinity_terms(
                    pod, node, terms, 1
                )
        # (2) existing pods matching the incoming pod's terms
        #     (filtering.go:256; all nodes x all pods).
        aff_terms = [_Term(pod, t) for t in _required_affinity_terms(pod)]
        anti_terms = [_Term(pod, t) for t in _required_anti_affinity_terms(pod)]
        if aff_terms or anti_terms:
            for ni in all_nodes:
                node = ni.node
                if node is None:
                    continue
                for existing in ni.pods:
                    s.affinity.update_with_affinity_terms(
                        existing, node, aff_terms, 1
                    )
                    s.anti_affinity.update_with_anti_affinity_terms(
                        existing, node, anti_terms, 1
                    )
        state.write(PRE_FILTER_STATE_KEY, s)
        return None

    def pre_filter_extensions(self) -> PreFilterExtensions:
        return self._extensions

    def filter(
        self, state: CycleState, pod: Pod, node_info: NodeInfo
    ) -> Optional[Status]:
        """filtering.go:516 Filter."""
        node = node_info.node
        if node is None:
            return Status.error("node not found")
        s = _get_pre_filter_state(state)
        if isinstance(s, Status):
            return s

        # existing pods' anti-affinity (filtering.go:404): any label pair of
        # this node with a positive count blocks the pod.
        for key, value in node.metadata.labels.items():
            if s.existing_anti_affinity.get((key, value)) > 0:
                return Status.unschedulable(
                    ERR_REASON_AFFINITY_NOT_MATCH,
                    ERR_REASON_EXISTING_ANTI_AFFINITY,
                )

        aff_terms = _required_affinity_terms(pod)
        anti_terms = _required_anti_affinity_terms(pod)
        if not aff_terms and not anti_terms:
            return None

        # incoming affinity: node must carry every term's topology pair with
        # a positive count (filtering.go:420 nodeMatchesAllTopologyTerms).
        if aff_terms:
            matches_all = True
            for term in aff_terms:
                tp_val = node.metadata.labels.get(term.topology_key)
                if tp_val is None or s.affinity.get(
                    (term.topology_key, tp_val)
                ) <= 0:
                    matches_all = False
                    break
            if not matches_all:
                # first-pod-in-series escape hatch (filtering.go:494): no pod
                # anywhere matches and the pod matches its own terms.
                terms = [_Term(pod, t) for t in aff_terms]
                self_match = bool(terms) and all(
                    t.matches(pod) for t in terms
                )
                if s.affinity.counts or not self_match:
                    return Status.unschedulable_and_unresolvable(
                        ERR_REASON_AFFINITY_NOT_MATCH,
                        ERR_REASON_AFFINITY_RULES,
                    )

        # incoming anti-affinity: any positive pair blocks
        # (filtering.go:437 nodeMatchesAnyTopologyTerm).
        for term in anti_terms:
            tp_val = node.metadata.labels.get(term.topology_key)
            if tp_val is not None and s.anti_affinity.get(
                (term.topology_key, tp_val)
            ) > 0:
                return Status.unschedulable(
                    ERR_REASON_AFFINITY_NOT_MATCH,
                    ERR_REASON_ANTI_AFFINITY_RULES,
                )
        return None

    # -- PreScore / Score ---------------------------------------------------

    def pre_score(
        self, state: CycleState, pod: Pod, nodes: List[NodeInfo]
    ) -> Optional[Status]:
        """scoring.go:169 PreScore."""
        s = PreScoreState()
        state.write(PRE_SCORE_STATE_KEY, s)
        if not nodes:
            return None
        snapshot = state.read("__snapshot__")
        affinity = pod.spec.affinity
        has_aff = affinity is not None and affinity.pod_affinity is not None
        has_anti = affinity is not None and affinity.pod_anti_affinity is not None
        if has_aff:
            s.affinity_terms = _preferred_terms(
                affinity.pod_affinity.preferred_during_scheduling, pod
            )
        if has_anti:
            s.anti_affinity_terms = _preferred_terms(
                affinity.pod_anti_affinity.preferred_during_scheduling, pod
            )
        # Unless the incoming pod has constraints, only nodes hosting pods
        # with affinity matter (scoring.go:193).
        if has_aff or has_anti:
            all_nodes = snapshot.list_node_infos()
        else:
            all_nodes = snapshot.have_pods_with_affinity_list
        for ni in all_nodes:
            node = ni.node
            if node is None:
                continue
            pods = ni.pods if (has_aff or has_anti) else ni.pods_with_affinity
            for existing in pods:
                self._process_existing_pod(s, existing, node, pod)
        return None

    def _process_term(
        self,
        s: PreScoreState,
        term: _Term,
        pod_to_check: Pod,
        fixed_node: Node,
        multiplier: int,
    ) -> None:
        """scoring.go:79 processTerm."""
        if not fixed_node.metadata.labels:
            return
        tp_val = fixed_node.metadata.labels.get(term.topology_key)
        if tp_val is None or not term.matches(pod_to_check):
            return
        by_val = s.topology_score.setdefault(term.topology_key, {})
        by_val[tp_val] = by_val.get(tp_val, 0) + term.weight * multiplier

    def _process_existing_pod(
        self, s: PreScoreState, existing: Pod, existing_node: Node, incoming: Pod
    ) -> None:
        """scoring.go:111 processExistingPod."""
        for term in s.affinity_terms:
            self._process_term(s, term, existing, existing_node, 1)
        for term in s.anti_affinity_terms:
            self._process_term(s, term, existing, existing_node, -1)

        ex_aff = existing.spec.affinity
        if ex_aff is not None and ex_aff.pod_affinity is not None:
            if self.hard_pod_affinity_weight > 0:
                for t in ex_aff.pod_affinity.required_during_scheduling:
                    term = _Term(existing, t, self.hard_pod_affinity_weight)
                    self._process_term(s, term, incoming, existing_node, 1)
            for term in _preferred_terms(
                ex_aff.pod_affinity.preferred_during_scheduling, existing
            ):
                self._process_term(s, term, incoming, existing_node, 1)
        if ex_aff is not None and ex_aff.pod_anti_affinity is not None:
            for term in _preferred_terms(
                ex_aff.pod_anti_affinity.preferred_during_scheduling, existing
            ):
                self._process_term(s, term, incoming, existing_node, -1)

    def score(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> Tuple[int, Optional[Status]]:
        """scoring.go:267 Score."""
        snapshot = state.read("__snapshot__")
        ni = snapshot.get_node_info(node_name)
        if ni is None or ni.node is None:
            return 0, Status.error(f"node {node_name} not in snapshot")
        try:
            s: PreScoreState = state.read(PRE_SCORE_STATE_KEY)
        except KeyError:
            return 0, Status.error(
                f"error reading {PRE_SCORE_STATE_KEY!r} from cycleState"
            )
        score = 0
        for tp_key, by_val in s.topology_score.items():
            tp_val = ni.node.metadata.labels.get(tp_key)
            if tp_val is not None:
                score += by_val.get(tp_val, 0)
        return score, None

    def normalize_score(
        self, state: CycleState, pod: Pod, scores: List[NodeScore]
    ) -> Optional[Status]:
        """scoring.go:294 NormalizeScore: linear rescale of
        [min, max] -> [0, 100]; zero-initialized extremes match reference."""
        try:
            s: PreScoreState = state.read(PRE_SCORE_STATE_KEY)
        except KeyError:
            return Status.error(
                f"error reading {PRE_SCORE_STATE_KEY!r} from cycleState"
            )
        if not s.topology_score:
            return None
        max_count = 0
        min_count = 0
        for ns in scores:
            max_count = max(max_count, ns.score)
            min_count = min(min_count, ns.score)
        diff = max_count - min_count
        for ns in scores:
            if diff > 0:
                ns.score = int(MAX_NODE_SCORE * (ns.score - min_count) / diff)
            else:
                ns.score = 0
        return None
