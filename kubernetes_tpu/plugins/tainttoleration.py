"""TaintToleration Filter+PreScore+Score
(reference framework/plugins/tainttoleration/taint_toleration.go)."""

from __future__ import annotations

from typing import List, Optional, Tuple

from kubernetes_tpu.api.types import (
    TAINT_EFFECT_NO_EXECUTE,
    TAINT_EFFECT_NO_SCHEDULE,
    TAINT_EFFECT_PREFER_NO_SCHEDULE,
    Pod,
    Taint,
    Toleration,
)
from kubernetes_tpu.cache.node_info import NodeInfo
from kubernetes_tpu.framework.interface import CycleState, Plugin, Status
from kubernetes_tpu.plugins.helpers import default_normalize_score

_STATE_KEY = "PreScoreTaintToleration"


def find_untolerated_taint(
    taints: List[Taint], tolerations: List[Toleration], effects: List[str]
) -> Optional[Taint]:
    for taint in taints:
        if taint.effect not in effects:
            continue
        if not any(t.tolerates(taint) for t in tolerations):
            return taint
    return None


class _TolerationState(list):
    def clone(self) -> "_TolerationState":
        return _TolerationState(self)


class TaintToleration(Plugin):
    NAME = "TaintToleration"

    def filter(
        self, state: CycleState, pod: Pod, node_info: NodeInfo
    ) -> Optional[Status]:
        if node_info.node is None:
            return Status.error("node not found")
        taint = find_untolerated_taint(
            node_info.node.spec.taints,
            pod.spec.tolerations,
            [TAINT_EFFECT_NO_SCHEDULE, TAINT_EFFECT_NO_EXECUTE],
        )
        if taint is not None:
            return Status.unschedulable_and_unresolvable(
                f"node(s) had taint {{{taint.key}: {taint.value}}}, "
                "that the pod didn't tolerate"
            )
        return None

    def pre_score(
        self, state: CycleState, pod: Pod, nodes: List[NodeInfo]
    ) -> Optional[Status]:
        # Only PreferNoSchedule-effect tolerations matter for scoring
        # (taint_toleration.go:97 getAllTolerationPreferNoSchedule).
        tolerations = [
            t
            for t in pod.spec.tolerations
            if not t.effect or t.effect == TAINT_EFFECT_PREFER_NO_SCHEDULE
        ]
        state.write(_STATE_KEY, _TolerationState(tolerations))
        return None

    def score(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> Tuple[int, Optional[Status]]:
        snapshot = state.read("__snapshot__")
        ni = snapshot.get_node_info(node_name)
        if ni is None or ni.node is None:
            return 0, Status.error(f"node {node_name} not in snapshot")
        try:
            tolerations = state.read(_STATE_KEY)
        except KeyError:
            return 0, Status.error("no prescore state")
        count = sum(
            1
            for taint in ni.node.spec.taints
            if taint.effect == TAINT_EFFECT_PREFER_NO_SCHEDULE
            and not any(t.tolerates(taint) for t in tolerations)
        )
        return count, None

    def normalize_score(self, state, pod, scores) -> Optional[Status]:
        # Fewer intolerable taints => higher score (reversed normalize).
        default_normalize_score(100, True, scores)
        return None
