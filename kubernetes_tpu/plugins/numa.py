"""NUMA-aligned extended-resource placement (BASELINE config #4:
"nvidia.com/gpu + topology-aware NUMA requests").

The reference has no scheduler-side NUMA model: alignment lives in the
kubelet's device manager + TopologyManager
(/root/reference/pkg/kubelet/cm/devicemanager/manager.go:103
GetTopologyHints, :128 Allocate) -- a pod that schedules onto a node
whose free devices cannot be aligned is REJECTED at admission
(TopologyAffinityError) and retries elsewhere. This plugin lifts the
hint semantics to scheduling time so aligned pods never bounce:

- a node advertises its device topology with the label
  ``numa.kubernetes-tpu.io/gpu-groups`` = "4_4" (devices per NUMA
  group; the device-manager's per-socket pools),
- a pod opts in with the annotation
  ``numa.kubernetes-tpu.io/aligned`` = "<resource>", requesting that
  its ENTIRE <resource> request fit inside one NUMA group,
- Filter rejects nodes where no group has enough free devices
  (mirroring the hint "no single-NUMA placement exists"),
- Score implements the device-manager's best-fit preference: tighter
  surviving groups score higher (keep big groups whole),
- Reserve records the chosen (best-fit) group in the pod annotation
  ``numa.kubernetes-tpu.io/assigned-group`` so later pods account the
  group's usage; Unreserve removes it.

Aligned pods take the sequential host path (scheduler/batch.py
solver_supported routes on the annotation): group bookkeeping is a
per-node argmin over free groups with in-flight state, which the batch
solver does not model.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import dataclasses

from kubernetes_tpu.api.types import Pod, pod_resource_requests
from kubernetes_tpu.cache.node_info import NodeInfo
from kubernetes_tpu.framework.interface import CycleState, Plugin, Status

GROUPS_LABEL = "numa.kubernetes-tpu.io/gpu-groups"
ALIGNED_ANNOTATION = "numa.kubernetes-tpu.io/aligned"
ASSIGNED_ANNOTATION = "numa.kubernetes-tpu.io/assigned-group"


def aligned_resource(pod: Pod) -> str:
    """The resource name the pod wants single-NUMA-aligned ("" none)."""
    return pod.metadata.annotations.get(ALIGNED_ANNOTATION, "")


def _aligned_request(pod: Pod, resource: str) -> int:
    return int(pod_resource_requests(pod).get(resource, 0))


def _node_groups(node_info: NodeInfo) -> Optional[List[int]]:
    node = node_info.node
    if node is None:
        return None
    raw = node.metadata.labels.get(GROUPS_LABEL)
    if not raw:
        return None
    try:
        return [int(x) for x in raw.split("_") if x]
    except ValueError:
        return None


def group_free(
    node_info: NodeInfo, resource: str
) -> Optional[List[int]]:
    """Free devices per NUMA group: label capacities minus the recorded
    group assignments of the node's pods (assumed pods included -- they
    are in NodeInfo.pods). Devices held by UNALIGNED pods have no known
    group, so they are subtracted from EVERY group -- pessimistic, but
    the only direction that keeps the "aligned pods never bounce"
    guarantee on mixed nodes (the kubelet may have scattered them
    anywhere)."""
    groups = _node_groups(node_info)
    if groups is None:
        return None
    free = list(groups)
    unattributed = 0
    for p in node_info.pods:
        g = p.metadata.annotations.get(ASSIGNED_ANNOTATION)
        if g is None:
            unattributed += int(
                pod_resource_requests(p).get(resource, 0)
            )
            continue
        try:
            gi = int(g)
        except ValueError:
            continue
        if 0 <= gi < len(free):
            free[gi] -= _aligned_request(p, resource)
    if unattributed:
        free = [f - unattributed for f in free]
    return free


def _best_fit(free: List[int], want: int) -> Optional[int]:
    """Smallest group that still fits (device-manager hint preference:
    keep large groups whole); None when nothing fits."""
    best = None
    for gi, f in enumerate(free):
        if f >= want and (best is None or f < free[best]):
            best = gi
    return best


class NodeResourcesNumaAligned(Plugin):
    """Filter + Score + Reserve for single-NUMA-aligned extended
    resources (no-op for pods without the opt-in annotation)."""

    NAME = "NodeResourcesNumaAligned"

    def __init__(self, handle=None) -> None:
        self._handle = handle

    def _want(self, pod: Pod) -> Tuple[str, int]:
        res = aligned_resource(pod)
        if not res:
            return "", 0
        return res, _aligned_request(pod, res)

    def filter(
        self, state: CycleState, pod: Pod, node_info: NodeInfo
    ) -> Optional[Status]:
        res, want = self._want(pod)
        if not want:
            return None
        free = group_free(node_info, res)
        if free is None:
            # a node without the topology label cannot guarantee
            # alignment for an opted-in pod (TopologyAffinityError
            # would reject it at the kubelet)
            return Status.unschedulable(
                "node advertises no NUMA device topology"
            )
        if _best_fit(free, want) is None:
            return Status.unschedulable(
                f"no NUMA group with {want} free {res}"
            )
        return None

    def score(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> Tuple[int, Optional[Status]]:
        res, want = self._want(pod)
        if not want:
            return 0, None
        snapshot = state.read("__snapshot__")
        ni = snapshot.get_node_info(node_name) if snapshot else None
        if ni is None:
            return 0, None
        free = group_free(ni, res)
        if free is None:
            return 0, None
        gi = _best_fit(free, want)
        if gi is None:
            return 0, None
        # tighter best-fit -> higher score (leftover 0 scores 100)
        leftover = free[gi] - want
        cap = max(free[gi], 1)
        return int(100 * (cap - leftover) / cap), None

    def reserve_relevant(self, pod: Pod) -> bool:
        """Bulk-commit fast-path predicate: reserve() is a no-op for
        pods without the single-NUMA-alignment opt-in annotation (the
        ``not want`` early return below). Declaring it lets the batch
        committer keep annotation-free pods on the bulk assume path
        instead of running a per-pod Reserve pipeline for a guaranteed
        no-op."""
        return bool(aligned_resource(pod))

    def reserve(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> Optional[Status]:
        res, want = self._want(pod)
        if not want:
            return None
        snapshot = (
            self._handle.snapshot_shared_lister()
            if self._handle is not None else None
        )
        ni = snapshot.get_node_info(node_name) if snapshot else None
        if ni is None:
            return Status.error("no node info at reserve")
        free = group_free(ni, res)
        if free is None:
            return Status.unschedulable(
                "node advertises no NUMA device topology"
            )
        gi = _best_fit(free, want)
        if gi is None:
            return Status.unschedulable(
                f"no NUMA group with {want} free {res}"
            )
        # local write on a REPLACED metadata object: the assumed
        # clone's metadata dict is shared with the informer-cache/store
        # object and is contractually read-only (types.py assumed_clone),
        # so the clone gets its own copy carrying the assignment (the
        # cache's NodeInfo holds the clone -> in-flight filters see it)
        # and the durable API write below updates the stored object
        # through the store's own copy-on-write path
        pod.metadata = dataclasses.replace(
            pod.metadata,
            annotations={
                **pod.metadata.annotations,
                ASSIGNED_ANNOTATION: str(gi),
            },
        )
        client = getattr(self._handle, "client", None)
        if client is not None:
            try:
                def set_group(p: Pod) -> None:
                    p.metadata.annotations[ASSIGNED_ANNOTATION] = str(gi)

                client.server.guaranteed_update(
                    "Pod", pod.metadata.namespace, pod.metadata.name,
                    set_group,
                )
            except Exception:  # noqa: BLE001 - reserve must not crash
                pass
        return None

    def unreserve(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> None:
        if ASSIGNED_ANNOTATION in pod.metadata.annotations:
            ann = dict(pod.metadata.annotations)
            ann.pop(ASSIGNED_ANNOTATION, None)
            pod.metadata = dataclasses.replace(
                pod.metadata, annotations=ann
            )
        client = getattr(self._handle, "client", None)
        if client is not None:
            try:
                def clear_group(p: Pod) -> None:
                    # never strip a BOUND pod: a stale re-attempt's
                    # unreserve must not destroy the live placement's
                    # group assignment (written by the attempt that won)
                    if p.spec.node_name:
                        return
                    p.metadata.annotations.pop(ASSIGNED_ANNOTATION, None)

                client.server.guaranteed_update(
                    "Pod", pod.metadata.namespace, pod.metadata.name,
                    clear_group,
                )
            except Exception:  # noqa: BLE001
                pass
