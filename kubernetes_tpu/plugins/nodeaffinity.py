"""NodeAffinity Filter+Score
(reference framework/plugins/nodeaffinity/node_affinity.go)."""

from __future__ import annotations

from typing import Optional, Tuple

from kubernetes_tpu.api.selectors import (
    match_node_selector_term,
    node_matches_node_selector,
    node_selector_dict_matches,
)
from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.cache.node_info import NodeInfo
from kubernetes_tpu.framework.interface import CycleState, Plugin, Status
from kubernetes_tpu.plugins.helpers import default_normalize_score

ERR_REASON = "node(s) didn't match node selector"


def pod_matches_node_selector_and_affinity(pod: Pod, node_info: NodeInfo) -> bool:
    """Reference predicates: both pod.spec.nodeSelector and
    requiredDuringSchedulingIgnoredDuringExecution must match."""
    node = node_info.node
    labels = node.metadata.labels
    fields = {"metadata.name": node.metadata.name}
    if pod.spec.node_selector and not node_selector_dict_matches(
        pod.spec.node_selector, labels
    ):
        return False
    aff = pod.spec.affinity
    if aff and aff.node_affinity and aff.node_affinity.required_during_scheduling:
        if not node_matches_node_selector(
            labels, aff.node_affinity.required_during_scheduling, fields
        ):
            return False
    return True


class NodeAffinity(Plugin):
    NAME = "NodeAffinity"

    def filter(
        self, state: CycleState, pod: Pod, node_info: NodeInfo
    ) -> Optional[Status]:
        if node_info.node is None:
            return Status.error("node not found")
        if not pod_matches_node_selector_and_affinity(pod, node_info):
            return Status.unschedulable(ERR_REASON)
        return None

    def score(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> Tuple[int, Optional[Status]]:
        snapshot = state.read("__snapshot__")
        ni = snapshot.get_node_info(node_name)
        if ni is None or ni.node is None:
            return 0, Status.error(f"node {node_name} not in snapshot")
        node = ni.node
        count = 0
        aff = pod.spec.affinity
        if aff and aff.node_affinity:
            for term in aff.node_affinity.preferred_during_scheduling:
                if term.weight == 0:
                    continue
                if match_node_selector_term(
                    node.metadata.labels,
                    term.preference,
                    {"metadata.name": node.metadata.name},
                ):
                    count += term.weight
        return count, None

    def normalize_score(self, state, pod, scores) -> Optional[Status]:
        default_normalize_score(100, False, scores)
        return None
