"""In-tree plugins: the host-path (oracle) implementations.

Reference: /root/reference/pkg/scheduler/framework/plugins/. Every plugin
here has vectorized TPU equivalents in kubernetes_tpu.ops (feasibility-mask
columns for Filter, score-matrix columns for Score); this sequential set is
the correctness oracle the TPU profile is differentially tested against.
"""

from kubernetes_tpu.framework.registry import Registry


def new_in_tree_registry() -> Registry:
    """Reference framework/plugins/registry.go:45 NewInTreeRegistry."""
    from kubernetes_tpu.plugins import (
        coscheduling,
        defaultbinder,
        imagelocality,
        interpodaffinity,
        nodeaffinity,
        nodename,
        nodeports,
        nodepreferavoidpods,
        noderesources,
        nodeunschedulable,
        podtopologyspread,
        queuesort,
        selectorspread,
        tainttoleration,
        volumes,
    )

    r = Registry()
    r.register(queuesort.PrioritySort.NAME, lambda a, h: queuesort.PrioritySort())
    r.register(noderesources.Fit.NAME, lambda a, h: noderesources.Fit(a))
    r.register(
        noderesources.LeastAllocated.NAME, lambda a, h: noderesources.LeastAllocated()
    )
    r.register(
        noderesources.MostAllocated.NAME, lambda a, h: noderesources.MostAllocated()
    )
    r.register(
        noderesources.BalancedAllocation.NAME,
        lambda a, h: noderesources.BalancedAllocation(),
    )
    r.register(
        noderesources.RequestedToCapacityRatio.NAME,
        lambda a, h: noderesources.RequestedToCapacityRatio(a),
    )
    r.register(
        noderesources.ResourceLimits.NAME, lambda a, h: noderesources.ResourceLimits()
    )
    r.register(nodename.NodeName.NAME, lambda a, h: nodename.NodeName())
    r.register(nodeports.NodePorts.NAME, lambda a, h: nodeports.NodePorts())
    r.register(
        nodeunschedulable.NodeUnschedulable.NAME,
        lambda a, h: nodeunschedulable.NodeUnschedulable(),
    )
    r.register(nodeaffinity.NodeAffinity.NAME, lambda a, h: nodeaffinity.NodeAffinity())
    r.register(
        tainttoleration.TaintToleration.NAME,
        lambda a, h: tainttoleration.TaintToleration(),
    )
    r.register(
        imagelocality.ImageLocality.NAME, lambda a, h: imagelocality.ImageLocality(h)
    )
    r.register(
        nodepreferavoidpods.NodePreferAvoidPods.NAME,
        lambda a, h: nodepreferavoidpods.NodePreferAvoidPods(),
    )
    r.register(
        defaultbinder.DefaultBinder.NAME, lambda a, h: defaultbinder.DefaultBinder(h)
    )
    r.register(
        podtopologyspread.PodTopologySpread.NAME,
        lambda a, h: podtopologyspread.PodTopologySpread(h),
    )
    r.register(
        interpodaffinity.InterPodAffinity.NAME,
        lambda a, h: interpodaffinity.InterPodAffinity(a, h),
    )
    r.register(
        volumes.VolumeRestrictions.NAME, lambda a, h: volumes.VolumeRestrictions()
    )
    r.register(volumes.VolumeZone.NAME, lambda a, h: volumes.VolumeZone(h))
    r.register(volumes.CSILimits.NAME, lambda a, h: volumes.CSILimits(h))
    from kubernetes_tpu.plugins import numa

    r.register(
        numa.NodeResourcesNumaAligned.NAME,
        lambda a, h: numa.NodeResourcesNumaAligned(h),
    )
    r.register(volumes.EBSLimits.NAME, lambda a, h: volumes.EBSLimits(h))
    r.register(volumes.GCEPDLimits.NAME, lambda a, h: volumes.GCEPDLimits(h))
    r.register(
        volumes.AzureDiskLimits.NAME, lambda a, h: volumes.AzureDiskLimits(h)
    )
    r.register(volumes.VolumeBinding.NAME, lambda a, h: volumes.VolumeBinding(h))
    r.register(
        selectorspread.DefaultPodTopologySpread.NAME,
        lambda a, h: selectorspread.DefaultPodTopologySpread(h),
    )
    r.register(
        selectorspread.ServiceAffinity.NAME,
        lambda a, h: selectorspread.ServiceAffinity(a, h),
    )
    r.register(
        selectorspread.NodeLabel.NAME, lambda a, h: selectorspread.NodeLabel(a)
    )
    r.register(
        coscheduling.Coscheduling.NAME,
        lambda a, h: coscheduling.Coscheduling(a, h),
    )
    return r
