"""noderesources plugins: Fit filter and the allocation-based scorers.

Reference: /root/reference/pkg/scheduler/framework/plugins/noderesources/
(fit.go, least_allocated.go, most_allocated.go, balanced_allocation.go,
requested_to_capacity_ratio.go, resource_limits.go, resource_allocation.go).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import (
    RESOURCE_CPU,
    RESOURCE_MEMORY,
    Pod,
    pod_resource_limits,
    pod_resource_requests,
)
from kubernetes_tpu.cache.node_info import (
    DEFAULT_MEMORY_REQUEST,
    DEFAULT_MILLI_CPU_REQUEST,
    NodeInfo,
    Resource,
    new_resource,
    non_zero_requests,
)
from kubernetes_tpu.framework.interface import (
    CycleState,
    MAX_NODE_SCORE,
    Plugin,
    Status,
)

_PRE_FILTER_FIT_STATE_KEY = "PreFilterNodeResourcesFit"


@dataclass
class _FitState:
    pod_request: Resource

    def clone(self) -> "_FitState":
        return _FitState(self.pod_request.clone())


class Fit(Plugin):
    """PreFilter+Filter (fit.go:99 computePodResourceRequest, :181
    fitsRequest)."""

    NAME = "NodeResourcesFit"

    def __init__(self, args: Optional[dict] = None) -> None:
        args = args or {}
        self.ignored_resources = set(args.get("ignored_resources", ()))
        self.ignored_resource_groups = set(args.get("ignored_resource_groups", ()))

    def pre_filter(self, state: CycleState, pod: Pod) -> Optional[Status]:
        state.write(
            _PRE_FILTER_FIT_STATE_KEY,
            _FitState(new_resource(pod_resource_requests(pod))),
        )
        return None

    def _get_state(self, state: CycleState) -> _FitState:
        try:
            return state.read(_PRE_FILTER_FIT_STATE_KEY)
        except KeyError:
            # Filter without PreFilter (preemption simulations recompute)
            raise

    def filter(
        self, state: CycleState, pod: Pod, node_info: NodeInfo
    ) -> Optional[Status]:
        try:
            fit_state = self._get_state(state)
        except KeyError:
            fit_state = _FitState(new_resource(pod_resource_requests(pod)))
        insufficient = self._insufficient_resources(fit_state.pod_request, node_info)
        if insufficient:
            return Status.unschedulable(*insufficient)
        return None

    def _insufficient_resources(
        self, req: Resource, node_info: NodeInfo
    ) -> List[str]:
        """fit.go:181 fitsRequest."""
        out: List[str] = []
        allowed = node_info.allocatable.allowed_pod_number
        if len(node_info.pods) + 1 > allowed:
            out.append(f"Too many pods ({len(node_info.pods)}/{allowed})")
        if (
            req.milli_cpu == 0
            and req.memory == 0
            and req.ephemeral_storage == 0
            and not any(req.scalar.values())
        ):
            return out
        alloc = node_info.allocatable
        used = node_info.requested
        if req.milli_cpu > alloc.milli_cpu - used.milli_cpu:
            out.append("Insufficient cpu")
        if req.memory > alloc.memory - used.memory:
            out.append("Insufficient memory")
        if req.ephemeral_storage > alloc.ephemeral_storage - used.ephemeral_storage:
            out.append("Insufficient ephemeral-storage")
        for name, qty in req.scalar.items():
            if qty == 0 or name in self.ignored_resources:
                continue
            group = name.split("/", 1)[0] if "/" in name else ""
            if group in self.ignored_resource_groups:
                continue
            if qty > alloc.scalar.get(name, 0) - used.scalar.get(name, 0):
                out.append(f"Insufficient {name}")
        return out


def _pod_plus_node_requested(pod: Pod, node_info: NodeInfo) -> Tuple[int, int]:
    """(cpu, mem) = node's non-zero requested + this pod's non-zero request
    (reference resource_allocation.go:90 calculateResourceAllocatableRequest)."""
    pcpu, pmem = non_zero_requests(pod)
    return (
        node_info.non_zero_requested.milli_cpu + pcpu,
        node_info.non_zero_requested.memory + pmem,
    )


class LeastAllocated(Plugin):
    """Score (least_allocated.go): prefers emptier nodes."""

    NAME = "NodeResourcesLeastAllocated"

    def score(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> Tuple[int, Optional[Status]]:
        ni = _node_info_or_error(self, node_name, state)
        if isinstance(ni, Status):
            return 0, ni
        req_cpu, req_mem = _pod_plus_node_requested(pod, ni)
        cap_cpu = ni.allocatable.milli_cpu
        cap_mem = ni.allocatable.memory

        def least(cap: int, req: int) -> int:
            if cap == 0:
                return 0
            if req > cap:
                return 0
            return (cap - req) * MAX_NODE_SCORE // cap

        return (least(cap_cpu, req_cpu) + least(cap_mem, req_mem)) // 2, None


class MostAllocated(Plugin):
    """Score (most_allocated.go): bin-packing, prefers fuller nodes."""

    NAME = "NodeResourcesMostAllocated"

    def score(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> Tuple[int, Optional[Status]]:
        ni = _node_info_or_error(self, node_name, state)
        if isinstance(ni, Status):
            return 0, ni
        req_cpu, req_mem = _pod_plus_node_requested(pod, ni)
        cap_cpu = ni.allocatable.milli_cpu
        cap_mem = ni.allocatable.memory

        def most(cap: int, req: int) -> int:
            if cap == 0 or req > cap:
                return 0
            return req * MAX_NODE_SCORE // cap

        return (most(cap_cpu, req_cpu) + most(cap_mem, req_mem)) // 2, None


class BalancedAllocation(Plugin):
    """Score (balanced_allocation.go:83): 100 * (1 - |cpuFrac - memFrac|)."""

    NAME = "NodeResourcesBalancedAllocation"

    def score(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> Tuple[int, Optional[Status]]:
        ni = _node_info_or_error(self, node_name, state)
        if isinstance(ni, Status):
            return 0, ni
        req_cpu, req_mem = _pod_plus_node_requested(pod, ni)
        cap_cpu = ni.allocatable.milli_cpu
        cap_mem = ni.allocatable.memory
        cpu_frac = req_cpu / cap_cpu if cap_cpu else 1.0
        mem_frac = req_mem / cap_mem if cap_mem else 1.0
        if cpu_frac >= 1.0 or mem_frac >= 1.0:
            return 0, None
        diff = abs(cpu_frac - mem_frac)
        return int((1 - diff) * MAX_NODE_SCORE), None


@dataclass
class _FunctionShapePoint:
    utilization: int  # 0-100
    score: int  # 0-10 (scaled to 0-100 by the plugin)


class RequestedToCapacityRatio(Plugin):
    """Score (requested_to_capacity_ratio.go): user-defined piecewise-linear
    utilization -> score curve."""

    NAME = "RequestedToCapacityRatio"

    def __init__(self, args: Optional[dict] = None) -> None:
        args = args or {}
        shape = args.get("shape") or [
            {"utilization": 0, "score": 0},
            {"utilization": 100, "score": 10},
        ]
        self.points = [
            _FunctionShapePoint(p["utilization"], p["score"]) for p in shape
        ]
        resources = args.get("resources") or [
            {"name": RESOURCE_CPU, "weight": 1},
            {"name": RESOURCE_MEMORY, "weight": 1},
        ]
        self.resources = [(r["name"], r.get("weight", 1)) for r in resources]

    def _curve(self, utilization: float) -> float:
        """Piecewise linear through shape points, score scaled x10 -> 0-100."""
        pts = self.points
        if utilization <= pts[0].utilization:
            return pts[0].score * 10
        for a, b in zip(pts, pts[1:]):
            if utilization <= b.utilization:
                span = b.utilization - a.utilization
                t = (utilization - a.utilization) / span if span else 0.0
                return (a.score + (b.score - a.score) * t) * 10
        return pts[-1].score * 10

    def score(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> Tuple[int, Optional[Status]]:
        ni = _node_info_or_error(self, node_name, state)
        if isinstance(ni, Status):
            return 0, ni
        req_cpu, req_mem = _pod_plus_node_requested(pod, ni)
        values = {
            RESOURCE_CPU: (req_cpu, ni.allocatable.milli_cpu),
            RESOURCE_MEMORY: (req_mem, ni.allocatable.memory),
        }
        total_weight = sum(w for _, w in self.resources)
        if total_weight == 0:
            return 0, None
        acc = 0.0
        for name, weight in self.resources:
            req, cap = values.get(name, (0, 0))
            utilization = min(req * 100.0 / cap, 100.0) if cap else 100.0
            acc += self._curve(utilization) * weight
        return int(acc / total_weight), None


_RESOURCE_LIMITS_STATE_KEY = "PreScoreResourceLimits"


class ResourceLimits(Plugin):
    """PreScore+Score (resource_limits.go): score 1 if the node can satisfy
    the pod's resource *limits*, else 0."""

    NAME = "NodeResourceLimits"

    def pre_score(
        self, state: CycleState, pod: Pod, nodes: List[NodeInfo]
    ) -> Optional[Status]:
        state.write(
            _RESOURCE_LIMITS_STATE_KEY, new_resource(pod_resource_limits(pod))
        )
        return None

    def score(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> Tuple[int, Optional[Status]]:
        ni = _node_info_or_error(self, node_name, state)
        if isinstance(ni, Status):
            return 0, ni
        try:
            limits: Resource = state.read(_RESOURCE_LIMITS_STATE_KEY)
        except KeyError:
            return 0, None
        cpu_ok = limits.milli_cpu == 0 or limits.milli_cpu <= ni.allocatable.milli_cpu
        mem_ok = limits.memory == 0 or limits.memory <= ni.allocatable.memory
        has_any = limits.milli_cpu > 0 or limits.memory > 0
        return (1 if (has_any and cpu_ok and mem_ok) else 0), None


def _node_info_or_error(plugin: Plugin, node_name: str, state: CycleState):
    """Score plugins read NodeInfo through the snapshot placed into the
    cycle state by the generic scheduler."""
    try:
        snapshot = state.read("__snapshot__")
    except KeyError:
        return Status.error(f"{plugin.name()}: no snapshot in cycle state")
    ni = snapshot.get_node_info(node_name)
    if ni is None or ni.node is None:
        return Status.error(f"{plugin.name()}: node {node_name} not in snapshot")
    return ni
