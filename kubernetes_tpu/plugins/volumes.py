"""Volume plugins: VolumeRestrictions, VolumeZone, NodeVolumeLimits
(CSI + in-tree), and VolumeBinding with a lite volume binder.

References:
- volumerestrictions/volume_restrictions.go (:46 isVolumeConflict, :121
  Filter): GCE-PD/EBS/ISCSI/RBD mount-conflict rules
- volumezone/volume_zone.go (:73 Filter): PV zone/region labels must match
  node labels; WaitForFirstConsumer claims are skipped
- nodevolumelimits/csi.go + non_csi.go: attachable-volume count limits per
  driver (CSINode allocatable) / per cloud type (fixed defaults)
- volumebinding/volume_binding.go + the binder
  pkg/controller/volume/scheduling/scheduler_binder.go:235 FindPodVolumes
  (bound PV node-affinity check; unbound WaitForFirstConsumer claims
  matched against available PVs or deemed provisionable), :320
  AssumePodVolumes / :397 BindPodVolumes collapsed into PreBind here.

The volume dimension stays host-side in the TPU design (string/topology
heavy, rarely the bottleneck); pods with PVCs take the sequential path.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.selectors import node_matches_node_selector
from kubernetes_tpu.api.types import (
    CSINode,
    LABEL_REGION_KEYS,
    LABEL_ZONE_KEYS,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    StorageClass,
    VOLUME_BINDING_WAIT,
    Volume,
)
from kubernetes_tpu.cache.node_info import (
    AZURE_DISK_VOLUME_RESOURCE,
    CSI_ATTACH_PREFIX,
    EBS_VOLUME_RESOURCE,
    GCE_PD_VOLUME_RESOURCE,
    NodeInfo,
)
from kubernetes_tpu.framework.interface import CycleState, Plugin, Status

ERR_REASON_DISK_CONFLICT = "node(s) had no available disk"
ERR_REASON_ZONE_CONFLICT = "node(s) had no available volume zone"
ERR_REASON_UNBOUND_IMMEDIATE = "pod has unbound immediate PersistentVolumeClaims"
ERR_REASON_BINDING = "node(s) didn't find available persistent volumes to bind"
ERR_REASON_NODE_CONFLICT = (
    "node(s) had volume node affinity conflict"
)
ERR_REASON_MAX_VOLUME_COUNT = "node(s) exceed max volume count"

# reference nodevolumelimits/non_csi.go default limits
DEFAULT_EBS_LIMIT = 39
DEFAULT_GCE_PD_LIMIT = 16
DEFAULT_AZURE_LIMIT = 16


class VolumeRestrictions(Plugin):
    """Filter (volume_restrictions.go:121)."""

    NAME = "VolumeRestrictions"

    @staticmethod
    def _conflicts(v: Volume, existing: Volume) -> bool:
        if v.gce_pd_name and v.gce_pd_name == existing.gce_pd_name:
            if not (v.read_only and existing.read_only):
                return True
        if (
            v.aws_ebs_volume_id
            and v.aws_ebs_volume_id == existing.aws_ebs_volume_id
        ):
            return True
        if v.iscsi_target and v.iscsi_target == existing.iscsi_target:
            if not (v.read_only and existing.read_only):
                return True
        if v.rbd_image and v.rbd_image == existing.rbd_image:
            if not (v.read_only and existing.read_only):
                return True
        return False

    def filter(
        self, state: CycleState, pod: Pod, node_info: NodeInfo
    ) -> Optional[Status]:
        for v in pod.spec.volumes:
            if not (
                v.gce_pd_name or v.aws_ebs_volume_id or v.iscsi_target
                or v.rbd_image
            ):
                continue
            for existing_pod in node_info.pods:
                for ev in existing_pod.spec.volumes:
                    if self._conflicts(v, ev):
                        return Status.unschedulable(ERR_REASON_DISK_CONFLICT)
        return None


class _Listers:
    """Shared lister access for the PVC/PV/SC/CSINode-consuming plugins."""

    def __init__(self, handle=None) -> None:
        self.informers = getattr(handle, "informers", None)

    def _get(self, kind_accessor: str, namespace: str, name: str):
        if self.informers is None:
            return None
        return getattr(self.informers, kind_accessor)().get(namespace, name)

    def pvc(self, namespace: str, name: str) -> Optional[PersistentVolumeClaim]:
        return self._get("persistent_volume_claims", namespace, name)

    def pv(self, name: str) -> Optional[PersistentVolume]:
        return self._get("persistent_volumes", "", name)

    def storage_class(self, name: str) -> Optional[StorageClass]:
        return self._get("storage_classes", "", name)

    def csi_node(self, name: str) -> Optional[CSINode]:
        return self._get("csi_nodes", "", name)

    def list_pvs(self) -> List[PersistentVolume]:
        if self.informers is None:
            return []
        return self.informers.persistent_volumes().list()


def classify_pod_volumes(pod, listers: _Listers) -> Tuple[str, Tuple]:
    """Classify a pod's volumes for the device path. Returns
    ``(host_reason, counts)``:

    - ``host_reason == ""``: every volume filter is either provably
      node-independent OR a pure attachable-volume COUNT the ``[N, R]``
      tensor's volume columns enforce on device (tensors/node_tensor.py)
      -- the pod rides the batch solver. Previously any countable source
      fell off the device entirely (the 54 pods/s SchedulingCSIPVs
      cliff).
    - a non-empty reason keeps the pod on the exact host oracle: direct
      in-tree sources (VolumeRestrictions mount-CONFLICT rules are
      pairwise identity, not counts), unbound claims
      (WaitForFirstConsumer / missing), missing PVs, PV node affinity,
      or zonal PV labels (VolumeZone).

    ``counts`` is the sorted ``((limit_resource, n_unique_handles), ...)``
    tuple over the pod's countable volumes -- resolved through PVC -> PV
    for bound claims and read directly off in-tree sources -- and is
    returned even for host-routed pods: the node's in-use accounting
    (NodeInfo.volume_in_use) must see every attach regardless of which
    path placed the pod. Counting is per-pod-unique and additive across
    pods, i.e. conservative versus the oracle's per-node-unique handle
    sets: the device can under-admit a shared handle but never
    over-admit (the dispatcher re-checks device rejects of countable
    pods on the host path)."""
    reason = ""
    handles: Dict[str, set] = {}

    def count(resource: str, handle: str) -> None:
        handles.setdefault(resource, set()).add(handle)

    for v in pod.spec.volumes:
        if (
            v.gce_pd_name or v.aws_ebs_volume_id
            or v.iscsi_target or v.rbd_image
        ):
            # conflict semantics, not counts: host path. The attach
            # still consumes the node's in-tree limit budget.
            reason = reason or "direct-volume-source"
            if v.gce_pd_name:
                count(GCE_PD_VOLUME_RESOURCE, v.gce_pd_name)
            if v.aws_ebs_volume_id:
                count(EBS_VOLUME_RESOURCE, v.aws_ebs_volume_id)
            continue
        if not v.pvc_claim_name:
            continue
        pvc = listers.pvc(pod.metadata.namespace, v.pvc_claim_name)
        if pvc is None or not pvc.volume_name:
            reason = reason or "unbound-pvc"
            continue
        pv = listers.pv(pvc.volume_name)
        if pv is None:
            reason = reason or "pv-missing"
            continue
        if pv.node_affinity is not None:
            reason = reason or "pv-node-affinity"
        elif any(
            k in pv.metadata.labels
            for k in LABEL_ZONE_KEYS + LABEL_REGION_KEYS
        ):
            reason = reason or "pv-zonal"
        if pv.csi_driver:
            count(
                CSI_ATTACH_PREFIX + pv.csi_driver,
                pv.csi_volume_handle or pv.metadata.name,
            )
        elif pv.gce_pd_name:
            count(GCE_PD_VOLUME_RESOURCE, pv.gce_pd_name)
        elif pv.aws_ebs_volume_id:
            count(EBS_VOLUME_RESOURCE, pv.aws_ebs_volume_id)
        elif pv.azure_disk_name:
            count(AZURE_DISK_VOLUME_RESOURCE, pv.azure_disk_name)
    counts = tuple(
        sorted((name, len(hs)) for name, hs in handles.items())
    )
    return reason, counts


def volumes_device_safe(pod, listers: _Listers) -> bool:
    """True when the batch solver can place this pod without the host
    volume oracle (see ``classify_pod_volumes``). Since the
    volume-count device columns landed, countable bound PVs (CSI and
    in-tree via PVC) are device-safe too -- their limits solve as
    ``[N, R]`` columns; only conflict-bearing direct sources, unbound
    claims, and node-affine/zonal PVs keep the host path."""
    return not classify_pod_volumes(pod, listers)[0]


def _zone_values(value: str) -> set:
    """volumehelpers.LabelZonesToSet: multi-zone PV labels are
    '__'-separated."""
    return set(value.split("__"))


class VolumeZone(Plugin):
    """Filter (volume_zone.go:73)."""

    NAME = "VolumeZone"

    def __init__(self, handle=None) -> None:
        self.listers = _Listers(handle)

    def filter(
        self, state: CycleState, pod: Pod, node_info: NodeInfo
    ) -> Optional[Status]:
        node = node_info.node
        if node is None:
            return Status.error("node not found")
        zone_keys = LABEL_ZONE_KEYS + LABEL_REGION_KEYS
        constraints = {
            k: v for k, v in node.metadata.labels.items() if k in zone_keys
        }
        if not constraints:
            return None
        for v in pod.spec.volumes:
            if not v.pvc_claim_name:
                continue
            pvc = self.listers.pvc(pod.metadata.namespace, v.pvc_claim_name)
            if pvc is None:
                return Status.error(
                    f"PersistentVolumeClaim {v.pvc_claim_name!r} not found"
                )
            if not pvc.volume_name:
                sc = self.listers.storage_class(pvc.storage_class_name)
                if sc is not None and sc.volume_binding_mode == VOLUME_BINDING_WAIT:
                    continue  # unbound wait-for-consumer: skip
                return Status.error("PersistentVolume had no name")
            pv = self.listers.pv(pvc.volume_name)
            if pv is None:
                return Status.error(
                    f"PersistentVolume {pvc.volume_name!r} not found"
                )
            for k, val in pv.metadata.labels.items():
                if k not in zone_keys:
                    continue
                node_v = constraints.get(k)
                if node_v is None or node_v not in _zone_values(val):
                    return Status.unschedulable_and_unresolvable(
                        ERR_REASON_ZONE_CONFLICT
                    )
        return None


class CSILimits(Plugin):
    """Filter (nodevolumelimits/csi.go): unique CSI volume handles per
    driver vs CSINode allocatable."""

    NAME = "NodeVolumeLimitsCSI"

    def __init__(self, handle=None) -> None:
        self.listers = _Listers(handle)

    def _pod_csi_volumes(self, pod: Pod) -> List[Tuple[str, str]]:
        """[(driver, handle)] via PVC -> PV."""
        out = []
        for v in pod.spec.volumes:
            if not v.pvc_claim_name:
                continue
            pvc = self.listers.pvc(pod.metadata.namespace, v.pvc_claim_name)
            if pvc is None or not pvc.volume_name:
                continue
            pv = self.listers.pv(pvc.volume_name)
            if pv is not None and pv.csi_driver:
                out.append((pv.csi_driver, pv.csi_volume_handle))
        return out

    def filter(
        self, state: CycleState, pod: Pod, node_info: NodeInfo
    ) -> Optional[Status]:
        new_volumes = self._pod_csi_volumes(pod)
        if not new_volumes:
            return None
        csi_node = self.listers.csi_node(node_info.node_name)
        if csi_node is None:
            return None  # no limits known
        limits = {
            d.name: d.allocatable_count
            for d in csi_node.drivers
            if d.allocatable_count is not None
        }
        if not limits:
            return None
        in_use: Dict[str, set] = {}
        for existing in node_info.pods:
            for driver, handle in self._pod_csi_volumes(existing):
                in_use.setdefault(driver, set()).add(handle)
        for driver, handle in new_volumes:
            if driver not in limits:
                continue
            used = in_use.setdefault(driver, set())
            if handle not in used and len(used) + 1 > limits[driver]:
                return Status.unschedulable(ERR_REASON_MAX_VOLUME_COUNT)
            used.add(handle)
        return None


class _InTreeLimits(Plugin):
    """Filter (nodevolumelimits/non_csi.go): attachable in-tree volume
    count vs a fixed per-cloud limit."""

    VOLUME_ATTR = ""
    PV_ATTR = ""
    DEFAULT_LIMIT = 0

    def __init__(self, handle=None, limit: Optional[int] = None) -> None:
        self.listers = _Listers(handle)
        self.limit = limit if limit is not None else self.DEFAULT_LIMIT

    def _pod_volume_ids(self, pod: Pod) -> set:
        out = set()
        for v in pod.spec.volumes:
            direct = getattr(v, self.VOLUME_ATTR, "")
            if direct:
                out.add(direct)
            elif v.pvc_claim_name:
                pvc = self.listers.pvc(pod.metadata.namespace, v.pvc_claim_name)
                if pvc is not None and pvc.volume_name:
                    pv = self.listers.pv(pvc.volume_name)
                    if pv is not None:
                        via_pv = getattr(pv, self.PV_ATTR, "")
                        if via_pv:
                            out.add(via_pv)
        return out

    def filter(
        self, state: CycleState, pod: Pod, node_info: NodeInfo
    ) -> Optional[Status]:
        new_ids = self._pod_volume_ids(pod)
        if not new_ids:
            return None
        attached = set()
        for existing in node_info.pods:
            attached |= self._pod_volume_ids(existing)
        if len(attached | new_ids) > self.limit:
            return Status.unschedulable(ERR_REASON_MAX_VOLUME_COUNT)
        return None


class EBSLimits(_InTreeLimits):
    NAME = "EBSLimits"
    VOLUME_ATTR = "aws_ebs_volume_id"
    PV_ATTR = "aws_ebs_volume_id"
    DEFAULT_LIMIT = DEFAULT_EBS_LIMIT


class GCEPDLimits(_InTreeLimits):
    NAME = "GCEPDLimits"
    VOLUME_ATTR = "gce_pd_name"
    PV_ATTR = "gce_pd_name"
    DEFAULT_LIMIT = DEFAULT_GCE_PD_LIMIT


class AzureDiskLimits(_InTreeLimits):
    NAME = "AzureDiskLimits"
    VOLUME_ATTR = ""  # no direct azure source in the flattened Volume
    PV_ATTR = "azure_disk_name"
    DEFAULT_LIMIT = DEFAULT_AZURE_LIMIT


class VolumeBinder:
    """Lite SchedulerVolumeBinder (scheduler_binder.go): feasibility at
    Filter, all-or-nothing bind at PreBind."""

    def __init__(self, handle=None) -> None:
        self.listers = _Listers(handle)
        self.client = getattr(handle, "client", None)

    def _claims(self, pod: Pod) -> List[Tuple[Volume, Optional[PersistentVolumeClaim]]]:
        return [
            (v, self.listers.pvc(pod.metadata.namespace, v.pvc_claim_name))
            for v in pod.spec.volumes
            if v.pvc_claim_name
        ]

    def _pv_matches_node(self, pv: PersistentVolume, node_info: NodeInfo) -> bool:
        if pv.node_affinity is None:
            return True
        node = node_info.node
        return node_matches_node_selector(
            node.metadata.labels, pv.node_affinity,
            {"metadata.name": node.metadata.name},
        )

    def _find_matching_pv(
        self,
        pvc: PersistentVolumeClaim,
        node_info: NodeInfo,
        reserved: Optional[set] = None,
    ) -> Optional[PersistentVolume]:
        """``reserved`` carries PV names already matched to earlier claims
        of the same pod in this call -- the assume-cache role of the
        reference binder (scheduler_binder.go:320), preventing one PV from
        satisfying two claims."""
        best = None
        for pv in self.listers.list_pvs():
            if reserved and pv.metadata.name in reserved:
                continue
            if pv.claim_ref_name and not pv.is_bound_to(
                pvc.metadata.namespace, pvc.metadata.name
            ):
                continue
            if pv.storage_class_name != pvc.storage_class_name:
                continue
            if pv.capacity_bytes < pvc.requested_bytes:
                continue
            if not self._pv_matches_node(pv, node_info):
                continue
            if best is None or pv.capacity_bytes < best.capacity_bytes:
                best = pv  # smallest fitting PV
        return best

    def find_pod_volumes(
        self, pod: Pod, node_info: NodeInfo
    ) -> Optional[Status]:
        """FindPodVolumes (scheduler_binder.go:235)."""
        reserved: set = set()
        for v, pvc in self._claims(pod):
            if pvc is None:
                return Status.unschedulable_and_unresolvable(
                    f"persistentvolumeclaim {v.pvc_claim_name!r} not found"
                )
            if pvc.volume_name:
                pv = self.listers.pv(pvc.volume_name)
                if pv is None:
                    return Status.unschedulable_and_unresolvable(
                        f"persistentvolume {pvc.volume_name!r} not found"
                    )
                if not self._pv_matches_node(pv, node_info):
                    return Status.unschedulable_and_unresolvable(
                        ERR_REASON_NODE_CONFLICT
                    )
                continue
            # unbound claim
            sc = self.listers.storage_class(pvc.storage_class_name)
            if sc is None or sc.volume_binding_mode != VOLUME_BINDING_WAIT:
                return Status.unschedulable_and_unresolvable(
                    ERR_REASON_UNBOUND_IMMEDIATE
                )
            match = self._find_matching_pv(pvc, node_info, reserved)
            if match is not None:
                reserved.add(match.metadata.name)
                continue
            if sc.provisioner and sc.provisioner != "kubernetes.io/no-provisioner":
                continue  # dynamically provisionable on this node
            return Status.unschedulable(ERR_REASON_BINDING)
        return None

    def bind_pod_volumes(self, pod: Pod, node_info: NodeInfo) -> Optional[Status]:
        """AssumePodVolumes+BindPodVolumes collapsed: bind matched PVs."""
        if self.client is None:
            return None
        reserved: set = set()
        for v, pvc in self._claims(pod):
            if pvc is None or pvc.volume_name:
                continue
            pv = self._find_matching_pv(pvc, node_info, reserved)
            if pv is None:
                sc = self.listers.storage_class(pvc.storage_class_name)
                if sc is not None and sc.provisioner and \
                        sc.provisioner != "kubernetes.io/no-provisioner":
                    continue  # provisioning is the controller's job
                return Status.error(
                    f"no PV to bind for claim {pvc.key()}"
                )
            # guaranteed updates: never mutate the lister's shared objects
            # in place (the store's copy-on-write contract)
            reserved.add(pv.metadata.name)
            pv_name = pv.metadata.name
            ns, claim = pvc.metadata.namespace, pvc.metadata.name

            def bind_pv(obj) -> None:
                obj.claim_ref_namespace = ns
                obj.claim_ref_name = claim

            def bind_pvc(obj) -> None:
                obj.volume_name = pv_name
                obj.phase = "Bound"

            try:
                self.client.server.guaranteed_update(
                    "PersistentVolume", "", pv_name, bind_pv
                )
                self.client.server.guaranteed_update(
                    "PersistentVolumeClaim", ns, claim, bind_pvc
                )
            except KeyError as e:
                return Status.error(f"volume binding failed: {e}")
        return None


class VolumeBinding(Plugin):
    """Filter + PreBind (volume_binding.go)."""

    NAME = "VolumeBinding"

    def __init__(self, handle=None) -> None:
        self.binder = VolumeBinder(handle)

    def filter(
        self, state: CycleState, pod: Pod, node_info: NodeInfo
    ) -> Optional[Status]:
        if not any(v.pvc_claim_name for v in pod.spec.volumes):
            return None
        return self.binder.find_pod_volumes(pod, node_info)

    def pre_bind_relevant(self, pod: Pod) -> bool:
        """Bulk-commit fast-path predicate: pre_bind() is a no-op for
        pods without PVC volumes."""
        return any(v.pvc_claim_name for v in pod.spec.volumes)

    def pre_bind(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> Optional[Status]:
        if not any(v.pvc_claim_name for v in pod.spec.volumes):
            return None
        snapshot = state.read("__snapshot__")
        ni = snapshot.get_node_info(node_name)
        if ni is None:
            return Status.error(f"node {node_name} not in snapshot")
        return self.binder.bind_pod_volumes(pod, ni)
