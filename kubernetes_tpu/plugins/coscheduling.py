"""Coscheduling: all-or-nothing gang scheduling via the Permit extension
point.

Reference: the Permit/WaitingPod machinery this rides on is
/root/reference/pkg/scheduler/framework/v1alpha1/interface.go:384 (Permit,
can return Wait) + waiting_pods_map.go; the gang semantics follow the
out-of-tree scheduler-plugins Coscheduling plugin that SURVEY.md section
2.2 identifies as the reference's gang mechanism ("not in-tree -- enabled
by the Permit extension point").

Flow: each member of a PodGroup is filtered/scored/assumed normally; at
Permit, if fewer than ``min_member`` members hold assignments the pod
parks in WAIT (holding its resources via the assume). When the threshold
member arrives, it allows every waiting member. A timeout rejects the
waiters, which unreserves + requeues them -- all-or-nothing with bounded
capacity hold.

The TPU batch solver composes naturally: a whole gang usually lands in
one batch, each member is assumed during commit, and the final member's
Permit releases the group in the same cycle.
"""

from __future__ import annotations

from typing import Optional, Tuple

from kubernetes_tpu.api.types import POD_GROUP_LABEL, Pod, PodGroup
from kubernetes_tpu.cache.node_info import NodeInfo
from kubernetes_tpu.framework.interface import CycleState, Plugin, Status

DEFAULT_SCHEDULE_TIMEOUT_SECONDS = 60


class Coscheduling(Plugin):
    NAME = "Coscheduling"

    def __init__(self, args: Optional[dict] = None, handle=None) -> None:
        args = args or {}
        self.handle = handle
        self.default_timeout = float(
            args.get("schedule_timeout_seconds", DEFAULT_SCHEDULE_TIMEOUT_SECONDS)
        )

    # -- helpers ------------------------------------------------------------

    def _group_of(self, pod: Pod) -> Optional[str]:
        return pod.metadata.labels.get(POD_GROUP_LABEL)

    def _pod_group(self, pod: Pod, name: str) -> Optional[PodGroup]:
        informers = getattr(self.handle, "informers", None)
        if informers is None:
            return None
        return informers.pod_groups().get(pod.metadata.namespace, name)

    def _count_total_members(self, pod: Pod, group: str) -> int:
        """Every group member known to the cluster (informer view)."""
        informers = getattr(self.handle, "informers", None)
        if informers is None:
            return 0
        return sum(
            1
            for p in informers.pods().list()
            if p.metadata.namespace == pod.metadata.namespace
            and p.metadata.labels.get(POD_GROUP_LABEL) == group
        )

    def group_quorum_info(self, pod: Pod, group: str):
        """Public quorum query for the batch solver's all-or-nothing
        group masks: (min_member, total known members). The same
        knowledge horizon as pre_filter's fail-fast."""
        pg = self._pod_group(pod, group)
        return (
            pg.min_member if pg is not None else 1,
            self._count_total_members(pod, group),
        )

    def _count_holding_members(self, pod: Pod, group: str) -> int:
        """Distinct members currently holding resources: bound/assumed
        pods in the snapshot, pods parked at Permit, and the pod being
        permitted itself (assumed, but the snapshot may predate it --
        especially on the batch path where a whole gang is assumed before
        any Permit runs). Deduplicated by uid: an assumed pod that is also
        waiting must count once."""
        ns = pod.metadata.namespace
        uids = {pod.metadata.uid}
        snapshot = self.handle.snapshot_shared_lister()
        for p in snapshot.list_pods():
            if (
                p.metadata.namespace == ns
                and p.metadata.labels.get(POD_GROUP_LABEL) == group
            ):
                uids.add(p.metadata.uid)

        def visit(wp) -> None:
            wpod = wp.pod
            if (
                wpod.metadata.namespace == ns
                and wpod.metadata.labels.get(POD_GROUP_LABEL) == group
            ):
                uids.add(wpod.metadata.uid)

        self.handle.iterate_over_waiting_pods(visit)
        return len(uids)

    # -- PreFilter: fail fast when the gang can never assemble --------------

    def pre_filter(self, state: CycleState, pod: Pod) -> Optional[Status]:
        group = self._group_of(pod)
        if not group:
            return None
        pg = self._pod_group(pod, group)
        if pg is None:
            return None
        total = self._count_total_members(pod, group)
        if total < pg.min_member:
            return Status.unschedulable_and_unresolvable(
                f"pod group {group!r} has {total} members, "
                f"less than minMember {pg.min_member}"
            )
        return None

    # -- Permit: the gang barrier -------------------------------------------

    def permit_relevant(self, pod: Pod) -> bool:
        """Bulk-commit fast-path predicate: permit() is a no-op for pods
        without a pod-group label."""
        return bool(pod.metadata.labels.get(POD_GROUP_LABEL))

    def permit(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> Tuple[Optional[Status], float]:
        group = self._group_of(pod)
        if not group:
            return None, 0.0
        pg = self._pod_group(pod, group)
        min_member = pg.min_member if pg is not None else 1
        timeout = (
            pg.schedule_timeout_seconds if pg is not None
            else self.default_timeout
        )
        assigned = self._count_holding_members(pod, group)
        if assigned >= min_member:
            # threshold reached: release every waiting member
            ns = pod.metadata.namespace

            def allow(wp) -> None:
                if (
                    wp.pod.metadata.namespace == ns
                    and wp.pod.metadata.labels.get(POD_GROUP_LABEL) == group
                ):
                    wp.allow(self.NAME)

            self.handle.iterate_over_waiting_pods(allow)
            return None, 0.0
        return Status.wait(), float(timeout)
