"""DefaultBinder bind plugin
(reference framework/plugins/defaultbinder/default_binder.go:50-61)."""

from __future__ import annotations

from typing import Optional

from kubernetes_tpu.api.types import Binding, Pod
from kubernetes_tpu.framework.interface import CycleState, Plugin, Status


class DefaultBinder(Plugin):
    NAME = "DefaultBinder"

    def __init__(self, handle) -> None:
        self.handle = handle

    def bind(self, state: CycleState, pod: Pod, node_name: str) -> Optional[Status]:
        client = self.handle.client_set()
        if client is None:
            return Status.error("no client available for binding")
        try:
            client.bind(
                Binding(
                    pod_namespace=pod.metadata.namespace,
                    pod_name=pod.metadata.name,
                    pod_uid=pod.metadata.uid,
                    target_node=node_name,
                )
            )
        except Exception as e:  # Conflict / NotFound -> bind failure
            return Status.error(str(e))
        return None
