"""PodTopologySpread: hard (DoNotSchedule) filtering and soft
(ScheduleAnyway) scoring of topology-spread constraints.

Reference: /root/reference/pkg/scheduler/framework/plugins/podtopologyspread/
(filtering.go: preFilterState :43, criticalPaths :86, calPreFilterState :198,
Filter :285; scoring.go: preScoreState :38, PreScore :92, Score :166,
NormalizeScore :199; common.go: topologySpreadConstraint :34).

On TPU the pair-count maps become dense ``[num_constraints, num_topologies]``
count tensors updated by scatter-add inside the assignment scan
(kubernetes_tpu.ops); this host implementation is the correctness oracle.

DefaultConstraints (service/RC/RS/STS-derived selectors, common.go:44) are
not wired because the default v1alpha2 provider enables none; pods without
explicit constraints simply produce an empty state.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.selectors import labels_match_selector
from kubernetes_tpu.api.types import Node, Pod, TopologySpreadConstraint
from kubernetes_tpu.cache.node_info import NodeInfo
from kubernetes_tpu.framework.interface import (
    CycleState,
    MAX_NODE_SCORE,
    NodeScore,
    Plugin,
    PreFilterExtensions,
    Status,
)
from kubernetes_tpu.plugins.nodeaffinity import (
    pod_matches_node_selector_and_affinity,
)

PRE_FILTER_STATE_KEY = "PreFilterPodTopologySpread"
PRE_SCORE_STATE_KEY = "PreScorePodTopologySpread"

ERR_REASON_CONSTRAINTS_NOT_MATCH = (
    "node(s) didn't match pod topology spread constraints"
)

DO_NOT_SCHEDULE = "DoNotSchedule"
SCHEDULE_ANYWAY = "ScheduleAnyway"

_MAX_INT32 = (1 << 31) - 1


class _Constraint:
    """Internal parsed constraint (reference common.go:34)."""

    __slots__ = ("max_skew", "topology_key", "selector")

    def __init__(self, c: TopologySpreadConstraint) -> None:
        self.max_skew = c.max_skew
        self.topology_key = c.topology_key
        self.selector = c.label_selector


def _filter_constraints(
    constraints: List[TopologySpreadConstraint], action: str
) -> List[_Constraint]:
    return [_Constraint(c) for c in constraints if c.when_unsatisfiable == action]


def _node_labels_match_constraints(
    node_labels: Dict[str, str], constraints: List[_Constraint]
) -> bool:
    """ALL topology keys must be present (reference common.go:60)."""
    return all(c.topology_key in node_labels for c in constraints)


class CriticalPaths:
    """2-slot min tracker (reference filtering.go:86 criticalPaths).
    Slot 0 always holds the global minimum match count."""

    __slots__ = ("values", "nums")

    def __init__(self) -> None:
        self.values: List[Optional[str]] = [None, None]
        self.nums: List[int] = [_MAX_INT32, _MAX_INT32]

    def min_match_num(self) -> int:
        return self.nums[0]

    def update(self, tp_val: str, num: int) -> None:
        if tp_val == self.values[0]:
            i = 0
        elif tp_val == self.values[1]:
            i = 1
        else:
            i = -1
        if i >= 0:
            self.nums[i] = num
            if self.nums[0] > self.nums[1]:
                self.values[0], self.values[1] = self.values[1], self.values[0]
                self.nums[0], self.nums[1] = self.nums[1], self.nums[0]
        elif num < self.nums[0]:
            self.values[1], self.nums[1] = self.values[0], self.nums[0]
            self.values[0], self.nums[0] = tp_val, num
        elif num < self.nums[1]:
            self.values[1], self.nums[1] = tp_val, num

    def copy(self) -> "CriticalPaths":
        cp = CriticalPaths()
        cp.values = list(self.values)
        cp.nums = list(self.nums)
        return cp


class PreFilterState:
    """Reference filtering.go:43 preFilterState."""

    def __init__(
        self,
        constraints: Optional[List[_Constraint]] = None,
    ) -> None:
        self.constraints: List[_Constraint] = constraints or []
        self.tp_key_to_critical_paths: Dict[str, CriticalPaths] = {}
        self.tp_pair_to_match_num: Dict[Tuple[str, str], int] = {}

    def clone(self) -> "PreFilterState":
        s = PreFilterState(self.constraints)  # constraints are immutable
        s.tp_key_to_critical_paths = {
            k: v.copy() for k, v in self.tp_key_to_critical_paths.items()
        }
        s.tp_pair_to_match_num = dict(self.tp_pair_to_match_num)
        return s

    def update_with_pod(
        self, updated_pod: Pod, preemptor: Pod, node: Optional[Node], delta: int
    ) -> None:
        """Reference filtering.go:127 updateWithPod: incremental count update
        used by AddPod/RemovePod (nominated pods + preemption)."""
        if (
            node is None
            or updated_pod.metadata.namespace != preemptor.metadata.namespace
        ):
            return
        if not _node_labels_match_constraints(
            node.metadata.labels, self.constraints
        ):
            return
        pod_labels = updated_pod.metadata.labels
        for c in self.constraints:
            if not labels_match_selector(pod_labels, c.selector):
                continue
            k = c.topology_key
            v = node.metadata.labels[k]
            pair = (k, v)
            self.tp_pair_to_match_num[pair] = (
                self.tp_pair_to_match_num.get(pair, 0) + delta
            )
            self.tp_key_to_critical_paths[k].update(
                v, self.tp_pair_to_match_num[pair]
            )


class _SpreadPreFilterExtensions(PreFilterExtensions):
    def add_pod(self, state, pod_to_schedule, pod_to_add, node_info):
        s = _get_pre_filter_state(state)
        if isinstance(s, Status):
            return s
        s.update_with_pod(pod_to_add, pod_to_schedule, node_info.node, 1)
        return None

    def remove_pod(self, state, pod_to_schedule, pod_to_remove, node_info):
        s = _get_pre_filter_state(state)
        if isinstance(s, Status):
            return s
        s.update_with_pod(pod_to_remove, pod_to_schedule, node_info.node, -1)
        return None


def _get_pre_filter_state(state: CycleState):
    try:
        return state.read(PRE_FILTER_STATE_KEY)
    except KeyError:
        return Status.error(
            f"error reading {PRE_FILTER_STATE_KEY!r} from cycleState"
        )


class PreScoreState:
    """Reference scoring.go:38 preScoreState."""

    def __init__(self) -> None:
        self.constraints: List[_Constraint] = []
        self.node_name_set: set = set()
        self.topology_pair_to_pod_counts: Dict[Tuple[str, str], int] = {}

    def clone(self) -> "PreScoreState":
        return self  # reference Clone is a no-op share


class PodTopologySpread(Plugin):
    NAME = "PodTopologySpread"

    def __init__(self, handle=None) -> None:
        self.handle = handle
        self._extensions = _SpreadPreFilterExtensions()

    # -- PreFilter / Filter (DoNotSchedule) ---------------------------------

    def pre_filter(self, state: CycleState, pod: Pod) -> Optional[Status]:
        s = self._cal_pre_filter_state(state, pod)
        state.write(PRE_FILTER_STATE_KEY, s)
        return None

    def pre_filter_extensions(self) -> PreFilterExtensions:
        return self._extensions

    def _cal_pre_filter_state(
        self, state: CycleState, pod: Pod
    ) -> PreFilterState:
        """Reference filtering.go:198 calPreFilterState."""
        constraints = _filter_constraints(
            pod.spec.topology_spread_constraints, DO_NOT_SCHEDULE
        )
        if not constraints:
            return PreFilterState()
        snapshot = state.read("__snapshot__")
        s = PreFilterState(constraints)
        for ni in snapshot.list_node_infos():
            node = ni.node
            if node is None:
                continue
            # Spreading applies only to nodes passing nodeSelector/affinity.
            if not pod_matches_node_selector_and_affinity(pod, ni):
                continue
            if not _node_labels_match_constraints(
                node.metadata.labels, constraints
            ):
                continue
            for c in constraints:
                match_total = 0
                for existing in ni.pods:
                    if (
                        existing.metadata.deletion_timestamp is not None
                        or existing.metadata.namespace != pod.metadata.namespace
                    ):
                        continue
                    if labels_match_selector(
                        existing.metadata.labels, c.selector
                    ):
                        match_total += 1
                pair = (c.topology_key, node.metadata.labels[c.topology_key])
                s.tp_pair_to_match_num[pair] = (
                    s.tp_pair_to_match_num.get(pair, 0) + match_total
                )
        for c in constraints:
            s.tp_key_to_critical_paths[c.topology_key] = CriticalPaths()
        for (k, v), num in s.tp_pair_to_match_num.items():
            s.tp_key_to_critical_paths[k].update(v, num)
        return s

    def filter(
        self, state: CycleState, pod: Pod, node_info: NodeInfo
    ) -> Optional[Status]:
        """Reference filtering.go:285 Filter."""
        node = node_info.node
        if node is None:
            return Status.error("node not found")
        s = _get_pre_filter_state(state)
        if isinstance(s, Status):
            return s
        if not s.tp_pair_to_match_num or not s.constraints:
            return None
        pod_labels = pod.metadata.labels
        for c in s.constraints:
            tp_key = c.topology_key
            if tp_key not in node.metadata.labels:
                return Status.unschedulable(ERR_REASON_CONSTRAINTS_NOT_MATCH)
            tp_val = node.metadata.labels[tp_key]
            self_match = 1 if labels_match_selector(pod_labels, c.selector) else 0
            paths = s.tp_key_to_critical_paths.get(tp_key)
            if paths is None:
                continue
            min_match = paths.min_match_num()
            match_num = s.tp_pair_to_match_num.get((tp_key, tp_val), 0)
            skew = match_num + self_match - min_match
            if skew > c.max_skew:
                return Status.unschedulable(ERR_REASON_CONSTRAINTS_NOT_MATCH)
        return None

    # -- PreScore / Score (ScheduleAnyway) ----------------------------------

    def pre_score(
        self, state: CycleState, pod: Pod, nodes: List[NodeInfo]
    ) -> Optional[Status]:
        """Reference scoring.go:92 PreScore."""
        snapshot = state.read("__snapshot__")
        all_nodes = snapshot.list_node_infos()
        s = PreScoreState()
        state.write(PRE_SCORE_STATE_KEY, s)
        if not nodes or not all_nodes:
            return None
        s.constraints = _filter_constraints(
            pod.spec.topology_spread_constraints, SCHEDULE_ANYWAY
        )
        if not s.constraints:
            return None
        # init: eligible topology pairs come from *filtered* nodes only
        # (scoring.go:56 initPreScoreState).
        for ni in nodes:
            node = ni.node
            if node is None or not _node_labels_match_constraints(
                node.metadata.labels, s.constraints
            ):
                continue
            for c in s.constraints:
                pair = (c.topology_key, node.metadata.labels[c.topology_key])
                s.topology_pair_to_pod_counts.setdefault(pair, 0)
            s.node_name_set.add(node.metadata.name)
        # count matches over ALL nodes (scoring.go:120 processAllNode).
        for ni in all_nodes:
            node = ni.node
            if node is None:
                continue
            if not pod_matches_node_selector_and_affinity(pod, ni):
                continue
            if not _node_labels_match_constraints(
                node.metadata.labels, s.constraints
            ):
                continue
            for c in s.constraints:
                pair = (c.topology_key, node.metadata.labels[c.topology_key])
                if pair not in s.topology_pair_to_pod_counts:
                    continue
                match_sum = 0
                for existing in ni.pods:
                    if (
                        existing.metadata.deletion_timestamp is not None
                        or existing.metadata.namespace != pod.metadata.namespace
                    ):
                        continue
                    if labels_match_selector(
                        existing.metadata.labels, c.selector
                    ):
                        match_sum += 1
                s.topology_pair_to_pod_counts[pair] += match_sum
        return None

    def score(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> Tuple[int, Optional[Status]]:
        """Raw score = matching pod count (normalized later);
        reference scoring.go:166."""
        snapshot = state.read("__snapshot__")
        ni = snapshot.get_node_info(node_name)
        if ni is None or ni.node is None:
            return 0, Status.error(f"node {node_name} not in snapshot")
        try:
            s: PreScoreState = state.read(PRE_SCORE_STATE_KEY)
        except KeyError:
            return 0, Status.error(
                f"error reading {PRE_SCORE_STATE_KEY!r} from cycleState"
            )
        node = ni.node
        if node.metadata.name not in s.node_name_set:
            return 0, None
        score = 0
        for c in s.constraints:
            tp_val = node.metadata.labels.get(c.topology_key)
            if tp_val is not None:
                score += s.topology_pair_to_pod_counts.get(
                    (c.topology_key, tp_val), 0
                )
        return score, None

    def normalize_score(
        self, state: CycleState, pod: Pod, scores: List[NodeScore]
    ) -> Optional[Status]:
        """Reference scoring.go:199 NormalizeScore: flipped-linear against
        (total - min); ineligible nodes score 0."""
        try:
            s: PreScoreState = state.read(PRE_SCORE_STATE_KEY)
        except KeyError:
            return Status.error(
                f"error reading {PRE_SCORE_STATE_KEY!r} from cycleState"
            )
        # min stays MaxInt64 when no node is eligible, making the diff
        # non-zero so every node normalizes to 0 (matches reference).
        min_score = (1 << 63) - 1
        total = 0
        for ns in scores:
            if ns.name not in s.node_name_set:
                continue
            total += ns.score
            min_score = min(min_score, ns.score)
        max_min_diff = total - min_score
        for ns in scores:
            if max_min_diff == 0:
                ns.score = MAX_NODE_SCORE
                continue
            if ns.name not in s.node_name_set:
                ns.score = 0
                continue
            flipped = total - ns.score
            ns.score = int(MAX_NODE_SCORE * (flipped / max_min_diff))
        return None
