"""NodeName filter (reference framework/plugins/nodename/node_name.go)."""

from __future__ import annotations

from typing import Optional

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.cache.node_info import NodeInfo
from kubernetes_tpu.framework.interface import CycleState, Plugin, Status

ERR_REASON = "node(s) didn't match the requested hostname"


class NodeName(Plugin):
    NAME = "NodeName"

    def filter(
        self, state: CycleState, pod: Pod, node_info: NodeInfo
    ) -> Optional[Status]:
        if node_info.node is None:
            return Status.error("node not found")
        if pod.spec.node_name and pod.spec.node_name != node_info.node_name:
            return Status.unschedulable(ERR_REASON)
        return None
