"""In-process API server: storage, list/watch fan-out, binding subresource.

Reference: the apiserver+etcd pair the integration tests spin up
(/root/reference/test/integration/framework/master_utils.go:332, etcd3
store at staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go, watch
fan-out at storage/cacher/cacher.go:238). The control plane's only durable
state lives here; the scheduler holds soft state only and resumes by
re-list+watch, exactly like the reference.
"""

from kubernetes_tpu.apiserver.server import APIServer, Conflict, NotFound, WatchEvent

__all__ = ["APIServer", "Conflict", "NotFound", "WatchEvent"]
