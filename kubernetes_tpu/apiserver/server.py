"""The in-process API server.

Semantics modeled on the reference storage layer:

- monotonically increasing resourceVersion per write
  (etcd3/store.go: ModRevision)
- create is txn-if-absent (store.go:144); update uses optimistic
  concurrency on resourceVersion (store.go:220 GuaranteedUpdate)
- watch(since_rv) replays buffered events after rv, then streams live
  (storage/cacher/cacher.go:238 watchCache fan-out)
- the pods/binding subresource sets spec.nodeName under a guaranteed
  update and refuses to re-bind a bound pod
  (pkg/registry/core/pod/storage/storage.go:159-229 assignPod)

Objects returned by get/list and carried in watch events are shared
references: callers must treat them as read-only and deep-copy before
mutating (the same contract client-go informer caches impose).
"""

from __future__ import annotations

import threading
from bisect import bisect_right
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import POD_PENDING, POD_RUNNING, Binding, Node, Pod
from kubernetes_tpu.robustness.faults import FaultPoint, get_injector

try:
    from kubernetes_tpu.native import cow_clone as _cow_clone
    from kubernetes_tpu.native import bind_assumed_bulk as _bind_assumed_bulk
except Exception:  # noqa: BLE001 - pure-Python fallback
    _cow_clone = None
    _bind_assumed_bulk = None

_POD_COW_ATTRS = ("metadata", "spec", "status")

#: scheduler-side memo keys that ride object __dict__ copies. The bind
#: path only writes spec.node_name, which invalidates just the static-
#: mask signature; arbitrary updates (guaranteed_update's mutate, a
#: client update) may change anything, so every memo must go.
_SIG_MEMO = "_sig_memo"
_ALL_MEMOS = ("_sig_memo", "_hot_memo", "_req_memo", "_nzr_memo", "_packrow")


def _strip_memos(obj: Any) -> None:
    d = obj.__dict__
    for k in _ALL_MEMOS:
        d.pop(k, None)

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


class NotFound(KeyError):
    pass


class Conflict(ValueError):
    pass


class BindConflict(Conflict):
    """A typed bind conflict: the optimistic-concurrency answer of the
    multi-active control plane, NOT a transport failure. ``kind`` names
    the shape so the committer can absorb it through the requeue path
    (and the conflict ledger can account for it):

    - ``already-bound``: the pod is bound to a different node (a sibling
      stack won the race, or a takeover re-bind raced the original);
    - ``uid-mismatch``: the pod was deleted and recreated under the same
      key (a new incarnation -- the binding targeted the old one);
    - ``foreign-partition``: the binder's partition lease over the
      target node is held live by another stack (the server-side half of
      the commit fence, checked under the store lock)."""

    def __init__(self, message: str, kind: str = "already-bound",
                 current_node: str = "") -> None:
        super().__init__(message)
        self.kind = kind
        self.current_node = current_node


class Gone(Exception):
    """410 Gone analogue (apiserver storage.NewTooLargeResourceVersionError
    inverse): the requested since_rv predates the oldest retained watch
    event, so replay would silently miss events. The watcher must relist
    and diff instead. Deliberately NOT a KeyError/ValueError subclass --
    callers that treat those as not-found/conflict must not swallow it."""


def _api_unavailable_maybe() -> None:
    """Injected whole-transaction failure (the api_unavailable point):
    list/bind/guaranteed_update raise as if the server were unreachable;
    retry policies and informer relists are expected to absorb it."""
    inj = get_injector()
    if inj is not None:
        inj.raise_maybe(FaultPoint.API_UNAVAILABLE)


@dataclass(slots=True)
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    object: Any
    resource_version: int
    #: decode-once ingest record (the (namespace, name) key), filled
    #: lazily by the FIRST consumer that walks obj.metadata (native
    #: ingest_decode/ingest_apply or their Python twins) and shared by
    #: every later cursor draining the same per-kind event log -- N
    #: partitioned informer sets decode each apiserver transaction once
    decoded: Any = None


class Watch:
    """One client watch stream: a CURSOR into the kind's shared event
    log, not a private mailbox.

    The original design delivered every event into a per-watch deque --
    one lock round trip and one copy per event PER WATCHER, so N active
    scheduler stacks multiplied the in-process fan-out cost of every
    store transaction by N (the event loop cost ROADMAP item 4 calls
    out). Here producers append to the kind's bounded history ONCE
    (which replay already required) and notify a per-kind condition;
    each watcher drains ``history[cursor:]`` in batches on its own
    schedule. Broadcast is O(events), independent of watcher count
    (tools/bench_hotpath.py ``watch_fanout_*`` pins this).

    A watcher that lags so far that the history trim passes its cursor
    raises ``Gone`` on the next read -- exactly the 410 semantics a
    reconnecting watcher already handles (informers relist+diff).
    """

    __slots__ = ("_server", "kind", "_cursor", "stopped")

    def __init__(self, server: "APIServer", kind: str, cursor: int):
        self._server = server
        self.kind = kind
        #: absolute event ordinal (monotone per kind, survives trims)
        self._cursor = cursor
        self.stopped = False

    def _drain_locked(self) -> List[WatchEvent]:
        """Caller holds the kind condition."""
        srv = self._server
        base = srv._history_base[self.kind]
        hist = srv._history[self.kind]
        if self._cursor < base:
            raise Gone(
                f"{self.kind} watch lagged past the history trim "
                f"(cursor {self._cursor} < base {base}); relist"
            )
        idx = self._cursor - base
        out = hist[idx:] if idx < len(hist) else []
        self._cursor = base + len(hist)
        return list(out)

    def _has_pending_locked(self) -> bool:
        srv = self._server
        return (
            self._cursor
            < srv._history_base[self.kind] + len(srv._history[self.kind])
        )

    def next(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        """Next event, or None on stop/timeout."""
        cond = self._server._kind_conds[self.kind]
        with cond:
            if not self._has_pending_locked() and not self.stopped:
                cond.wait(timeout)
            srv = self._server
            base = srv._history_base[self.kind]
            hist = srv._history[self.kind]
            if self._cursor < base:
                raise Gone(
                    f"{self.kind} watch lagged past the history trim"
                )
            idx = self._cursor - base
            if idx >= len(hist):
                return None
            self._cursor += 1
            return hist[idx]

    def next_batch(
        self, timeout: Optional[float] = None
    ) -> List[WatchEvent]:
        """Block for at least one event (or stop/timeout), then drain
        everything pending."""
        cond = self._server._kind_conds[self.kind]
        with cond:
            if not self._has_pending_locked() and not self.stopped:
                cond.wait(timeout)
            return self._drain_locked()

    def pending(self) -> List[WatchEvent]:
        """Drain without blocking (used by the synchronous pump mode)."""
        cond = self._server._kind_conds[self.kind]
        with cond:
            return self._drain_locked()

    def stop(self) -> None:
        self._server._remove_watch(self)
        cond = self._server._kind_conds.get(self.kind)
        self.stopped = True
        if cond is not None:
            with cond:
                cond.notify_all()


def _obj_key(obj: Any) -> Tuple[str, str]:
    meta = obj.metadata
    return (meta.namespace, meta.name)


def _route_key(kind: str, obj: Any) -> str:
    """The per-host routing key of an event: which single consumer (if
    any) a routed watcher set would want it delivered to. Pods route by
    the node they are bound to (a kubelet's spec.nodeName-filtered
    watch); everything else routes by object name (node-lease renewals
    and NodeStatus writes route to that node's watcher)."""
    if kind == "Pod":
        return obj.spec.node_name or ""
    return obj.metadata.name


class RoutedWatch:
    """A route-filtered watch cursor with a PRIVATE buffer.

    Unlike ``Watch`` (a cursor into the kind's shared log, where every
    watcher drains every event), a RoutedWatch registers the route keys
    it wants (node names) and the broadcast path delivers each event to
    the interested watchers ONLY -- one dict probe per event, zero work
    per uninterested watcher. This is what keeps fleet-scale heartbeat
    traffic O(interested) instead of O(watchers): ten thousand hollow
    kubelets sharing a kind do not each rescan every sibling's Lease
    renewals (tools/bench_hotpath.py ``heartbeat_fanout_*`` pins this).

    Events that never had a route (an unbound pod) are invisible here by
    design -- a kubelet only cares once spec.nodeName points at it. A
    consumer that stalls past the server's history limit overflows its
    buffer and gets ``Gone`` on the next read (relist, same 410 contract
    as a lagged shared-log cursor).
    """

    __slots__ = ("_server", "kind", "routes", "_events", "_overflowed",
                 "stopped")

    def __init__(self, server: "APIServer", kind: str, routes) -> None:
        self._server = server
        self.kind = kind
        self.routes = frozenset(routes)
        self._events: List[WatchEvent] = []
        self._overflowed = False
        self.stopped = False

    def _deliver_locked(self, ev: WatchEvent) -> None:
        """Caller holds the kind condition (the broadcast path)."""
        if self._overflowed:
            return
        if len(self._events) >= self._server._history_limit:
            self._overflowed = True
            self._events = []
            return
        self._events.append(ev)

    def _drain_locked(self) -> List[WatchEvent]:
        if self._overflowed:
            self._overflowed = False
            raise Gone(
                f"{self.kind} routed watch overflowed its buffer; relist"
            )
        out = self._events
        self._events = []
        return out

    def next_batch(
        self, timeout: Optional[float] = None
    ) -> List[WatchEvent]:
        cond = self._server._kind_conds[self.kind]
        with cond:
            if not self._events and not self._overflowed \
                    and not self.stopped:
                cond.wait(timeout)
            return self._drain_locked()

    def pending(self) -> List[WatchEvent]:
        cond = self._server._kind_conds[self.kind]
        with cond:
            return self._drain_locked()

    def stop(self) -> None:
        self._server._remove_watch(self)
        cond = self._server._kind_conds.get(self.kind)
        self.stopped = True
        if cond is not None:
            with cond:
                cond.notify_all()


class APIServer:
    """Multi-kind object store with watch fan-out."""

    #: pre-registered kinds; any other kind gets a store on first use
    #: (the REST-registry analogue: pkg/registry/ storage per resource)
    KINDS = (
        "Pod", "Node", "PodDisruptionBudget", "PodGroup", "Lease", "Service",
        "PersistentVolume", "PersistentVolumeClaim", "StorageClass",
        "CSINode", "ReplicationController", "ReplicaSet", "StatefulSet",
        "Secret", "PriorityClass", "ResourceQuota",
    )

    def __init__(self, watch_history_limit: int = 200_000) -> None:
        self._lock = threading.RLock()
        self._rv = 0
        self._stores: Dict[str, Dict[Tuple[str, str], Any]] = {
            k: {} for k in self.KINDS
        }
        # the shared per-kind event log IS the watch fan-out: watchers
        # hold cursors into it (see Watch), so broadcast is O(events)
        # regardless of watcher count. `_history_base[kind]` is the
        # absolute ordinal of history[0] (bumped by trims, so cursors
        # survive them); `_kind_conds` serializes log mutation against
        # watcher reads without the store lock.
        self._history: Dict[str, List[WatchEvent]] = {k: [] for k in self.KINDS}
        self._history_base: Dict[str, int] = {k: 0 for k in self.KINDS}
        self._kind_conds: Dict[str, threading.Condition] = {
            k: threading.Condition() for k in self.KINDS
        }
        self._history_limit = watch_history_limit
        # highest rv ever trimmed out of a kind's history: a watch asking
        # to replay from below this would silently miss events -> Gone
        self._history_trunc_rv: Dict[str, int] = {k: 0 for k in self.KINDS}
        # per-host routed delivery: kind -> route key -> interested
        # RoutedWatch list (guarded by the kind condition). Empty unless
        # someone opened a routed watch, so the broadcast fast path pays
        # one falsy dict probe per transaction.
        self._route_watchers: Dict[str, Dict[str, List[RoutedWatch]]] = {}
        # multi-active partitioned scheduling (scheduler/partition.py):
        # when installed, bulk binds carrying a binder identity are
        # checked against the live partition leases under the store lock
        self._partition_authority = None

    def _ensure_kind(self, kind: str) -> None:
        if kind not in self._stores:
            self._stores[kind] = {}
            self._history[kind] = []
            self._history_base[kind] = 0
            self._kind_conds[kind] = threading.Condition()
            self._history_trunc_rv[kind] = 0

    def install_partition_authority(self, authority) -> None:
        """Install the server-side partition bind fence (an object with
        ``check(binder, node_name) -> Optional[str]``); None clears."""
        with self._lock:
            self._partition_authority = authority

    # -- core ---------------------------------------------------------------

    def _next_rv(self) -> int:
        self._rv += 1
        return self._rv

    def _trim_history_locked(self, kind: str, hist: List[WatchEvent]) -> None:
        """Caller holds the kind condition."""
        if len(hist) > self._history_limit:
            cut = len(hist) // 2
            # record the highest discarded rv so watch(since_rv) can
            # detect a replay gap instead of silently skipping it, and
            # advance the base so live cursors keep their meaning (a
            # cursor below the new base is Gone on its next read)
            self._history_trunc_rv[kind] = hist[cut - 1].resource_version
            self._history_base[kind] += cut
            del hist[:cut]

    def _route_locked(self, kind: str, event: WatchEvent) -> None:
        """Deliver one event to the routed watchers interested in its
        route key (caller holds the kind condition). One dict probe per
        event when the routing index is armed; nothing otherwise."""
        idx = self._route_watchers.get(kind)
        if not idx:
            return
        route = _route_key(kind, event.object)
        if not route:
            return
        watchers = idx.get(route)
        if watchers:
            for w in watchers:
                w._deliver_locked(event)

    def _broadcast(self, kind: str, event: WatchEvent) -> None:
        cond = self._kind_conds[kind]
        with cond:
            hist = self._history[kind]
            hist.append(event)
            self._trim_history_locked(kind, hist)
            self._route_locked(kind, event)
            cond.notify_all()

    def _broadcast_many(self, kind: str, events: List[WatchEvent]) -> None:
        """One log extend + ONE wakeup for a whole transaction's worth
        of events: watchers drain the log in batches, so the per-event
        cost no longer scales with the watcher count (the bulk-bind
        fan-out path under N active stacks)."""
        if not events:
            return
        cond = self._kind_conds[kind]
        with cond:
            hist = self._history[kind]
            hist.extend(events)
            self._trim_history_locked(kind, hist)
            if self._route_watchers.get(kind):
                for ev in events:
                    self._route_locked(kind, ev)
            cond.notify_all()

    def current_rv(self) -> int:
        with self._lock:
            return self._rv

    # -- CRUD ---------------------------------------------------------------

    def create(self, obj: Any) -> Any:
        kind = obj.kind
        with self._lock:
            self._ensure_kind(kind)
            store = self._stores[kind]
            key = _obj_key(obj)
            if key in store:
                raise Conflict(f"{kind} {key} already exists")
            obj.metadata.resource_version = self._next_rv()
            store[key] = obj
            self._broadcast(kind, WatchEvent(ADDED, obj, obj.metadata.resource_version))
            return obj

    def create_bulk(self, objs: List[Any]) -> List[Any]:
        """Create many objects of one kind in a single store transaction
        with one bulk watch fan-out -- the ingestion analogue of
        bind_bulk. All-or-nothing per object (a conflict raises after none
        of the later objects are applied), matching N sequential creates
        that stop at the first failure."""
        if not objs:
            return objs
        kind = objs[0].kind
        events: List[WatchEvent] = []
        with self._lock:
            self._ensure_kind(kind)
            store = self._stores[kind]
            for obj in objs:
                if obj.kind != kind:
                    raise ValueError("create_bulk objects must share a kind")
                key = _obj_key(obj)
                if key in store:
                    self._broadcast_many(kind, events)
                    raise Conflict(f"{kind} {key} already exists")
                obj.metadata.resource_version = self._next_rv()
                store[key] = obj
                events.append(
                    WatchEvent(ADDED, obj, obj.metadata.resource_version)
                )
            self._broadcast_many(kind, events)
        return objs

    def get(self, kind: str, namespace: str, name: str) -> Any:
        with self._lock:
            self._ensure_kind(kind)
            obj = self._stores[kind].get((namespace, name))
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            return obj

    def list(self, kind: str) -> Tuple[List[Any], int]:
        """Returns (objects, resourceVersion) -- the list+watch handshake."""
        _api_unavailable_maybe()
        with self._lock:
            self._ensure_kind(kind)
            return list(self._stores[kind].values()), self._rv

    def update(self, obj: Any, expect_rv: Optional[int] = None) -> Any:
        """Replace; optimistic-concurrency check when expect_rv given."""
        kind = obj.kind
        with self._lock:
            self._ensure_kind(kind)
            store = self._stores[kind]
            key = _obj_key(obj)
            current = store.get(key)
            if current is None:
                raise NotFound(f"{kind} {key} not found")
            if expect_rv is not None and current.metadata.resource_version != expect_rv:
                raise Conflict(
                    f"{kind} {key}: resourceVersion {expect_rv} is stale "
                    f"(current {current.metadata.resource_version})"
                )
            # the replacement may be a clone carrying scheduler memos
            # computed against the OLD spec
            _strip_memos(obj)
            obj.metadata.resource_version = self._next_rv()
            store[key] = obj
            self._broadcast(
                kind, WatchEvent(MODIFIED, obj, obj.metadata.resource_version)
            )
            return obj

    def guaranteed_update(
        self, kind: str, namespace: str, name: str, mutate: Callable[[Any], None]
    ) -> Any:
        """Atomic read-modify-write (etcd3 store.go:220 GuaranteedUpdate).

        Copy-on-write: the previously stored object stays intact so informer
        caches can hand handlers a distinct (old, new) pair -- the reference
        gets this for free from serialization; mutators must not mutate
        nested collections in place.
        """
        import copy as _copy

        _api_unavailable_maybe()
        with self._lock:
            old = self.get(kind, namespace, name)
            cow_attrs = tuple(
                a for a in _POD_COW_ATTRS if hasattr(old, a)
            )
            if _cow_clone is not None:
                obj = _cow_clone(old, cow_attrs)
            else:
                obj = _copy.copy(old)
                for attr in cow_attrs:
                    setattr(obj, attr, _copy.copy(getattr(old, attr)))
            _strip_memos(obj)
            mutate(obj)
            obj.metadata.resource_version = self._next_rv()
            self._stores[kind][(namespace, name)] = obj
            self._broadcast(
                kind, WatchEvent(MODIFIED, obj, obj.metadata.resource_version)
            )
            return obj

    def delete(
        self, kind: str, namespace: str, name: str,
        expect_uid: Optional[str] = None,
    ) -> Any:
        """``expect_uid``: uid-preconditioned delete (the Kubernetes
        delete-options Preconditions.UID analogue), checked atomically
        under the store lock -- a delayed eviction can fence itself
        against a respawned same-name incarnation without a racy
        read-then-delete."""
        with self._lock:
            self._ensure_kind(kind)
            obj = self._stores[kind].get((namespace, name))
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            if expect_uid is not None and obj.metadata.uid != expect_uid:
                raise Conflict(
                    f"{kind} {namespace}/{name}: uid "
                    f"{obj.metadata.uid} does not match precondition "
                    f"{expect_uid}"
                )
            self._stores[kind].pop((namespace, name))
            rv = self._next_rv()
            self._broadcast(kind, WatchEvent(DELETED, obj, rv))
            return obj

    def delete_bulk(
        self, kind: str, keys: List[Tuple[str, str]],
        missing_out: Optional[List[Tuple[str, str]]] = None,
    ) -> int:
        """Delete many objects of one kind in a single transaction with
        one bulk watch fan-out (the eviction analogue of bind_bulk);
        missing keys are skipped (and appended to ``missing_out`` when
        given, so an evictor that pre-spent a disruption budget can
        refund the units whose delete evicted nothing). Returns the
        number deleted."""
        events: List[WatchEvent] = []
        with self._lock:
            self._ensure_kind(kind)
            store = self._stores[kind]
            for namespace, name in keys:
                obj = store.pop((namespace, name), None)
                if obj is None:
                    if missing_out is not None:
                        missing_out.append((namespace, name))
                    continue
                events.append(WatchEvent(DELETED, obj, self._next_rv()))
            self._broadcast_many(kind, events)
        return len(events)

    # -- watch --------------------------------------------------------------

    def watch(self, kind: str, since_rv: int = 0) -> Watch:
        with self._lock:
            self._ensure_kind(kind)
            inj = get_injector()
            if inj is not None and inj.should_fire(
                FaultPoint.WATCH_HISTORY_TRUNCATED
            ):
                raise Gone(
                    f"{kind} watch history truncated (injected 410)"
                )
            if since_rv < self._history_trunc_rv.get(kind, 0):
                # events in (since_rv, trunc_rv] were trimmed: replaying
                # only what's retained would silently skip them
                raise Gone(
                    f"{kind} watch history truncated past rv "
                    f"{self._history_trunc_rv[kind]}; cannot replay from "
                    f"{since_rv}"
                )
            # cursor = first retained event with rv > since_rv (the
            # kind's rv sequence is monotone, so bisect positions the
            # replay start without scanning)
            cond = self._kind_conds[kind]
            with cond:
                hist = self._history[kind]
                rvs = [ev.resource_version for ev in hist]
                idx = bisect_right(rvs, since_rv)
                cursor = self._history_base[kind] + idx
            return Watch(self, kind, cursor)

    def watch_routes(
        self, kind: str, routes, since_rv: int = 0
    ) -> RoutedWatch:
        """Open a route-filtered watch: only events whose route key
        (Pod -> spec.nodeName, else metadata.name) is in ``routes`` are
        delivered. Retained history after ``since_rv`` is replayed
        (filtered) into the buffer at registration, so the list+watch
        handshake works exactly like the shared-log cursor; a since_rv
        below the trim raises Gone."""
        with self._lock:
            self._ensure_kind(kind)
            if since_rv < self._history_trunc_rv.get(kind, 0):
                raise Gone(
                    f"{kind} watch history truncated past rv "
                    f"{self._history_trunc_rv[kind]}; cannot replay from "
                    f"{since_rv}"
                )
            cond = self._kind_conds[kind]
            with cond:
                w = RoutedWatch(self, kind, routes)
                hist = self._history[kind]
                rvs = [ev.resource_version for ev in hist]
                idx = bisect_right(rvs, since_rv)
                for ev in hist[idx:]:
                    if _route_key(kind, ev.object) in w.routes:
                        w._deliver_locked(ev)
                index = self._route_watchers.setdefault(kind, {})
                for route in w.routes:
                    index.setdefault(route, []).append(w)
            return w

    def _remove_watch(self, w) -> None:
        # shared-log cursors hold no server-side state; routed watchers
        # unregister from the delivery index
        if not isinstance(w, RoutedWatch):
            return
        cond = self._kind_conds.get(w.kind)
        if cond is None:
            return
        with cond:
            index = self._route_watchers.get(w.kind)
            if not index:
                return
            for route in w.routes:
                watchers = index.get(route)
                if watchers and w in watchers:
                    watchers.remove(w)
                    if not watchers:
                        del index[route]
            if not index:
                self._route_watchers.pop(w.kind, None)

    # -- pods/binding subresource (storage.go:159 BindingREST.Create) -------

    def _bind_locked(
        self, binding: Binding, binder: Optional[str] = None
    ) -> Tuple[Pod, bool]:
        """Validate + apply one binding; caller holds the store lock.
        Returns (pod, changed) and appends nothing -- the caller decides
        how to fan out the watch event (single vs bulk delivery).
        ``changed`` is False when the pod was ALREADY bound to the same
        node: a retried commit whose first attempt actually landed (or a
        restarted scheduler re-driving a recovered placement) is
        idempotent success, not a conflict -- no write, no event.
        Conflicts raise TYPED ``BindConflict``s so a multi-active
        committer can absorb them through the requeue path instead of
        treating them as scheduler errors."""
        store = self._stores["Pod"]
        old: Optional[Pod] = store.get(
            (binding.pod_namespace, binding.pod_name)
        )
        if old is None:
            raise NotFound(
                f"Pod {binding.pod_namespace}/{binding.pod_name} not found"
            )
        if binding.pod_uid and old.metadata.uid != binding.pod_uid:
            raise BindConflict(
                f"pod {old.key()} uid mismatch: binding has "
                f"{binding.pod_uid}, pod has {old.metadata.uid}",
                kind="uid-mismatch",
            )
        if old.spec.node_name:
            if old.spec.node_name == binding.target_node:
                return old, False
            raise BindConflict(
                f"pod {old.key()} is already bound to {old.spec.node_name}",
                kind="already-bound",
                current_node=old.spec.node_name,
            )
        if not binding.target_node:
            raise ValueError("binding.target_node is required")
        auth = self._partition_authority
        if auth is not None and binder is not None:
            reason = auth.check(binder, binding.target_node)
            if reason:
                raise BindConflict(
                    f"pod {old.key()}: binder {binder!r} does not own "
                    f"the partition of node {binding.target_node!r}",
                    kind=reason,
                )
        # copy-on-write update (guaranteed_update semantics); the native
        # clone replaces a 4-deep copy.copy chain on the burst's hottest
        # store transaction (10k binds per measured window)
        if _cow_clone is not None:
            pod = _cow_clone(old, _POD_COW_ATTRS)
        else:
            import copy as _copy

            pod = _copy.copy(old)
            pod.metadata = _copy.copy(old.metadata)
            pod.spec = _copy.copy(old.spec)
            pod.status = _copy.copy(old.status)
        pod.spec.node_name = binding.target_node
        pod.__dict__.pop(_SIG_MEMO, None)
        pod.metadata.resource_version = self._next_rv()
        store[(binding.pod_namespace, binding.pod_name)] = pod
        return pod, True

    def bind(self, binding: Binding, binder: Optional[str] = None) -> Pod:
        _api_unavailable_maybe()
        with self._lock:
            pod, changed = self._bind_locked(binding, binder=binder)
            if changed:
                self._broadcast(
                    "Pod",
                    WatchEvent(MODIFIED, pod, pod.metadata.resource_version),
                )
            return pod

    def unbind(
        self, namespace: str, name: str,
        expect_uid: Optional[str] = None,
        expect_node: Optional[str] = None,
    ) -> Pod:
        """Atomically release a binding: clear spec.nodeName, reset the
        phase to Pending, drop start_time. The rebind-after-timeout
        primitive of the closed bind loop -- a bound-but-never-acked pod
        goes back to unbound UNDER THE STORE LOCK, fenced three ways:

        - ``expect_uid``: the incarnation the ack deadline was armed for
          (a respawn under the same key must not be unbound);
        - ``expect_node``: the node the bind targeted (a racing rebind
          that already moved the pod must not be undone);
        - the pod must not be ``Running`` yet: a kubelet ack that lands
          first WINS and the unbind comes back as a typed ``acked``
          conflict (the tracker treats that as the ack it was waiting
          for). The store lock is the serialization point, so exactly
          one of {ack, unbind} takes effect.

        The MODIFIED bound->unbound event re-enters the pod into the
        scheduling queue and releases the zombie node's capacity through
        the ordinary cache-removal/slot-scatter path -- no scheduler
        side channel."""
        _api_unavailable_maybe()
        with self._lock:
            store = self._stores["Pod"]
            old: Optional[Pod] = store.get((namespace, name))
            if old is None:
                raise NotFound(f"Pod {namespace}/{name} not found")
            if expect_uid is not None and old.metadata.uid != expect_uid:
                raise BindConflict(
                    f"pod {old.key()} uid mismatch: unbind targeted "
                    f"{expect_uid}, pod has {old.metadata.uid}",
                    kind="uid-mismatch",
                )
            if not old.spec.node_name:
                return old  # already unbound: idempotent success
            if (
                expect_node is not None
                and old.spec.node_name != expect_node
            ):
                raise BindConflict(
                    f"pod {old.key()} is bound to {old.spec.node_name}, "
                    f"not {expect_node}",
                    kind="already-bound",
                    current_node=old.spec.node_name,
                )
            if old.status.phase == POD_RUNNING:
                raise BindConflict(
                    f"pod {old.key()} was acked Running on "
                    f"{old.spec.node_name}; binding stands",
                    kind="acked",
                    current_node=old.spec.node_name,
                )
            if _cow_clone is not None:
                pod = _cow_clone(old, _POD_COW_ATTRS)
            else:
                import copy as _copy

                pod = _copy.copy(old)
                pod.metadata = _copy.copy(old.metadata)
                pod.spec = _copy.copy(old.spec)
                pod.status = _copy.copy(old.status)
            pod.spec.node_name = ""
            pod.status.phase = POD_PENDING
            pod.status.start_time = None
            _strip_memos(pod)
            pod.metadata.resource_version = self._next_rv()
            store[(namespace, name)] = pod
            self._broadcast(
                "Pod",
                WatchEvent(MODIFIED, pod, pod.metadata.resource_version),
            )
            return pod

    def bind_bulk(
        self, bindings: List[Binding], binder: Optional[str] = None
    ) -> List[Tuple[Optional[Pod], Optional[Exception]]]:
        """Pipelined bulk commit: all bindings validated and applied under
        ONE store transaction (the batch analogue of per-pod
        BindingREST.Create, storage.go:159). Per-binding failures don't
        abort the rest -- each slot returns (pod, None) or (None, error),
        mirroring N independent API calls minus N-1 lock round trips.
        Watch events for the whole transaction fan out in one bulk
        delivery per watcher. ``binder`` identifies the committing stack
        for the partition authority's server-side fence."""
        _api_unavailable_maybe()
        out: List[Tuple[Optional[Pod], Optional[Exception]]] = []
        events: List[WatchEvent] = []
        with self._lock:
            for binding in bindings:
                try:
                    pod, changed = self._bind_locked(binding, binder=binder)
                    if changed:
                        events.append(
                            WatchEvent(
                                MODIFIED, pod, pod.metadata.resource_version
                            )
                        )
                    out.append((pod, None))
                except Exception as e:  # noqa: BLE001 - per-slot result
                    out.append((None, e))
            self._broadcast_many("Pod", events)
        return out

    def bind_assumed_bulk(
        self, assumed_pods: List[Pod], binder: Optional[str] = None
    ) -> List[Tuple[int, Exception]]:
        """Bulk bind commit driven directly by the scheduler's assumed
        clones (metadata carries namespace/name/uid, spec.node_name the
        target) -- the allocation-free fast path of ``bind_bulk``: no
        Binding objects, no per-slot result tuples. Returns only the
        failed slots as (index, error); an empty list means every pod
        bound. The whole transaction runs under one store lock with one
        bulk watch fan-out, through the native C loop when available
        (native/_hotpath.c bind_assumed_bulk).

        ``binder`` arms the partition authority's server-side fence:
        pods targeting a node whose partition lease is held live by a
        DIFFERENT stack come back as typed ``foreign-partition``
        conflicts. The check runs in Python BEFORE the native loop (the
        loop stays partition-blind); surviving slots remap through
        ``idx_map`` so error indexes stay caller-relative."""
        _api_unavailable_maybe()
        with self._lock:
            pods = assumed_pods
            idx_map: Optional[List[int]] = None
            pre: List[Tuple[int, Exception]] = []
            auth = self._partition_authority
            if auth is not None and binder is not None:
                allowed: List[Pod] = []
                idx_map = []
                verdict: Dict[str, Optional[str]] = {}
                for i, a in enumerate(assumed_pods):
                    node = a.spec.node_name
                    reason = verdict.get(node, "")
                    if reason == "":
                        reason = auth.check(binder, node)
                        verdict[node] = reason
                    if reason:
                        pre.append((i, BindConflict(
                            f"pod {a.key()}: binder {binder!r} does not "
                            f"own the partition of node {node!r}",
                            kind=reason,
                        )))
                    else:
                        allowed.append(a)
                        idx_map.append(i)
                pods = allowed

            def caller_idx(i: int) -> int:
                return idx_map[i] if idx_map is not None else i

            if _bind_assumed_bulk is not None:
                errors, events, new_rv = _bind_assumed_bulk(
                    self._stores["Pod"], pods, self._rv, WatchEvent
                )
                self._rv = new_rv
                self._broadcast_many("Pod", events)
                if not errors:
                    return pre
                store = self._stores["Pod"]
                out: List[Tuple[int, Exception]] = list(pre)
                for idx, code, msg in errors:
                    exc: Exception
                    if code == 0:
                        exc = NotFound(msg)
                    elif code == 1:
                        # idempotent same-node re-bind (a retried commit
                        # whose first attempt landed, or a restarted
                        # scheduler re-driving a recovered placement):
                        # the C loop reports it as a conflict, but the
                        # store already holds exactly the requested state
                        a = pods[idx]
                        cur = store.get(
                            (a.metadata.namespace, a.metadata.name)
                        )
                        if (
                            cur is not None
                            and cur.spec.node_name == a.spec.node_name
                            and cur.metadata.uid == a.metadata.uid
                        ):
                            continue
                        kind = (
                            "uid-mismatch"
                            if cur is not None
                            and cur.metadata.uid != a.metadata.uid
                            else "already-bound"
                        )
                        exc = BindConflict(
                            msg, kind=kind,
                            current_node=(
                                cur.spec.node_name if cur is not None else ""
                            ),
                        )
                    elif code == 2:
                        exc = ValueError(msg)
                    else:
                        exc = RuntimeError(msg)
                    out.append((caller_idx(idx), exc))
                return out
            # pure-Python fallback: delegate to the shared bind_bulk
            # transaction (one loop to maintain) and convert its per-slot
            # results to the failures-only shape (the authority already
            # ran above; don't pass binder down and double-check)
            results = self.bind_bulk(
                [
                    Binding(
                        pod_namespace=a.metadata.namespace,
                        pod_name=a.metadata.name,
                        pod_uid=a.metadata.uid,
                        target_node=a.spec.node_name,
                    )
                    for a in pods
                ]
            )
            return pre + [
                (caller_idx(i), err)
                for i, (_pod, err) in enumerate(results)
                if err is not None
            ]

    # -- pod status subresource ---------------------------------------------

    def update_pod_status(
        self, namespace: str, name: str, mutate: Callable[[Pod], None]
    ) -> Pod:
        def wrap(p: Pod) -> None:
            mutate(p)

        return self.guaranteed_update("Pod", namespace, name, wrap)
