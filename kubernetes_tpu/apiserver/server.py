"""The in-process API server.

Semantics modeled on the reference storage layer:

- monotonically increasing resourceVersion per write
  (etcd3/store.go: ModRevision)
- create is txn-if-absent (store.go:144); update uses optimistic
  concurrency on resourceVersion (store.go:220 GuaranteedUpdate)
- watch(since_rv) replays buffered events after rv, then streams live
  (storage/cacher/cacher.go:238 watchCache fan-out)
- the pods/binding subresource sets spec.nodeName under a guaranteed
  update and refuses to re-bind a bound pod
  (pkg/registry/core/pod/storage/storage.go:159-229 assignPod)

Objects returned by get/list and carried in watch events are shared
references: callers must treat them as read-only and deep-copy before
mutating (the same contract client-go informer caches impose).
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import Binding, Node, Pod
from kubernetes_tpu.robustness.faults import FaultPoint, get_injector

try:
    from kubernetes_tpu.native import cow_clone as _cow_clone
    from kubernetes_tpu.native import bind_assumed_bulk as _bind_assumed_bulk
except Exception:  # noqa: BLE001 - pure-Python fallback
    _cow_clone = None
    _bind_assumed_bulk = None

_POD_COW_ATTRS = ("metadata", "spec", "status")

#: scheduler-side memo keys that ride object __dict__ copies. The bind
#: path only writes spec.node_name, which invalidates just the static-
#: mask signature; arbitrary updates (guaranteed_update's mutate, a
#: client update) may change anything, so every memo must go.
_SIG_MEMO = "_sig_memo"
_ALL_MEMOS = ("_sig_memo", "_hot_memo", "_req_memo", "_nzr_memo")


def _strip_memos(obj: Any) -> None:
    d = obj.__dict__
    for k in _ALL_MEMOS:
        d.pop(k, None)

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


class NotFound(KeyError):
    pass


class Conflict(ValueError):
    pass


class Gone(Exception):
    """410 Gone analogue (apiserver storage.NewTooLargeResourceVersionError
    inverse): the requested since_rv predates the oldest retained watch
    event, so replay would silently miss events. The watcher must relist
    and diff instead. Deliberately NOT a KeyError/ValueError subclass --
    callers that treat those as not-found/conflict must not swallow it."""


def _api_unavailable_maybe() -> None:
    """Injected whole-transaction failure (the api_unavailable point):
    list/bind/guaranteed_update raise as if the server were unreachable;
    retry policies and informer relists are expected to absorb it."""
    inj = get_injector()
    if inj is not None:
        inj.raise_maybe(FaultPoint.API_UNAVAILABLE)


@dataclass(slots=True)
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    object: Any
    resource_version: int


class Watch:
    """One client watch stream.

    Events land in a deque under a Condition; producers can deliver in
    bulk (one lock round trip per transaction instead of per event) and
    consumers can drain in bulk (``next_batch``) -- the in-proc analogue
    of the reference's HTTP/2 watch stream frames carrying many events
    per read.
    """

    def __init__(self, server: "APIServer", kind: str):
        self._server = server
        self.kind = kind
        self._items: "deque[WatchEvent]" = deque()
        self._cond = threading.Condition()
        self.stopped = False

    def _deliver(self, event: WatchEvent) -> None:
        with self._cond:
            self._items.append(event)
            self._cond.notify()

    def _deliver_many(self, events: List[WatchEvent]) -> None:
        with self._cond:
            self._items.extend(events)
            self._cond.notify()

    def next(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        """Next event, or None on stop/timeout."""
        with self._cond:
            if not self._items and not self.stopped:
                self._cond.wait(timeout)
            if not self._items:
                return None
            return self._items.popleft()

    def next_batch(
        self, timeout: Optional[float] = None
    ) -> List[WatchEvent]:
        """Block for at least one event (or stop/timeout), then drain
        everything pending."""
        with self._cond:
            if not self._items and not self.stopped:
                self._cond.wait(timeout)
            out = list(self._items)
            self._items.clear()
            return out

    def pending(self) -> List[WatchEvent]:
        """Drain without blocking (used by the synchronous pump mode)."""
        with self._cond:
            out = list(self._items)
            self._items.clear()
            return out

    def stop(self) -> None:
        self._server._remove_watch(self)
        with self._cond:
            self.stopped = True
            self._cond.notify_all()


def _obj_key(obj: Any) -> Tuple[str, str]:
    meta = obj.metadata
    return (meta.namespace, meta.name)


class APIServer:
    """Multi-kind object store with watch fan-out."""

    #: pre-registered kinds; any other kind gets a store on first use
    #: (the REST-registry analogue: pkg/registry/ storage per resource)
    KINDS = (
        "Pod", "Node", "PodDisruptionBudget", "PodGroup", "Lease", "Service",
        "PersistentVolume", "PersistentVolumeClaim", "StorageClass",
        "CSINode", "ReplicationController", "ReplicaSet", "StatefulSet",
        "Secret",
    )

    def __init__(self, watch_history_limit: int = 200_000) -> None:
        self._lock = threading.RLock()
        self._rv = 0
        self._stores: Dict[str, Dict[Tuple[str, str], Any]] = {
            k: {} for k in self.KINDS
        }
        self._watches: Dict[str, List[Watch]] = {k: [] for k in self.KINDS}
        # bounded per-kind event history for watch(since_rv) replay
        self._history: Dict[str, List[WatchEvent]] = {k: [] for k in self.KINDS}
        self._history_limit = watch_history_limit
        # highest rv ever trimmed out of a kind's history: a watch asking
        # to replay from below this would silently miss events -> Gone
        self._history_trunc_rv: Dict[str, int] = {k: 0 for k in self.KINDS}

    def _ensure_kind(self, kind: str) -> None:
        if kind not in self._stores:
            self._stores[kind] = {}
            self._watches[kind] = []
            self._history[kind] = []
            self._history_trunc_rv[kind] = 0

    # -- core ---------------------------------------------------------------

    def _next_rv(self) -> int:
        self._rv += 1
        return self._rv

    def _trim_history(self, kind: str, hist: List[WatchEvent]) -> None:
        if len(hist) > self._history_limit:
            cut = len(hist) // 2
            # record the highest discarded rv so watch(since_rv) can
            # detect a replay gap instead of silently skipping it
            self._history_trunc_rv[kind] = hist[cut - 1].resource_version
            del hist[:cut]

    def _broadcast(self, kind: str, event: WatchEvent) -> None:
        hist = self._history[kind]
        hist.append(event)
        self._trim_history(kind, hist)
        for w in list(self._watches[kind]):
            w._deliver(event)

    def _broadcast_many(self, kind: str, events: List[WatchEvent]) -> None:
        """One history extend + one per-watch lock round trip for a whole
        transaction's worth of events (the bulk-bind fan-out path)."""
        if not events:
            return
        hist = self._history[kind]
        hist.extend(events)
        self._trim_history(kind, hist)
        for w in list(self._watches[kind]):
            w._deliver_many(events)

    def current_rv(self) -> int:
        with self._lock:
            return self._rv

    # -- CRUD ---------------------------------------------------------------

    def create(self, obj: Any) -> Any:
        kind = obj.kind
        with self._lock:
            self._ensure_kind(kind)
            store = self._stores[kind]
            key = _obj_key(obj)
            if key in store:
                raise Conflict(f"{kind} {key} already exists")
            obj.metadata.resource_version = self._next_rv()
            store[key] = obj
            self._broadcast(kind, WatchEvent(ADDED, obj, obj.metadata.resource_version))
            return obj

    def create_bulk(self, objs: List[Any]) -> List[Any]:
        """Create many objects of one kind in a single store transaction
        with one bulk watch fan-out -- the ingestion analogue of
        bind_bulk. All-or-nothing per object (a conflict raises after none
        of the later objects are applied), matching N sequential creates
        that stop at the first failure."""
        if not objs:
            return objs
        kind = objs[0].kind
        events: List[WatchEvent] = []
        with self._lock:
            self._ensure_kind(kind)
            store = self._stores[kind]
            for obj in objs:
                if obj.kind != kind:
                    raise ValueError("create_bulk objects must share a kind")
                key = _obj_key(obj)
                if key in store:
                    self._broadcast_many(kind, events)
                    raise Conflict(f"{kind} {key} already exists")
                obj.metadata.resource_version = self._next_rv()
                store[key] = obj
                events.append(
                    WatchEvent(ADDED, obj, obj.metadata.resource_version)
                )
            self._broadcast_many(kind, events)
        return objs

    def get(self, kind: str, namespace: str, name: str) -> Any:
        with self._lock:
            self._ensure_kind(kind)
            obj = self._stores[kind].get((namespace, name))
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            return obj

    def list(self, kind: str) -> Tuple[List[Any], int]:
        """Returns (objects, resourceVersion) -- the list+watch handshake."""
        _api_unavailable_maybe()
        with self._lock:
            self._ensure_kind(kind)
            return list(self._stores[kind].values()), self._rv

    def update(self, obj: Any, expect_rv: Optional[int] = None) -> Any:
        """Replace; optimistic-concurrency check when expect_rv given."""
        kind = obj.kind
        with self._lock:
            self._ensure_kind(kind)
            store = self._stores[kind]
            key = _obj_key(obj)
            current = store.get(key)
            if current is None:
                raise NotFound(f"{kind} {key} not found")
            if expect_rv is not None and current.metadata.resource_version != expect_rv:
                raise Conflict(
                    f"{kind} {key}: resourceVersion {expect_rv} is stale "
                    f"(current {current.metadata.resource_version})"
                )
            # the replacement may be a clone carrying scheduler memos
            # computed against the OLD spec
            _strip_memos(obj)
            obj.metadata.resource_version = self._next_rv()
            store[key] = obj
            self._broadcast(
                kind, WatchEvent(MODIFIED, obj, obj.metadata.resource_version)
            )
            return obj

    def guaranteed_update(
        self, kind: str, namespace: str, name: str, mutate: Callable[[Any], None]
    ) -> Any:
        """Atomic read-modify-write (etcd3 store.go:220 GuaranteedUpdate).

        Copy-on-write: the previously stored object stays intact so informer
        caches can hand handlers a distinct (old, new) pair -- the reference
        gets this for free from serialization; mutators must not mutate
        nested collections in place.
        """
        import copy as _copy

        _api_unavailable_maybe()
        with self._lock:
            old = self.get(kind, namespace, name)
            cow_attrs = tuple(
                a for a in _POD_COW_ATTRS if hasattr(old, a)
            )
            if _cow_clone is not None:
                obj = _cow_clone(old, cow_attrs)
            else:
                obj = _copy.copy(old)
                for attr in cow_attrs:
                    setattr(obj, attr, _copy.copy(getattr(old, attr)))
            _strip_memos(obj)
            mutate(obj)
            obj.metadata.resource_version = self._next_rv()
            self._stores[kind][(namespace, name)] = obj
            self._broadcast(
                kind, WatchEvent(MODIFIED, obj, obj.metadata.resource_version)
            )
            return obj

    def delete(self, kind: str, namespace: str, name: str) -> Any:
        with self._lock:
            self._ensure_kind(kind)
            obj = self._stores[kind].pop((namespace, name), None)
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            rv = self._next_rv()
            self._broadcast(kind, WatchEvent(DELETED, obj, rv))
            return obj

    def delete_bulk(
        self, kind: str, keys: List[Tuple[str, str]]
    ) -> int:
        """Delete many objects of one kind in a single transaction with
        one bulk watch fan-out (the eviction analogue of bind_bulk);
        missing keys are skipped. Returns the number deleted."""
        events: List[WatchEvent] = []
        with self._lock:
            self._ensure_kind(kind)
            store = self._stores[kind]
            for namespace, name in keys:
                obj = store.pop((namespace, name), None)
                if obj is None:
                    continue
                events.append(WatchEvent(DELETED, obj, self._next_rv()))
            self._broadcast_many(kind, events)
        return len(events)

    # -- watch --------------------------------------------------------------

    def watch(self, kind: str, since_rv: int = 0) -> Watch:
        with self._lock:
            self._ensure_kind(kind)
            inj = get_injector()
            if inj is not None and inj.should_fire(
                FaultPoint.WATCH_HISTORY_TRUNCATED
            ):
                raise Gone(
                    f"{kind} watch history truncated (injected 410)"
                )
            if since_rv < self._history_trunc_rv.get(kind, 0):
                # events in (since_rv, trunc_rv] were trimmed: replaying
                # only what's retained would silently skip them
                raise Gone(
                    f"{kind} watch history truncated past rv "
                    f"{self._history_trunc_rv[kind]}; cannot replay from "
                    f"{since_rv}"
                )
            w = Watch(self, kind)
            for ev in self._history[kind]:
                if ev.resource_version > since_rv:
                    w._deliver(ev)
            self._watches[kind].append(w)
            return w

    def _remove_watch(self, w: Watch) -> None:
        with self._lock:
            try:
                self._watches[w.kind].remove(w)
            except ValueError:
                pass

    # -- pods/binding subresource (storage.go:159 BindingREST.Create) -------

    def _bind_locked(self, binding: Binding) -> Tuple[Pod, bool]:
        """Validate + apply one binding; caller holds the store lock.
        Returns (pod, changed) and appends nothing -- the caller decides
        how to fan out the watch event (single vs bulk delivery).
        ``changed`` is False when the pod was ALREADY bound to the same
        node: a retried commit whose first attempt actually landed (or a
        restarted scheduler re-driving a recovered placement) is
        idempotent success, not a conflict -- no write, no event."""
        store = self._stores["Pod"]
        old: Optional[Pod] = store.get(
            (binding.pod_namespace, binding.pod_name)
        )
        if old is None:
            raise NotFound(
                f"Pod {binding.pod_namespace}/{binding.pod_name} not found"
            )
        if binding.pod_uid and old.metadata.uid != binding.pod_uid:
            raise Conflict(
                f"pod {old.key()} uid mismatch: binding has "
                f"{binding.pod_uid}, pod has {old.metadata.uid}"
            )
        if old.spec.node_name:
            if old.spec.node_name == binding.target_node:
                return old, False
            raise Conflict(
                f"pod {old.key()} is already bound to {old.spec.node_name}"
            )
        if not binding.target_node:
            raise ValueError("binding.target_node is required")
        # copy-on-write update (guaranteed_update semantics); the native
        # clone replaces a 4-deep copy.copy chain on the burst's hottest
        # store transaction (10k binds per measured window)
        if _cow_clone is not None:
            pod = _cow_clone(old, _POD_COW_ATTRS)
        else:
            import copy as _copy

            pod = _copy.copy(old)
            pod.metadata = _copy.copy(old.metadata)
            pod.spec = _copy.copy(old.spec)
            pod.status = _copy.copy(old.status)
        pod.spec.node_name = binding.target_node
        pod.__dict__.pop(_SIG_MEMO, None)
        pod.metadata.resource_version = self._next_rv()
        store[(binding.pod_namespace, binding.pod_name)] = pod
        return pod, True

    def bind(self, binding: Binding) -> Pod:
        _api_unavailable_maybe()
        with self._lock:
            pod, changed = self._bind_locked(binding)
            if changed:
                self._broadcast(
                    "Pod",
                    WatchEvent(MODIFIED, pod, pod.metadata.resource_version),
                )
            return pod

    def bind_bulk(
        self, bindings: List[Binding]
    ) -> List[Tuple[Optional[Pod], Optional[Exception]]]:
        """Pipelined bulk commit: all bindings validated and applied under
        ONE store transaction (the batch analogue of per-pod
        BindingREST.Create, storage.go:159). Per-binding failures don't
        abort the rest -- each slot returns (pod, None) or (None, error),
        mirroring N independent API calls minus N-1 lock round trips.
        Watch events for the whole transaction fan out in one bulk
        delivery per watcher."""
        _api_unavailable_maybe()
        out: List[Tuple[Optional[Pod], Optional[Exception]]] = []
        events: List[WatchEvent] = []
        with self._lock:
            for binding in bindings:
                try:
                    pod, changed = self._bind_locked(binding)
                    if changed:
                        events.append(
                            WatchEvent(
                                MODIFIED, pod, pod.metadata.resource_version
                            )
                        )
                    out.append((pod, None))
                except Exception as e:  # noqa: BLE001 - per-slot result
                    out.append((None, e))
            self._broadcast_many("Pod", events)
        return out

    def bind_assumed_bulk(
        self, assumed_pods: List[Pod]
    ) -> List[Tuple[int, Exception]]:
        """Bulk bind commit driven directly by the scheduler's assumed
        clones (metadata carries namespace/name/uid, spec.node_name the
        target) -- the allocation-free fast path of ``bind_bulk``: no
        Binding objects, no per-slot result tuples. Returns only the
        failed slots as (index, error); an empty list means every pod
        bound. The whole transaction runs under one store lock with one
        bulk watch fan-out, through the native C loop when available
        (native/_hotpath.c bind_assumed_bulk)."""
        _api_unavailable_maybe()
        with self._lock:
            if _bind_assumed_bulk is not None:
                errors, events, new_rv = _bind_assumed_bulk(
                    self._stores["Pod"], assumed_pods, self._rv, WatchEvent
                )
                self._rv = new_rv
                self._broadcast_many("Pod", events)
                if not errors:
                    return []
                store = self._stores["Pod"]
                out: List[Tuple[int, Exception]] = []
                for idx, code, msg in errors:
                    exc: Exception
                    if code == 0:
                        exc = NotFound(msg)
                    elif code == 1:
                        # idempotent same-node re-bind (a retried commit
                        # whose first attempt landed, or a restarted
                        # scheduler re-driving a recovered placement):
                        # the C loop reports it as a conflict, but the
                        # store already holds exactly the requested state
                        a = assumed_pods[idx]
                        cur = store.get(
                            (a.metadata.namespace, a.metadata.name)
                        )
                        if (
                            cur is not None
                            and cur.spec.node_name == a.spec.node_name
                            and cur.metadata.uid == a.metadata.uid
                        ):
                            continue
                        exc = Conflict(msg)
                    elif code == 2:
                        exc = ValueError(msg)
                    else:
                        exc = RuntimeError(msg)
                    out.append((idx, exc))
                return out
            # pure-Python fallback: delegate to the shared bind_bulk
            # transaction (one loop to maintain) and convert its per-slot
            # results to the failures-only shape
            results = self.bind_bulk(
                [
                    Binding(
                        pod_namespace=a.metadata.namespace,
                        pod_name=a.metadata.name,
                        pod_uid=a.metadata.uid,
                        target_node=a.spec.node_name,
                    )
                    for a in assumed_pods
                ]
            )
            return [
                (i, err) for i, (_pod, err) in enumerate(results)
                if err is not None
            ]

    # -- pod status subresource ---------------------------------------------

    def update_pod_status(
        self, namespace: str, name: str, mutate: Callable[[Pod], None]
    ) -> Pod:
        def wrap(p: Pod) -> None:
            mutate(p)

        return self.guaranteed_update("Pod", namespace, name, wrap)
