"""The in-process API server.

Semantics modeled on the reference storage layer:

- monotonically increasing resourceVersion per write
  (etcd3/store.go: ModRevision)
- create is txn-if-absent (store.go:144); update uses optimistic
  concurrency on resourceVersion (store.go:220 GuaranteedUpdate)
- watch(since_rv) replays buffered events after rv, then streams live
  (storage/cacher/cacher.go:238 watchCache fan-out)
- the pods/binding subresource sets spec.nodeName under a guaranteed
  update and refuses to re-bind a bound pod
  (pkg/registry/core/pod/storage/storage.go:159-229 assignPod)

Objects returned by get/list and carried in watch events are shared
references: callers must treat them as read-only and deep-copy before
mutating (the same contract client-go informer caches impose).
"""

from __future__ import annotations

import queue as _queue
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import Binding, Node, Pod

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


class NotFound(KeyError):
    pass


class Conflict(ValueError):
    pass


@dataclass
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    object: Any
    resource_version: int


class Watch:
    """One client watch stream; events arrive on an internal queue."""

    def __init__(self, server: "APIServer", kind: str):
        self._server = server
        self.kind = kind
        self._q: "_queue.Queue[Optional[WatchEvent]]" = _queue.Queue()
        self.stopped = False

    def _deliver(self, event: WatchEvent) -> None:
        self._q.put(event)

    def next(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        """Next event, or None on stop/timeout."""
        try:
            ev = self._q.get(timeout=timeout)
        except _queue.Empty:
            return None
        return ev

    def pending(self) -> List[WatchEvent]:
        """Drain without blocking (used by the synchronous pump mode)."""
        out = []
        while True:
            try:
                ev = self._q.get_nowait()
            except _queue.Empty:
                return out
            if ev is not None:
                out.append(ev)

    def stop(self) -> None:
        self.stopped = True
        self._server._remove_watch(self)
        self._q.put(None)


def _obj_key(obj: Any) -> Tuple[str, str]:
    meta = obj.metadata
    return (meta.namespace, meta.name)


class APIServer:
    """Multi-kind object store with watch fan-out."""

    #: pre-registered kinds; any other kind gets a store on first use
    #: (the REST-registry analogue: pkg/registry/ storage per resource)
    KINDS = (
        "Pod", "Node", "PodDisruptionBudget", "PodGroup", "Lease", "Service",
        "PersistentVolume", "PersistentVolumeClaim", "StorageClass",
        "CSINode", "ReplicationController", "ReplicaSet", "StatefulSet",
    )

    def __init__(self, watch_history_limit: int = 200_000) -> None:
        self._lock = threading.RLock()
        self._rv = 0
        self._stores: Dict[str, Dict[Tuple[str, str], Any]] = {
            k: {} for k in self.KINDS
        }
        self._watches: Dict[str, List[Watch]] = {k: [] for k in self.KINDS}
        # bounded per-kind event history for watch(since_rv) replay
        self._history: Dict[str, List[WatchEvent]] = {k: [] for k in self.KINDS}
        self._history_limit = watch_history_limit

    def _ensure_kind(self, kind: str) -> None:
        if kind not in self._stores:
            self._stores[kind] = {}
            self._watches[kind] = []
            self._history[kind] = []

    # -- core ---------------------------------------------------------------

    def _next_rv(self) -> int:
        self._rv += 1
        return self._rv

    def _broadcast(self, kind: str, event: WatchEvent) -> None:
        hist = self._history[kind]
        hist.append(event)
        if len(hist) > self._history_limit:
            del hist[: len(hist) // 2]
        for w in list(self._watches[kind]):
            w._deliver(event)

    def current_rv(self) -> int:
        with self._lock:
            return self._rv

    # -- CRUD ---------------------------------------------------------------

    def create(self, obj: Any) -> Any:
        kind = obj.kind
        with self._lock:
            self._ensure_kind(kind)
            store = self._stores[kind]
            key = _obj_key(obj)
            if key in store:
                raise Conflict(f"{kind} {key} already exists")
            obj.metadata.resource_version = self._next_rv()
            store[key] = obj
            self._broadcast(kind, WatchEvent(ADDED, obj, obj.metadata.resource_version))
            return obj

    def get(self, kind: str, namespace: str, name: str) -> Any:
        with self._lock:
            self._ensure_kind(kind)
            obj = self._stores[kind].get((namespace, name))
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            return obj

    def list(self, kind: str) -> Tuple[List[Any], int]:
        """Returns (objects, resourceVersion) -- the list+watch handshake."""
        with self._lock:
            self._ensure_kind(kind)
            return list(self._stores[kind].values()), self._rv

    def update(self, obj: Any, expect_rv: Optional[int] = None) -> Any:
        """Replace; optimistic-concurrency check when expect_rv given."""
        kind = obj.kind
        with self._lock:
            self._ensure_kind(kind)
            store = self._stores[kind]
            key = _obj_key(obj)
            current = store.get(key)
            if current is None:
                raise NotFound(f"{kind} {key} not found")
            if expect_rv is not None and current.metadata.resource_version != expect_rv:
                raise Conflict(
                    f"{kind} {key}: resourceVersion {expect_rv} is stale "
                    f"(current {current.metadata.resource_version})"
                )
            obj.metadata.resource_version = self._next_rv()
            store[key] = obj
            self._broadcast(
                kind, WatchEvent(MODIFIED, obj, obj.metadata.resource_version)
            )
            return obj

    def guaranteed_update(
        self, kind: str, namespace: str, name: str, mutate: Callable[[Any], None]
    ) -> Any:
        """Atomic read-modify-write (etcd3 store.go:220 GuaranteedUpdate).

        Copy-on-write: the previously stored object stays intact so informer
        caches can hand handlers a distinct (old, new) pair -- the reference
        gets this for free from serialization; mutators must not mutate
        nested collections in place.
        """
        import copy as _copy

        with self._lock:
            old = self.get(kind, namespace, name)
            obj = _copy.copy(old)
            obj.metadata = _copy.copy(old.metadata)
            for attr in ("spec", "status"):
                if hasattr(old, attr):
                    setattr(obj, attr, _copy.copy(getattr(old, attr)))
            mutate(obj)
            obj.metadata.resource_version = self._next_rv()
            self._stores[kind][(namespace, name)] = obj
            self._broadcast(
                kind, WatchEvent(MODIFIED, obj, obj.metadata.resource_version)
            )
            return obj

    def delete(self, kind: str, namespace: str, name: str) -> Any:
        with self._lock:
            self._ensure_kind(kind)
            obj = self._stores[kind].pop((namespace, name), None)
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            rv = self._next_rv()
            self._broadcast(kind, WatchEvent(DELETED, obj, rv))
            return obj

    # -- watch --------------------------------------------------------------

    def watch(self, kind: str, since_rv: int = 0) -> Watch:
        with self._lock:
            self._ensure_kind(kind)
            w = Watch(self, kind)
            for ev in self._history[kind]:
                if ev.resource_version > since_rv:
                    w._deliver(ev)
            self._watches[kind].append(w)
            return w

    def _remove_watch(self, w: Watch) -> None:
        with self._lock:
            try:
                self._watches[w.kind].remove(w)
            except ValueError:
                pass

    # -- pods/binding subresource (storage.go:159 BindingREST.Create) -------

    def bind(self, binding: Binding) -> Pod:
        with self._lock:
            pod: Pod = self.get("Pod", binding.pod_namespace, binding.pod_name)
            if binding.pod_uid and pod.metadata.uid != binding.pod_uid:
                raise Conflict(
                    f"pod {pod.key()} uid mismatch: binding has "
                    f"{binding.pod_uid}, pod has {pod.metadata.uid}"
                )
            if pod.spec.node_name and pod.spec.node_name != binding.target_node:
                raise Conflict(
                    f"pod {pod.key()} is already bound to {pod.spec.node_name}"
                )
            if not binding.target_node:
                raise ValueError("binding.target_node is required")

            def assign(p: Pod) -> None:
                p.spec.node_name = binding.target_node

            return self.guaranteed_update(
                "Pod", binding.pod_namespace, binding.pod_name, assign
            )

    def bind_bulk(
        self, bindings: List[Binding]
    ) -> List[Tuple[Optional[Pod], Optional[Exception]]]:
        """Pipelined bulk commit: all bindings validated and applied under
        ONE store transaction (the batch analogue of per-pod
        BindingREST.Create, storage.go:159). Per-binding failures don't
        abort the rest -- each slot returns (pod, None) or (None, error),
        mirroring N independent API calls minus N-1 lock round trips."""
        out: List[Tuple[Optional[Pod], Optional[Exception]]] = []
        with self._lock:
            for binding in bindings:
                try:
                    out.append((self.bind(binding), None))
                except Exception as e:  # noqa: BLE001 - per-slot result
                    out.append((None, e))
        return out

    # -- pod status subresource ---------------------------------------------

    def update_pod_status(
        self, namespace: str, name: str, mutate: Callable[[Pod], None]
    ) -> Pod:
        def wrap(p: Pod) -> None:
            mutate(p)

        return self.guaranteed_update("Pod", namespace, name, wrap)
