"""Low-overhead phase timing for the bench burst.

Wraps the hot pipeline stages with perf_counter accumulators (no
tracing): pack, upload+dispatch, result download, commit loop, bulk
bind, API create, informer apply. Prints a per-phase table after the
bench line. Overhead is a few ns per call, so the bench number stays
representative (unlike cProfile, which cut throughput ~3x).

Usage: python tools/time_bench.py  (env knobs same as bench.py)
"""

from __future__ import annotations

import os
import sys
import time
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ACC = defaultdict(float)
CNT = defaultdict(int)


def timed(name, fn):
    def wrapper(*a, **kw):
        t0 = time.perf_counter()
        try:
            return fn(*a, **kw)
        finally:
            ACC[name] += time.perf_counter() - t0
            CNT[name] += 1

    return wrapper


def main() -> None:
    import kubernetes_tpu.scheduler.batch as batch_mod
    import kubernetes_tpu.tensors as tensors_mod
    from kubernetes_tpu.scheduler.batch import BatchScheduler
    from kubernetes_tpu.scheduler.scheduler import Scheduler
    from kubernetes_tpu.apiserver.server import APIServer
    from kubernetes_tpu.client.informer import Informer

    # stage wrappers inside the batch module namespace
    batch_mod.pack_pod_batch = timed("pack_pod_batch", batch_mod.pack_pod_batch)
    batch_mod.static_mask_compact = timed(
        "static_mask_compact", batch_mod.static_mask_compact
    )
    batch_mod.pack_score_batch = timed(
        "pack_score_batch", batch_mod.pack_score_batch
    )
    BatchScheduler._dispatch_solve = timed(
        "dispatch_solve_total", BatchScheduler._dispatch_solve
    )
    BatchScheduler._complete_solve = timed(
        "complete_solve_total", BatchScheduler._complete_solve
    )
    BatchScheduler._commit_batch = timed(
        "commit_batch", BatchScheduler._commit_batch
    )
    BatchScheduler._bulk_binding_cycle = timed(
        "bulk_binding_cycle", BatchScheduler._bulk_binding_cycle
    )
    Scheduler.reserve_assume_permit = timed(
        "reserve_assume_permit", Scheduler.reserve_assume_permit
    )
    APIServer.create = timed("apiserver.create", APIServer.create)
    APIServer.bind_bulk = timed("apiserver.bind_bulk", APIServer.bind_bulk)
    Informer._apply_batch = timed("informer._apply_batch", Informer._apply_batch)
    # batch_mod.jax IS the shared jax module: one wrap covers every caller
    batch_mod.jax.device_put = timed("jax.device_put", batch_mod.jax.device_put)
    batch_mod.solve_packed = timed("solve_packed_dispatch", batch_mod.solve_packed)
    import numpy as _np
    _orig_asarray = _np.asarray
    def _asarray(*a, **kw):
        import time as _t
        t0 = _t.perf_counter()
        try:
            return _orig_asarray(*a, **kw)
        finally:
            dt = _t.perf_counter() - t0
            if dt > 0.001:
                ACC["np.asarray(slow)"] += dt
                CNT["np.asarray(slow)"] += 1
    batch_mod.np.asarray = _asarray

    import kubernetes_tpu.queue.scheduling_queue as q_mod

    q_mod.PriorityQueue.pop_batch = timed(
        "queue.pop_batch", q_mod.PriorityQueue.pop_batch
    )

    import bench

    bench.main()

    print("\nphase timings (s, calls):", file=sys.stderr)
    for name in sorted(ACC, key=lambda k: -ACC[k]):
        print(f"  {name:28s} {ACC[name]:8.3f}  x{CNT[name]}", file=sys.stderr)


if __name__ == "__main__":
    main()
