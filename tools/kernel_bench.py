"""Microbench the assignment kernels at bench shapes.

Times greedy_assign_compact / greedy_assign_constrained for
N=5000 nodes x B=2048 pods (the BENCH_r* shape): compile time, then
steady-state solve latency with and without the result download.

Usage: python tools/kernel_bench.py [N] [B]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax

from kubernetes_tpu.ops.assignment import (
    GreedyConfig,
    greedy_assign_compact,
)


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 5000
    b = int(sys.argv[2]) if len(sys.argv) > 2 else 2048
    r = 8
    rng = np.random.default_rng(0)

    allocatable = np.zeros((n, r), dtype=np.int32)
    allocatable[:, 0] = 32000
    allocatable[:, 1] = 64 * 1024 * 1024
    allocatable[:, 2] = 10**9
    allocatable[:, 3] = 110
    requested = np.zeros((n, r), dtype=np.int32)
    nzr = np.zeros((n, 2), dtype=np.int32)
    valid = np.ones(n, dtype=bool)
    pod_req = np.zeros((b, r), dtype=np.int32)
    pod_req[:, 0] = 250
    pod_req[:, 1] = 512 * 1024
    pod_req[:, 3] = 1
    pod_nzr = np.tile(np.array([[250, 512 * 1024]], dtype=np.int32), (b, 1))
    rows = np.ones((8, n), dtype=bool)
    midx = np.zeros(b, dtype=np.int32)
    active = np.ones(b, dtype=bool)

    t0 = time.perf_counter()
    up = jax.device_put(
        (allocatable, requested, nzr, valid, pod_req, pod_nzr, rows, midx,
         active)
    )
    jax.block_until_ready(up)
    t_up = time.perf_counter() - t0
    print(f"device_put ({n}x{r} nodes + {b} pods): {t_up*1000:.1f} ms")

    cfg = GreedyConfig()
    t0 = time.perf_counter()
    out = greedy_assign_compact(*up, config=cfg)
    jax.block_until_ready(out)
    print(f"compile+first solve: {time.perf_counter()-t0*1:.2f} s")

    for trial in range(3):
        t0 = time.perf_counter()
        out = greedy_assign_compact(*up, config=cfg)
        jax.block_until_ready(out)
        t_solve = time.perf_counter() - t0
        t0 = time.perf_counter()
        a = np.asarray(out[0])
        t_dl = time.perf_counter() - t0
        print(
            f"trial {trial}: solve {t_solve*1000:.1f} ms, "
            f"download {t_dl*1000:.1f} ms, placed {(a >= 0).sum()}"
        )

    # dispatch-only latency (what the pipelined path pays on the host)
    t0 = time.perf_counter()
    out = greedy_assign_compact(*up, config=cfg)
    t_dispatch = time.perf_counter() - t0
    jax.block_until_ready(out)
    print(f"dispatch (async) returned in {t_dispatch*1000:.1f} ms")

    # A/B vs the fused Pallas kernel via forced 10-solve chains (the
    # serving link's ~100ms round trip masks single-solve timings)
    from kubernetes_tpu.ops.pallas_solver import pallas_greedy_solve

    def chain(fn, k):
        a = out[0]
        req_s, nzr_s = up[1], up[2]
        for _ in range(k):
            a, req_s, nzr_s = fn(
                up[0], req_s, nzr_s, up[3], up[4], up[5], up[6], up[7],
                up[8], config=cfg,
            )
        return np.asarray(a)

    chain(pallas_greedy_solve, 1)  # compile
    for name, fn in (
        ("xla   ", greedy_assign_compact),
        ("pallas", pallas_greedy_solve),
    ):
        t1 = time.perf_counter()
        chain(fn, 1)
        one = time.perf_counter() - t1
        t1 = time.perf_counter()
        chain(fn, 10)
        ten = time.perf_counter() - t1
        print(
            f"{name}: marginal solve ~{(ten - one) / 9 * 1000:.1f} ms "
            f"(chain1 {one*1000:.0f} ms, chain10 {ten*1000:.0f} ms)"
        )


if __name__ == "__main__":
    main()
