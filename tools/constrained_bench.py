"""A/B the CONSTRAINED solvers at big cluster shapes.

Packs a realistic constrained batch with the real family packers
(spread-only by default -- the BigClusterSpread shape; --mixed adds
required/preferred pod affinity) and times the XLA constrained scan vs
the family-specialized Pallas kernel, printing the chosen Caps and the
VMEM estimate. This is the proof that the specialization breaks the old
~5.6k-node all-family VMEM ceiling on real hardware.

Usage: python tools/constrained_bench.py [N] [B] [--mixed]
"""

from __future__ import annotations

import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax

from kubernetes_tpu.cache.snapshot import new_snapshot
from kubernetes_tpu.ops.affinity import (
    noop_affinity_tensors,
    pack_affinity_batch,
    pad_affinity_tensors,
)
from kubernetes_tpu.ops.assignment import (
    GreedyConfig,
    greedy_assign_constrained,
)
from kubernetes_tpu.ops.host_masks import static_mask_compact
from kubernetes_tpu.ops.pallas_constrained import (
    Caps,
    VMEM_BUDGET,
    constrained_vmem_bytes,
    pallas_constrained_solve,
)
from kubernetes_tpu.ops.scoring import (
    noop_score_tensors,
    pack_score_batch,
    pad_score_tensors,
)
from kubernetes_tpu.ops.topology import (
    noop_spread_tensors,
    pack_spread_batch,
    pad_spread_tensors,
)
from kubernetes_tpu.tensors import NodeTensorCache, pack_pod_batch
from kubernetes_tpu.testing import make_node, make_pod

POD_BUCKET = 64
MASK_ROW_BUCKET = 8

DEFAULT_WEIGHTS = {
    "NodeAffinity": 1,
    "TaintToleration": 1,
    "DefaultPodTopologySpread": 1,
    "PodTopologySpread": 2,
    "InterPodAffinity": 1,
}


def build(n_nodes: int, b: int, mixed: bool):
    nodes = []
    for i in range(n_nodes):
        nodes.append(
            make_node(f"node-{i}")
            .capacity(cpu="32", memory="64Gi", pods=110)
            .label("topology.kubernetes.io/zone", f"zone-{i % 16}")
            .label("kubernetes.io/hostname", f"node-{i}")
            .obj()
        )
    existing = [
        make_pod(f"ex-{i}")
        .node(f"node-{i % n_nodes}")
        .container(cpu="100m", memory="128Mi")
        .labels(app="spread")
        .obj()
        for i in range(min(1000, n_nodes))
    ]
    pods = []
    for i in range(b):
        p = (
            make_pod(f"pod-{i}")
            .container(cpu="100m", memory="128Mi")
            .labels(app="spread")
            .spread_constraint(
                max_skew=250,
                topology_key="topology.kubernetes.io/zone",
                when_unsatisfiable="DoNotSchedule",
                match_labels={"app": "spread"},
            )
        )
        if mixed and i % 3 == 0:
            p = p.pod_affinity(
                "topology.kubernetes.io/zone", {"app": "spread"}
            )
        if mixed and i % 5 == 0:
            p = p.preferred_pod_affinity(
                "topology.kubernetes.io/zone", {"app": "spread"}, weight=5
            )
        pods.append(p.obj())

    snap = new_snapshot(existing, nodes)
    nt = NodeTensorCache().update(snap)
    batch = pack_pod_batch(pods, nt.dims)
    mask_rows, mask_index = static_mask_compact(pods, snap, nt)
    padded = POD_BUCKET * math.ceil(batch.size / POD_BUCKET)
    order = batch.order
    req = np.zeros((padded, nt.dims.num_dims), dtype=np.int32)
    nzr = np.zeros((padded, 2), dtype=np.int32)
    midx = np.zeros(padded, dtype=np.int32)
    active = np.zeros(padded, dtype=bool)
    req[:batch.size] = batch.requests[order]
    nzr[:batch.size] = batch.non_zero_requests[order]
    midx[:batch.size] = mask_index[order]
    active[:batch.size] = True
    u = mask_rows.shape[0]
    u_padded = MASK_ROW_BUCKET * math.ceil(u / MASK_ROW_BUCKET)
    rows = np.zeros((u_padded, nt.capacity), dtype=bool)
    rows[:u] = mask_rows

    ordered = [pods[int(i)] for i in order]
    sp = pack_spread_batch(ordered, snap, nt)
    af = pack_affinity_batch(ordered, snap, nt)
    sc = pack_score_batch(
        ordered, snap, nt, None, DEFAULT_WEIGHTS,
        hard_pod_affinity_weight=1, cluster_affinity_scoring=None,
    )
    sp_t = (
        pad_spread_tensors(sp, padded)
        if sp is not None else noop_spread_tensors(padded, nt.capacity)
    )
    af_t = (
        pad_affinity_tensors(af, padded)
        if af is not None else noop_affinity_tensors(padded, nt.capacity)
    )
    sc_t = (
        pad_score_tensors(sc, padded)
        if sc is not None else noop_score_tensors(padded, nt.capacity)
    )
    common = (
        nt.allocatable, nt.requested, nt.non_zero_requested, nt.valid,
        req, nzr, rows, midx, active,
    )
    present = (sp is not None, af is not None, sc is not None)
    return common, tuple(sp_t), tuple(af_t), tuple(sc_t), present


def derive_caps(sp_t, af_t, sc_t, sp_p, af_p, sc_p):
    from kubernetes_tpu.ops.assignment import caps_for_families

    return caps_for_families(sp_t, af_t, sc_t, sp_p, af_p, sc_p)


def main() -> None:
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    mixed = "--mixed" in sys.argv
    n = int(args[0]) if args else 20000
    b = int(args[1]) if len(args) > 1 else 1024
    t0 = time.perf_counter()
    common, sp_t, af_t, sc_t, present = build(n, b, mixed)
    print(f"pack: {time.perf_counter()-t0:.1f}s")
    caps = derive_caps(sp_t, af_t, sc_t, *present)
    n_cap = common[0].shape[0]
    est = constrained_vmem_bytes(
        n_cap, common[0].shape[1], common[6].shape[0],
        sc_t[0].shape[0], sc_t[5].shape[1], sp_t[0].shape[1], caps,
        chunk=min(common[4].shape[0], 1024),
    )
    print(
        f"caps={caps} vmem_est={est/2**20:.1f}MiB "
        f"budget={VMEM_BUDGET/2**20:.1f}MiB fits={est <= VMEM_BUDGET}"
    )

    up = jax.device_put(common)
    sp_d = jax.device_put(sp_t)
    af_d = jax.device_put(af_t)
    sc_d = jax.device_put(sc_t)
    jax.block_until_ready(up)
    cfg = GreedyConfig()

    def run(fn, tag, chain=4, **kw):
        t0 = time.perf_counter()
        out = fn(*up, sp_d, af_d, sc_d, config=cfg, **kw)
        jax.block_until_ready(out)
        print(f"{tag}: compile+first {time.perf_counter()-t0:.1f}s")

        def chained(k):
            """k dependent solves (carry req/nzr) + result download --
            the steady-state dispatch pattern; defeats async-dispatch
            timing artifacts on the tunneled chip."""
            req_s, nzr_s = up[1], up[2]
            o = None
            for _ in range(k):
                o = fn(
                    up[0], req_s, nzr_s, *up[3:], sp_d, af_d, sc_d,
                    config=cfg, **kw,
                )
                req_s, nzr_s = o[1], o[2]
            return np.asarray(o[0])

        chained(1)
        t1 = time.perf_counter()
        a1 = chained(1)
        one = time.perf_counter() - t1
        t1 = time.perf_counter()
        chained(1 + chain)
        more = time.perf_counter() - t1
        per = (more - one) / chain
        print(
            f"{tag}: marginal solve {per*1000:.1f} ms "
            f"({b/per:.0f} pods/s), placed {(a1 >= 0).sum()}"
        )
        return a1

    a_pl = run(pallas_constrained_solve, "pallas", caps=caps)
    a_xla = run(greedy_assign_constrained, "xla   ")
    same = (a_pl == a_xla).all()
    print(f"assignments identical: {same}")
    if not same:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
