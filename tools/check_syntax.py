#!/usr/bin/env python
"""Tier-0 syntax gate: ast-parse every ``*.py`` under the trees that
pytest collects, so a module that cannot even compile on THIS runtime
fails fast with its file name instead of cascading into dozens of opaque
pytest collection errors (the seed shipped a 3.12-only f-string in
utils/metrics.py that produced 21 collection errors on the 3.10
runtime).

Run standalone::

    python tools/check_syntax.py            # checks default trees
    python tools/check_syntax.py pkg tests  # or explicit roots

It is also invoked automatically by ``tests/conftest.py`` at pytest
startup (tier-0, before any collection), so the tier-1 command gets the
gate for free.

Exit status: 0 when every file parses, 1 otherwise (one line per broken
file on stderr).
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Iterable, List, Tuple

#: every tree a runtime imports from: the packages pytest collects,
#: plus the perf-matrix runner package and the top-level entry scripts
#: (bench.py / the driver's __graft_entry__) -- a syntax error there
#: fails CI loudly instead of surfacing mid-benchmark
DEFAULT_ROOTS = (
    "kubernetes_tpu", "tests", "tools", "benchmarks",
    "bench.py", "__graft_entry__.py",
)


def iter_python_files(roots: Iterable[str]) -> Iterable[str]:
    for root in roots:
        if os.path.isfile(root) and root.endswith(".py"):
            yield root
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [
                d for d in dirnames
                if d not in ("__pycache__", ".git")
            ]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def check_file(path: str) -> Tuple[str, str] | None:
    """Returns (path, error) on failure, None when the file parses."""
    try:
        with open(path, "rb") as f:
            src = f.read()
        ast.parse(src, filename=path)
    except SyntaxError as e:
        return (path, f"line {e.lineno}: {e.msg}")
    except Exception as e:  # noqa: BLE001 - unreadable file is a failure too
        return (path, str(e))
    return None


def check_tree(
    roots: Iterable[str] = DEFAULT_ROOTS, base_dir: str | None = None
) -> List[Tuple[str, str]]:
    """ast-parse every file; returns [(path, error)] for broken ones."""
    if base_dir:
        roots = [os.path.join(base_dir, r) for r in roots]
    roots = [r for r in roots if os.path.exists(r)]
    failures: List[Tuple[str, str]] = []
    for path in iter_python_files(roots):
        bad = check_file(path)
        if bad is not None:
            failures.append(bad)
    return failures


def probe_native_extension(base_dir: str | None = None) -> List[Tuple[str, str]]:
    """Tier-0 probe for the native ingest/commit extension
    (kubernetes_tpu/native): importing the package must either yield a
    WORKING extension (compile-on-import succeeded) or degrade to the
    pure-Python twins CLEANLY -- ``hotpath is None``, every exported
    fast-path symbol None, ``ingest_native_active()`` False, so the
    fallback metric (scheduler_ingest_native_fallbacks_total) can count
    what ran. A crash on import (or a half-exported module) is the
    failure mode this gate exists to catch: it would take the whole
    control plane down with it instead of degrading.

    Returns [(what, error)] like ``check_tree`` -- empty means either
    outcome is healthy."""
    if base_dir:
        sys.path.insert(0, base_dir)
    failures: List[Tuple[str, str]] = []
    try:
        from kubernetes_tpu import native
    except Exception as e:  # noqa: BLE001 - the forbidden outcome
        return [("kubernetes_tpu.native", f"import crashed: {e}")]
    exported = (
        "cow_clone", "assume_clones", "bind_assumed_bulk", "commit_gather",
    )
    if native.hotpath is None:
        # clean-fallback leg: every fast-path symbol must be None and
        # the ingest plane must report itself inactive
        for name in exported:
            if getattr(native, name, None) is not None:
                failures.append((
                    f"kubernetes_tpu.native.{name}",
                    "non-None fast-path symbol after a failed build",
                ))
        if native.ingest_native_active():
            failures.append((
                "kubernetes_tpu.native.ingest_native_active",
                "reports active with no extension built",
            ))
    else:
        # built leg: the ingest spine must be fully exported (a stale
        # .so missing entry points would half-run the plane)
        for name in exported + (
            "ingest_decode", "ingest_apply", "ingest_stamp",
            "pack_gather", "queue_shape", "mirror_scatter",
        ):
            if getattr(native.hotpath, name, None) is None:
                failures.append((
                    f"kubernetes_tpu.native._hotpath.{name}",
                    "missing from the built extension (stale .so?)",
                ))
    return failures


def main(argv: List[str]) -> int:
    roots = argv or list(DEFAULT_ROOTS)
    failures = check_tree(roots)
    failures += probe_native_extension(
        base_dir=os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    if failures:
        for path, err in failures:
            print(f"SYNTAX ERROR: {path}: {err}", file=sys.stderr)
        print(
            f"check_syntax: {len(failures)} file(s) failed to parse on "
            f"Python {sys.version.split()[0]}",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
