#!/usr/bin/env python
"""Tier-0 syntax gate: ast-parse every ``*.py`` under the trees that
pytest collects, so a module that cannot even compile on THIS runtime
fails fast with its file name instead of cascading into dozens of opaque
pytest collection errors (the seed shipped a 3.12-only f-string in
utils/metrics.py that produced 21 collection errors on the 3.10
runtime).

Run standalone::

    python tools/check_syntax.py            # checks default trees
    python tools/check_syntax.py pkg tests  # or explicit roots

It is also invoked automatically by ``tests/conftest.py`` at pytest
startup (tier-0, before any collection), so the tier-1 command gets the
gate for free.

Exit status: 0 when every file parses, 1 otherwise (one line per broken
file on stderr).
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Iterable, List, Tuple

#: every tree a runtime imports from: the packages pytest collects,
#: plus the perf-matrix runner package and the top-level entry scripts
#: (bench.py / the driver's __graft_entry__) -- a syntax error there
#: fails CI loudly instead of surfacing mid-benchmark
DEFAULT_ROOTS = (
    "kubernetes_tpu", "tests", "tools", "benchmarks",
    "bench.py", "__graft_entry__.py",
)


def iter_python_files(roots: Iterable[str]) -> Iterable[str]:
    for root in roots:
        if os.path.isfile(root) and root.endswith(".py"):
            yield root
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [
                d for d in dirnames
                if d not in ("__pycache__", ".git")
            ]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def check_file(path: str) -> Tuple[str, str] | None:
    """Returns (path, error) on failure, None when the file parses."""
    try:
        with open(path, "rb") as f:
            src = f.read()
        ast.parse(src, filename=path)
    except SyntaxError as e:
        return (path, f"line {e.lineno}: {e.msg}")
    except Exception as e:  # noqa: BLE001 - unreadable file is a failure too
        return (path, str(e))
    return None


def check_tree(
    roots: Iterable[str] = DEFAULT_ROOTS, base_dir: str | None = None
) -> List[Tuple[str, str]]:
    """ast-parse every file; returns [(path, error)] for broken ones."""
    if base_dir:
        roots = [os.path.join(base_dir, r) for r in roots]
    roots = [r for r in roots if os.path.exists(r)]
    failures: List[Tuple[str, str]] = []
    for path in iter_python_files(roots):
        bad = check_file(path)
        if bad is not None:
            failures.append(bad)
    return failures


def main(argv: List[str]) -> int:
    roots = argv or list(DEFAULT_ROOTS)
    failures = check_tree(roots)
    if failures:
        for path, err in failures:
            print(f"SYNTAX ERROR: {path}: {err}", file=sys.stderr)
        print(
            f"check_syntax: {len(failures)} file(s) failed to parse on "
            f"Python {sys.version.split()[0]}",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
