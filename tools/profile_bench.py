"""Profile the scheduler thread during the bench burst.

cProfile is attached to the BatchScheduler.run thread (the solver +
commit hot path) and, separately, to the bind-pool workers. Emits
profile_scheduler.txt (cumulative + tottime views) next to this file.

Usage: python tools/profile_bench.py  (env knobs same as bench.py)
"""

from __future__ import annotations

import cProfile
import io
import os
import pstats
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubernetes_tpu.scheduler.batch import BatchScheduler


def main() -> None:
    out_dir = os.path.dirname(os.path.abspath(__file__))
    prof = cProfile.Profile()
    orig_run = BatchScheduler.run

    def run_profiled(self):
        prof.enable()
        try:
            orig_run(self)
        finally:
            prof.disable()

    BatchScheduler.run = run_profiled

    import bench

    bench.main()

    prof.dump_stats(os.path.join(out_dir, "profile_scheduler.prof"))
    buf = io.StringIO()
    st = pstats.Stats(prof, stream=buf)
    buf.write("==== cumulative ====\n")
    st.sort_stats("cumulative").print_stats(45)
    buf.write("\n==== tottime ====\n")
    st.sort_stats("tottime").print_stats(45)
    with open(os.path.join(out_dir, "profile_scheduler.txt"), "w") as f:
        f.write(buf.getvalue())
    print("profile written to tools/profile_scheduler.txt", file=sys.stderr)


if __name__ == "__main__":
    main()
